"""Low-precision compute primitives (``--quant_compute {off,int8,fp8}``):
per-channel scaled int8/fp8 matmuls for the scanned transformer stack and
the ring collective matmuls.

r9 proved quantized *communication* pays (int8 wire at 0.254x fp32 with
error feedback recovering the trajectory); this module is the *compute*
half of the same economics: the dots themselves run on narrow operands,
so the MXU int8/fp8 paths (2x the bf16 peak on every TPU generation that
has them — ``obs/attribution.py``'s per-dtype tables) and HBM bandwidth
both get the 2-4x, and — composed with the decomposed TP rings
(``parallel/collective_matmul.py``) — the ppermutes carry the narrow
tensor + its scales, so wire and FLOPs shrink together (Wang et al.,
ASPLOS'23 decomposition applied to a quantized operand).

Numerics follow established low-precision-training practice (Micikevicius
et al., *FP8 Formats for Deep Learning*): **master weights stay fp32** in
``TrainState`` and the optimizer updates them directly — quantization is
re-derived from the masters every step, so rounding error never
accumulates across steps (the reason deterministic round-to-nearest is
safe here where the r9 gradient wire needed stochastic rounding + error
feedback: a wire error compounds into the trajectory, a compute error is
re-sampled from the fp32 truth each step). Scaling is symmetric per
*channel* of the contraction:

- activations: one scale per row over the contraction axis
  (``absmax/QMAX``), so the scale factors out of the dot exactly;
- weights: one scale per output channel (absmax over the contraction
  dims), factoring out on the other side — the scaled dot
  ``(a_q s_a) @ (w_q s_w)`` is algebraically exact given the quantized
  operands; the only error is the rounding of the operands themselves.

int8 accumulates in int32 (``preferred_element_type``), fp8 (e4m3 values,
e5m2 cotangents — the standard fwd/bwd split) in f32. The fp8 dtypes are
this jaxlib's native ``float8_e4m3fn``/``float8_e5m2``; backends without
a narrow MXU (this CPU host) upcast the operands in XLA — the program
still *carries* narrow-dtype dots (the ``--hlo_report`` quant tripwire's
witness) and the wire/HBM savings are real, only the FLOPs win needs the
real MXU.

:func:`quant_dense` is the drop-in replacement for the block matmuls
(``models/transformer.py`` routes fc1/fc2/qkv/out through it under
``--quant_compute``, with ``_DenseParams`` twins keeping the param tree
bit-interchangeable with the default path): a ``jax.custom_vjp`` whose
backward also runs narrow — dx and dw quantize their operands over the
respective contraction axes (both factorize per-channel), with fp8
cotangents in e5m2.

:func:`quant_matmul_pallas` is the fused dequant→dot→requant kernel
(``ops/flash.py`` is the in-tree exemplar): narrow operands stream from
HBM, the accumulator lives in VMEM scratch, and the per-channel scales
apply once at the final K tile — the dequantized f32 tensor never exists
in HBM, so the path wins memory bandwidth as well as FLOPs. Following
the FLASH_BWD convention, the XLA lowering is the default everywhere
(``QUANT_IMPL=pallas`` opts in; interpret mode keeps the kernel
continuously validated on CPU CI) until the real-Mosaic parity record
lands via ``tools/tpu_followup.sh legs_r17``.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

#: the --quant_compute surface; "off" must leave the default path
#: bit-untouched (pinned by test and the BENCH_MODE=quant parity leg)
QUANT_COMPUTE_MODES = ("off", "int8", "fp8")

#: fp8 value/weight dtype (3 mantissa bits, the fwd format) and cotangent
#: dtype (2 mantissa bits, 5 exponent bits — gradients need range more
#: than precision; the standard fwd/bwd split)
FP8_FWD_DTYPE = jnp.float8_e4m3fn
FP8_BWD_DTYPE = jnp.float8_e5m2

#: largest finite value of each narrow format (the symmetric-scale
#: denominator): int8 uses 127, e4m3fn saturates at 448, e5m2 at 57344
QMAX = {"int8": 127.0, "fp8": 448.0, "fp8_grad": 57344.0}


def _norm_axes(axes, ndim: int) -> tuple[int, ...]:
    if isinstance(axes, int):
        axes = (axes,)
    return tuple(a % ndim for a in axes)


def quantize_channel(x: jax.Array, mode: str, axes=-1, *,
                     grad: bool = False,
                     key: jax.Array | None = None
                     ) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-channel quantization of ``x`` over ``axes``.

    ``axes`` are the contraction axes the scale is shared over (absmax
    reduced there, keepdims) — one scale per remaining "channel", which
    is exactly the granularity that factors out of a dot contracting
    those axes. Returns ``(q, scale)`` with ``scale`` f32 and all-zero
    channels pinned to scale 1.0 (dequant stays exact zeros).

    ``mode``: ``int8`` (stochastic rounding when ``key`` is given —
    the ``parallel/compress.py`` recipe — else round-to-nearest) or
    ``fp8`` (hardware round-to-nearest-even via the dtype convert;
    ``grad=True`` selects e5m2 for cotangents).
    """
    if mode not in ("int8", "fp8"):
        raise ValueError(
            f"quantize_channel: unknown mode {mode!r}; expected int8 | fp8 "
            f"(the 'off' mode never reaches the quantizers)")
    axes = _norm_axes(axes, x.ndim)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    if mode == "int8":
        scale = jnp.where(amax > 0, amax / QMAX["int8"], 1.0)
        y = xf / scale
        if key is not None:
            u = jax.random.uniform(key, y.shape, jnp.float32)
            y = jnp.floor(y + u)
        else:
            y = jnp.round(y)
        q = jnp.clip(y, -127.0, 127.0).astype(jnp.int8)
    else:
        qmax = QMAX["fp8_grad" if grad else "fp8"]
        dt = FP8_BWD_DTYPE if grad else FP8_FWD_DTYPE
        scale = jnp.where(amax > 0, amax / qmax, 1.0)
        q = (xf / scale).astype(dt)
    return q, scale.astype(jnp.float32)


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_channel` (broadcasting scale)."""
    return q.astype(jnp.float32) * scale


def roundtrip_rel_error_bound(mode: str, *, grad: bool = False) -> float:
    """Documented per-channel relative error bound of one
    quantize→dequantize round trip, relative to the channel's absmax:
    half a quantum for round-to-nearest int8 (1/254), one e4m3/e5m2 ulp
    at the top of a binade for fp8 (2^-3 / 2^-2 relative spacing — the
    absolute error is bounded by ulp(absmax)). Pinned by unit test and
    the BENCH_MODE=quant roundtrip leg.
    """
    if mode == "int8":
        return 0.5 / QMAX["int8"]
    return 2.0 ** (-2 if grad else -3)


def quant_dot(aq: jax.Array, a_scale: jax.Array, wq: jax.Array,
              w_scale: jax.Array, *, out_dtype=jnp.float32) -> jax.Array:
    """Scaled narrow dot ``(..., K) @ (K, N) -> (..., N)``.

    ``aq`` quantized per row over its last axis (``a_scale``
    ``(..., 1)``); ``wq`` per output channel (``w_scale`` ``(1, N)``).
    int8 operands accumulate in int32 on the MXU int8 path; fp8 in f32.
    The scales apply ONCE to the accumulator — the fused dequant.
    """
    pet = jnp.int32 if aq.dtype == jnp.int8 else jnp.float32
    acc = lax.dot_general(aq, wq, (((aq.ndim - 1,), (0,)), ((), ())),
                          preferred_element_type=pet)
    return (acc.astype(jnp.float32) * a_scale * w_scale).astype(out_dtype)


# -- Pallas fused kernel ---------------------------------------------------

def _quant_matmul_kernel(aq_ref, wq_ref, as_ref, ws_ref, o_ref, acc_ref, *,
                         k_blocks: int, is_int8: bool):
    k = pl.program_id(2)  # K tile (sequential)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    a = aq_ref[...]
    w = wq_ref[...]
    if is_int8:
        # int32 accumulation: the MXU int8 path's native accumulator
        acc_ref[...] += lax.dot_general(
            a.astype(jnp.int32), w.astype(jnp.int32),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    else:
        acc_ref[...] += lax.dot_general(
            a.astype(jnp.float32), w.astype(jnp.float32),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k == k_blocks - 1)
    def _finalize():
        # fused dequant: scales hit the accumulator exactly once, and the
        # f32 tensor never round-trips through HBM
        out = acc_ref[...].astype(jnp.float32)
        out = out * as_ref[...] * ws_ref[...]
        o_ref[...] = out.astype(o_ref.dtype)


try:  # pallas availability mirrors ops/flash.py
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS = True
except Exception:  # noqa: BLE001 - environments without pallas
    _PALLAS = False


def quant_matmul_pallas(aq: jax.Array, a_scale: jax.Array, wq: jax.Array,
                        w_scale: jax.Array, *, out_dtype=jnp.float32,
                        block_m: int = 128, block_n: int = 128,
                        block_k: int = 128,
                        interpret: bool | None = None) -> jax.Array:
    """Fused dequant→dot→requant tiled matmul: ``(M, K) @ (K, N)``.

    Narrow operands stream tile-by-tile; the accumulator (int32 for
    int8, f32 for fp8) lives in VMEM scratch across the sequential K
    tiles; the per-channel scales apply once at the last tile and the
    output stores in ``out_dtype`` — HBM only ever sees narrow inputs
    and the final (bf16/f32) tiles. ``interpret`` defaults to
    off-TPU detection like ``ops.flash.flash_attention``.
    """
    if not _PALLAS:
        raise RuntimeError("pallas unavailable on this jax build; use the "
                           "XLA lowering (quant_dot)")
    m, k = aq.shape
    k2, n = wq.shape
    if k != k2:
        raise ValueError(f"quant_matmul_pallas: contraction mismatch "
                         f"{aq.shape} @ {wq.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bm, bn, bk = (math.gcd(m, block_m), math.gcd(n, block_n),
                  math.gcd(k, block_k))
    if not interpret and min(bm, bn, bk) < 8:
        raise ValueError(
            f"quant_matmul_pallas: dims ({m},{k},{n}) with blocks "
            f"({block_m},{block_n},{block_k}) fit only a "
            f"{min(bm, bn, bk)}-wide tile; pad to MXU-friendly multiples "
            "or use the XLA lowering")
    grid = (m // bm, n // bn, k // bk)
    is_int8 = aq.dtype == jnp.int8
    acc_dtype = jnp.int32 if is_int8 else jnp.float32
    kernel = functools.partial(_quant_matmul_kernel, k_blocks=grid[2],
                               is_int8=is_int8)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bm, 1), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(aq, wq, a_scale, w_scale)


if _PALLAS:
    CompilerParams = (getattr(pltpu, "CompilerParams", None)
                      or pltpu.TPUCompilerParams)


_impl_logged: set[str] = set()


def quant_impl() -> str:
    """Active lowering for the quantized dense dots, read at TRACE time
    (the FLASH_BWD convention): ``QUANT_IMPL=pallas`` opts into the
    fused kernel (interpret mode off-TPU — how CPU CI validates it);
    default ``xla`` everywhere until the real-Mosaic parity record lands
    (tools/tpu_followup.sh legs_r17). A typo'd override fails loudly."""
    impl = os.environ.get("QUANT_IMPL", "xla")
    if impl not in ("xla", "pallas"):
        raise ValueError(f"QUANT_IMPL={impl!r}: expected 'xla' or 'pallas'")
    if impl not in _impl_logged:
        _impl_logged.add(impl)
        from ..utils import get_logger

        get_logger(__name__).info(
            "quantized-dense lowering selected (trace-time; set QUANT_IMPL "
            "before first use or jax.clear_caches() to change)",
            {"impl": impl},
        )
    return impl


# -- the differentiable dense op -------------------------------------------

def _flat2(x: jax.Array, n_axes: int) -> tuple[jax.Array, tuple[int, ...]]:
    """Collapse to 2D: leading batch dims x flattened contraction dims."""
    batch_shape = x.shape[: x.ndim - n_axes]
    return x.reshape(math.prod(batch_shape) if batch_shape else 1, -1), \
        batch_shape


def _qdense_fwd_math(x2, w2, mode, out_dtype, impl):
    xq, xs = quantize_channel(x2, mode, axes=-1)
    wq, ws = quantize_channel(w2, mode, axes=0)
    ws = ws.reshape(1, -1)
    if impl == "pallas":
        return quant_matmul_pallas(xq, xs, wq, ws, out_dtype=out_dtype)
    return quant_dot(xq, xs, wq, ws, out_dtype=out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _qdense2(x2, w2, mode, out_dtype, impl):
    return _qdense_fwd_math(x2, w2, mode, out_dtype, impl)


def _qdense2_fwd(x2, w2, mode, out_dtype, impl):
    return _qdense_fwd_math(x2, w2, mode, out_dtype, impl), (x2, w2)


def _qdense2_bwd(mode, out_dtype, impl, res, gy):
    """Narrow backward: dx = gy @ w^T with gy quantized per row over N
    (e5m2 under fp8) and w per input-channel over N; dw = x^T @ gy with
    both quantized per channel over the batch axis M — every contraction
    carries per-channel scales on exactly the contracted axis, so the
    scaled dots are algebraically exact given the quantized operands."""
    x2, w2 = res
    gy = gy.astype(jnp.float32)
    # dx: contract N — gy rows scaled over N, w^T columns (= w input
    # channels) scaled over N
    gq, gs = quantize_channel(gy, mode, axes=-1, grad=True)
    wTq, wTs = quantize_channel(w2.T, mode, axes=0)   # (N, K), scale (1, K)
    dx = quant_dot(gq, gs, wTq, wTs, out_dtype=jnp.float32)
    # dw: contract M
    xq2, xs2 = quantize_channel(x2, mode, axes=0)     # (M, K), scale (1, K)
    gq2, gs2 = quantize_channel(gy, mode, axes=0, grad=True)  # scale (1, N)
    pet = jnp.int32 if xq2.dtype == jnp.int8 else jnp.float32
    dw = lax.dot_general(xq2, gq2, (((0,), (0,)), ((), ())),
                         preferred_element_type=pet).astype(jnp.float32)
    dw = dw * xs2.reshape(-1, 1) * gs2.reshape(1, -1)
    return dx.astype(x2.dtype), dw.astype(w2.dtype)


_qdense2.defvjp(_qdense2_fwd, _qdense2_bwd)


def quant_dense(x: jax.Array, kernel: jax.Array, bias: jax.Array,
                n_axes: int, mode: str, dtype=jnp.float32) -> jax.Array:
    """Low-precision twin of ``models/transformer._plain_dense``:
    DenseGeneral's contraction run as a per-channel-scaled narrow dot
    (forward AND backward), bias added in ``dtype``. ``kernel``/``bias``
    are the fp32 masters from the ``_DenseParams`` twins — quantization
    is re-derived from them at every call, so no rounding error ever
    accumulates into the stored weights."""
    if mode not in ("int8", "fp8"):
        raise ValueError(f"quant_dense: unknown mode {mode!r}")
    x2, batch_shape = _flat2(x, n_axes)
    w2 = kernel.reshape(x2.shape[-1], -1)
    y2 = _qdense2(x2, w2.astype(jnp.float32), mode, jnp.float32,
                  quant_impl())
    feat_shape = kernel.shape[n_axes:]
    y = y2.reshape(*batch_shape, *feat_shape)
    return (y + bias.astype(jnp.float32)).astype(dtype)


# -- accounting ------------------------------------------------------------

def quant_itemsize(mode: str) -> float:
    """Wire/HBM bytes per element of a quantized payload (both int8 and
    the fp8 formats are one byte; 'off' is the fp32 4)."""
    return 4.0 if mode == "off" else 1.0


def quant_scale_overhead(channel: int) -> float:
    """Extra f32-scale bytes per payload element for per-channel scaling
    with ``channel`` elements sharing one scale (4/channel)."""
    return 4.0 / max(int(channel), 1)
