"""Attention ops: the hot kernel of the transformer rungs (BERT, ViT).

The reference has no attention anywhere (its model is a 2-layer MLP,
``/root/reference/model.py:8-16``) — but the BASELINE.md config ladder
(BERT-base MLM, ViT-B/16) makes attention the dominant op of two of the
four target configs, so it gets a first-class TPU-native op library:

- ``dot_product_attention``: plain XLA einsum formulation. For moderate
  sequence lengths XLA already fuses this well onto the MXU; softmax runs
  in f32 regardless of compute dtype.
- ``blockwise_attention``: memory-efficient online-softmax formulation
  (Rabe & Staats / FlashAttention recurrence) expressed with ``lax.scan``
  over key/value blocks — O(block) memory instead of O(seq^2), fully
  differentiable (XLA differentiates the scan), and the exact building
  block ring attention shards over the ``seq`` mesh axis
  (``parallel/ring.py``).
- ``flash_attention``: Pallas TPU kernel (``ops/flash.py``) — fused
  tiled kernel keeping the running softmax state in VMEM.

``attention(..., impl="auto")`` picks per backend: Pallas on TPU, XLA
elsewhere.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
from jax import lax

Impl = Literal["auto", "xla", "blockwise", "flash"]

NEG_INF = -1e30  # additive mask value; finite so 0*inf NaNs can't appear


# Measured on TPU v5e (bench_records/flash_tpu_r4.jsonl): flash vs XLA is
# 1.07x full / 1.22x causal at seq 1024, 1.13x/1.09x at 2048, and
# 1.34x/3.24x at 4096 — the win grows with seq, and at 1024 the full
# (non-causal) case is already near parity. Below 1024 there is no
# hardware record at all (flash@512 is queued in
# tools/tpu_followup.sh 4), so ``auto`` keeps the XLA path there until
# a committed record says otherwise.
FLASH_MIN_SEQ = 1024


def _pick_impl(impl: Impl, q: jax.Array, k: jax.Array) -> str:
    if impl != "auto":
        return impl
    import os

    if os.environ.get("FLASH_DISABLE", "") == "1":
        # global escape hatch (read at trace time): forces the XLA path
        # for auto-dispatched call sites — the ablation baseline knob and
        # the operational kill switch should a Mosaic regression land
        return "xla"
    if jax.default_backend() == "tpu":
        # Pallas wants sublane-aligned head_dim (64 packs two rows per
        # vreg; 128 is native) and seq lengths that leave >=128 blocks
        # after the wrapper's divisor-fitting (flash.py picks
        # gcd(seq, block_size) as the block — and raises below 128, so
        # auto must check the kv length too, not pick a path that
        # crashes). The seq threshold and the self-attention restriction
        # (q_seq == kv_seq) bound the policy to the measured regime.
        head_dim, seq, kv_seq = q.shape[-1], q.shape[-3], k.shape[-3]
        if (head_dim % 64 == 0 and seq == kv_seq and seq % 128 == 0
                and seq >= FLASH_MIN_SEQ):
            return "flash"
    return "xla"


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: jax.Array | None = None,
    causal: bool = False,
    impl: Impl = "auto",
    block_size: int = 512,
) -> jax.Array:
    """Multi-head scaled dot-product attention.

    Args:
      q, k, v: ``(batch, seq, heads, head_dim)``.
      mask: optional boolean ``(batch, 1|heads, q_seq, kv_seq)``; True keeps.
      causal: apply a causal mask (combined with ``mask`` if both given).
      impl: implementation selector (see module docstring).
      block_size: kv-block length for the blockwise/flash paths.

    Returns ``(batch, seq, heads, head_dim)`` in the dtype of ``q``.
    """
    chosen = _pick_impl(impl, q, k)
    if chosen == "xla":
        return dot_product_attention(q, k, v, mask=mask, causal=causal)
    if chosen == "blockwise":
        return blockwise_attention(q, k, v, mask=mask, causal=causal,
                                   block_size=block_size)
    if chosen == "flash":
        from .flash import flash_attention

        return flash_attention(q, k, v, mask=mask, causal=causal,
                               block_size=min(block_size, q.shape[1]))
    raise ValueError(f"unknown attention impl {chosen!r}")


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: jax.Array | None = None,
    causal: bool = False,
) -> jax.Array:
    """Reference XLA formulation; softmax in f32."""
    dtype = q.dtype
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5
    # (B, S, H, D) x (B, T, H, D) -> (B, H, S, T)
    logits = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32) * scale
    logits = _apply_masks(logits, mask, causal)
    weights = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhst,bthd->bshd", weights, v)


def _apply_masks(logits: jax.Array, mask: jax.Array | None, causal: bool,
                 q_offset: int | jax.Array = 0) -> jax.Array:
    """Additive-mask ``(B, H, S, T)`` logits. ``q_offset`` shifts query
    positions (used by blockwise/ring where q is a chunk of a longer seq)."""
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (s, t), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (s, t), 1)
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    return logits


def online_softmax_update(
    state: tuple[jax.Array, jax.Array, jax.Array],
    qf: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    causal: bool = False,
    mask_block: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One step of the online-softmax recurrence over a kv chunk.

    The shared core of ``blockwise_attention`` (scan over local kv blocks)
    and ``parallel.ring.ring_attention`` (scan over *remote* kv chunks
    arriving via ``ppermute``). Positions are absolute: ``q_offset`` /
    ``k_offset`` locate the chunks inside the full sequence so causal
    masking stays correct when chunks are distributed.

    Args:
      state: ``(m, l, acc)`` with shapes ``(B,H,S)``, ``(B,H,S)``,
        ``(B,H,S,D)`` — f32 running max, normaliser, accumulator.
      qf: pre-scaled f32 queries ``(B,H,S,D)``.
      k, v: f32 kv chunk ``(B,H,T,D)``.
      mask_block: optional bool ``(B,1|H,S,T)``; True keeps.
    """
    m, l, acc = state
    s, t = qf.shape[-2], k.shape[-2]
    logits = jnp.einsum("bhsd,bhtd->bhst", qf, k)
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (s, t), 0)
        k_pos = k_offset + lax.broadcasted_iota(jnp.int32, (s, t), 1)
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    if mask_block is not None:
        logits = jnp.where(mask_block, logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum("bhst,bhtd->bhsd", p, v)
    return m_new, l_new, acc_new


def online_softmax_init(b: int, h: int, s: int, d: int):
    """Zero state for :func:`online_softmax_update`."""
    return (
        jnp.full((b, h, s), NEG_INF, jnp.float32),
        jnp.zeros((b, h, s), jnp.float32),
        jnp.zeros((b, h, s, d), jnp.float32),
    )


def online_softmax_finish(state, dtype) -> jax.Array:
    """Normalise the accumulator; fully-masked rows yield 0, not NaN."""
    m, l, acc = state
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.where((m <= NEG_INF / 2)[..., None], 0.0, out).astype(dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: jax.Array | None = None,
    causal: bool = False,
    block_size: int = 512,
) -> jax.Array:
    """Online-softmax attention scanning over kv blocks.

    Maintains the FlashAttention running state per query: max logit ``m``,
    normaliser ``l``, and unnormalised accumulator ``acc``; each kv block
    updates the state with the standard rescaling recurrence. Memory is
    O(seq * block) instead of O(seq^2), which is what makes million-token
    sequences feasible; the same recurrence consumes remote kv blocks in
    ring attention.
    """
    dtype = q.dtype
    b, s, h, d = q.shape
    t = k.shape[1]
    block = min(block_size, t)
    if t % block:
        raise ValueError(f"kv seq {t} not divisible by block {block}")
    n_blocks = t // block
    scale = d ** -0.5

    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # (B,H,S,D)
    kb = k.astype(jnp.float32).reshape(b, n_blocks, block, h, d)
    vb = v.astype(jnp.float32).reshape(b, n_blocks, block, h, d)
    mb = None
    if mask is not None:
        mask = jnp.broadcast_to(mask, (b, mask.shape[1], s, t))
        mb = mask.reshape(b, mask.shape[1], s, n_blocks, block)

    def body(carry, inp):
        (i, kblk, vblk) = inp
        kblk = kblk.transpose(0, 2, 1, 3)  # (B,H,block,D)
        vblk = vblk.transpose(0, 2, 1, 3)
        blk_mask = None
        if mb is not None:
            blk_mask = lax.dynamic_index_in_dim(mb, i, axis=3, keepdims=False)
        carry = online_softmax_update(
            carry, qf, kblk, vblk, k_offset=i * block, causal=causal,
            mask_block=blk_mask,
        )
        return carry, None

    ks = jnp.moveaxis(kb, 1, 0)  # (n_blocks, B, block, H, D) for scan
    vs = jnp.moveaxis(vb, 1, 0)
    state, _ = lax.scan(body, online_softmax_init(b, h, s, d),
                        (jnp.arange(n_blocks), ks, vs))
    return online_softmax_finish(state, dtype).transpose(0, 2, 1, 3)
