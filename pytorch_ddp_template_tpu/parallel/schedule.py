"""Unified decomposed-scan: ONE custom-vjp skeleton, per-axis collective
schedules (``--fsdp_overlap`` × ``--ddp_overlap`` × ``--tp_overlap``).

r8–r10 grew three explicit-overlap execution paths — decomposed FSDP
(``parallel/overlap.py``), compressed backward-overlapped DDP
(``parallel/compress.py``), ring collective-matmul TP
(``parallel/collective_matmul.py``) — that shared one skeleton by copy:
a forward ``lax.scan`` carrying next-layer state, a hand-written
``custom_vjp`` reverse scan recomputing each block from its saved
boundary activation, and a per-iteration gradient drain. Each path
refused the others, so a real pod shape (data × fsdp × model running
simultaneously) fell back to the unoverlapped GSPMD schedule on every
axis but one.

This module is that skeleton, written exactly once
(:func:`decomposed_scan`), with the per-mesh-axis work factored into
*collective schedule* contributions:

- **fsdp** (:class:`FsdpSchedule`): layer k+1's weight gather issued
  before layer k's compute, layer k's grad scatter drained under layer
  k−1's backward — the r8 pipeline, now able to gather over ``data``
  while leaving a live ``model`` sharding on the weights intact (the
  gather/scatter region specs carry the TP placement, so fsdp×tp
  composes: the data-axis gathers and the model-axis ring ppermutes are
  collectives over *different* mesh axes and pipeline independently).
- **ddp** (:class:`DdpSchedule`): each layer's cross-replica grad reduce
  issued inside its own reverse-scan iteration, in ``grad_comm`` wire
  precision with the r9 quantization/error-feedback path. Composed with
  tp, the whole block runs inside ONE ``shard_map`` region over
  ``data × model`` using the local ring kernels
  (``collective_matmul.tp_column_dense_local``/``tp_row_dense_local``),
  and the drain merges TP's per-layer ``data``-psum of weight grads with
  the compressed reduce: one exchange per layer, never a trailing wall.
- **tp** (:class:`PlainSchedule` + the ring ops inside the block): the
  rotation state lives inside the block's collective matmuls; the
  framework contributes the per-layer backward structure (recompute from
  boundary activations → every layer's weight-grad psum over ``data``
  drains inside its own iteration via shard_map's transpose).

``overlap_scan`` and ``ddp_overlap_scan`` remain as the single-axis
entry points (same signatures, same numerics) but are now thin wrappers
assembling a schedule and calling :func:`decomposed_scan` — no second or
third copy of the carry/recompute/drain logic survives.

Numerics: identical math to the single-axis paths (bit-exact gathers,
ring-reassociated TP sums at the last f32 ulp); dropout streams fold the
layer index (and under ddp the data/model shard coordinates) rather than
``nn.scan``'s split — statistically equivalent, not bit-interchangeable
(documented in README).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import DATA_AXIS, MODEL_AXIS, PIPE_AXIS

#: module paths inside one encoder block -> logical axis names, mirroring
#: the ``nn.with_logical_partitioning`` annotations in
#: ``models/transformer.py``. Needed because the decomposed paths run at
#: apply time, where params arrive as plain arrays (the boxes that carry
#: logical names exist only at init) — the region specs must be rebuilt
#: statically. A cross-check test pins this table against the init-time
#: metadata so the two cannot drift silently.
_BLOCK_LOGICAL_AXES: dict[tuple[str, str], tuple[str, ...]] = {
    ("query", "kernel"): ("embed", "heads", "kv"),
    ("key", "kernel"): ("embed", "heads", "kv"),
    ("value", "kernel"): ("embed", "heads", "kv"),
    ("query", "bias"): ("heads", "kv"),
    ("key", "bias"): ("heads", "kv"),
    ("value", "bias"): ("heads", "kv"),
    ("out", "kernel"): ("heads", "kv", "embed"),
    ("out", "bias"): ("embed",),
    ("fc1", "kernel"): ("embed", "mlp"),
    ("fc1", "bias"): ("mlp",),
    ("fc2", "kernel"): ("mlp", "embed"),
    ("fc2", "bias"): ("embed",),
    # LayerNorms are unannotated in the model (plain nn.LayerNorm):
    # one replicated feature dim ("embed" maps to no mesh axis)
    ("ln_attn", "scale"): ("embed",),
    ("ln_attn", "bias"): ("embed",),
    ("ln_mlp", "scale"): ("embed",),
    ("ln_mlp", "bias"): ("embed",),
}


def _path_keys(path) -> tuple[str, ...]:
    return tuple(
        getattr(p, "key", getattr(p, "name", str(p))) for p in path
    )


def stacked_tp_specs(stacked: Any, mesh: Mesh, *,
                     leading_layer_dim: bool = True) -> Any:
    """Per-leaf :class:`PartitionSpec` tree for a (stacked) encoder-block
    param tree under the Megatron TP layout (``parallel/sharding.py``
    rules applied to the block's logical axes).

    ``leading_layer_dim``: leaves carry the stacked ``(num_layers, ...)``
    dim first (replicated — FSDP adds its ``data`` split on top of these
    specs via :func:`overlap.make_layer_gather`). Unknown leaf paths fail
    with intent: a new block param silently mapped to "replicated" would
    be silently unsharded by the region specs.
    """
    from .sharding import active_rules

    rules = dict(active_rules(mesh))
    flat, treedef = jax.tree_util.tree_flatten_with_path(stacked)
    specs = []
    for path, leaf in flat:
        keys = _path_keys(path)
        axes = _BLOCK_LOGICAL_AXES.get(keys[-2:]) if len(keys) >= 2 else None
        if axes is None:
            raise ValueError(
                f"stacked_tp_specs: unknown block param at path "
                f"{'/'.join(keys)} — extend _BLOCK_LOGICAL_AXES "
                "(parallel/schedule.py) with its logical axes so the "
                "decomposed schedules know its TP placement"
            )
        entries = tuple(rules.get(name) for name in axes)
        want_ndim = len(axes) + (1 if leading_layer_dim else 0)
        if leaf.ndim != want_ndim:
            raise ValueError(
                f"stacked_tp_specs: param {'/'.join(keys)} has ndim "
                f"{leaf.ndim}, expected {want_ndim} for logical axes "
                f"{axes} (leading_layer_dim={leading_layer_dim})"
            )
        specs.append(P(None, *entries) if leading_layer_dim else P(*entries))
    return jax.tree_util.tree_unflatten(treedef, specs)


def staged_tp_specs(staged: Any, mesh: Mesh) -> Any:
    """Per-leaf :class:`PartitionSpec` tree for a pipe-STAGED block tree
    — leaves shaped ``(n_stages, layers_per_stage, *param)`` — under the
    Megatron TP layout: the stage dim shards over ``pipe``, the layer
    dim is replicated, and the trailing dims follow the same
    ``_BLOCK_LOGICAL_AXES`` placement :func:`stacked_tp_specs` uses.
    This is the ``stage_specs`` input of
    ``parallel.pipeline.pipelined_loss(compose='tp')``.
    """
    from .sharding import active_rules

    rules = dict(active_rules(mesh))
    flat, treedef = jax.tree_util.tree_flatten_with_path(staged)
    specs = []
    for path, leaf in flat:
        keys = _path_keys(path)
        axes = _BLOCK_LOGICAL_AXES.get(keys[-2:]) if len(keys) >= 2 else None
        if axes is None:
            raise ValueError(
                f"staged_tp_specs: unknown block param at path "
                f"{'/'.join(keys)} — extend _BLOCK_LOGICAL_AXES "
                "(parallel/schedule.py) with its logical axes so the "
                "pipelined TP schedule knows its placement"
            )
        if leaf.ndim != len(axes) + 2:
            raise ValueError(
                f"staged_tp_specs: param {'/'.join(keys)} has ndim "
                f"{leaf.ndim}, expected {len(axes) + 2} for logical axes "
                f"{axes} plus the (stage, layer) leading dims"
            )
        specs.append(P(PIPE_AXIS, None, *(rules.get(n) for n in axes)))
    return jax.tree_util.tree_unflatten(treedef, specs)


def spec_mentions(spec: P | None, axis: str) -> bool:
    """True when ``axis`` appears anywhere in a PartitionSpec."""
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        if axis in names:
            return True
    return False


# -- unified mesh validation -----------------------------------------------

def validate_schedule_mesh(mesh: Mesh | None, *, fsdp: bool = False,
                           ddp: bool = False, tp: bool = False,
                           pipe: bool = False) -> Mesh:
    """Refuse meshes the composed decomposed schedules cannot serve,
    with the reason named per axis.

    The composable sets: ``data`` (fsdp gathers / ddp reduces) ×
    ``model`` (tp rings) for the decomposed-scan family, and — since
    r22's boundary-hoisted collective waves — ``pipe`` × ``data`` ×
    at most ONE of {tp, fsdp, ddp} for the pipeline slot schedules
    (``pipe=True``): pipe×data×model when ``tp``, pipe×data(param
    split) when ``fsdp`` or ``ddp``. What stays refused is genuinely
    impossible or senseless, with the reason named: more than one
    in-stage decomposition per run (the slot boundary carries one
    uniform collective wave), a live ``model`` axis without ``tp``
    (silent unshard), and ``seq``/``expert`` axes which need in-region
    handling no schedule implements.
    """
    if mesh is None:
        raise ValueError(
            "the decomposed overlap schedules need the device mesh "
            "threaded into the model (models/registry.py does this; pass "
            "mesh= when building directly)"
        )
    if pipe:
        n_on = sum((fsdp, ddp, tp))
        if n_on > 1:
            other = "/".join(n for n, on in (
                ("fsdp", fsdp), ("ddp", ddp), ("tp", tp)) if on)
            raise ValueError(
                f"the pipeline slot schedules compose pipe with exactly "
                f"ONE in-stage decomposition per run, got {other}: the "
                "slot boundary carries one uniform collective wave and "
                "stacking a second would interleave two waves with "
                "different shapes per stage — drop all but one overlap "
                "flag"
            )
        if mesh.shape.get(PIPE_AXIS, 1) <= 1:
            raise ValueError(
                "the pipeline schedules drive a 'pipe' mesh axis of size "
                f">= 2, but the mesh is {dict(mesh.shape)} — add pipe:N "
                "to --mesh"
            )
        allowed = ({DATA_AXIS, PIPE_AXIS}
                   | ({MODEL_AXIS} if tp else set()))
        extra = {name: size for name, size in mesh.shape.items()
                 if name not in allowed and size > 1}
        if extra:
            if MODEL_AXIS in extra and not tp:
                raise ValueError(
                    f"mesh has a live '{MODEL_AXIS}' axis ({extra}) but "
                    "no --tp_overlap: the stage weights would be "
                    "model-sharded while the slot region specs "
                    "replicate them — a silent unshard every step; pass "
                    "--tp_overlap (pipe×tp composes since r22) or drop "
                    f"the {MODEL_AXIS} axis"
                )
            raise ValueError(
                f"the pipeline schedules compose over pipe×data"
                f"{'×model' if tp else ''} only; mesh also has {extra} "
                "— these axes need in-region handling no schedule "
                "implements; drop them"
            )
        if tp and mesh.shape.get(MODEL_AXIS, 1) <= 1:
            raise ValueError(
                "--tp_overlap under a pipe mesh shards each stage's "
                f"weights over a '{MODEL_AXIS}' axis, but the mesh is "
                f"{dict(mesh.shape)} — add model:N to --mesh or drop "
                "--tp_overlap"
            )
        return mesh
    allowed = {DATA_AXIS} | ({MODEL_AXIS} if tp else set())
    extra = {name: size for name, size in mesh.shape.items()
             if name not in allowed and size > 1}
    if extra:
        if MODEL_AXIS in extra and (fsdp or ddp) and not tp:
            what = ("--fsdp_overlap supports data-axis FSDP only"
                    if fsdp else
                    "--ddp_overlap supports replicated-param "
                    "data-parallel meshes only")
            raise ValueError(
                f"{what} unless composed with --tp_overlap; mesh also "
                f"has {extra}: a live '{MODEL_AXIS}' axis means the "
                "weights are model-sharded and the "
                f"{'gather' if fsdp else 'reduce'} region specs would "
                "silently unshard them — pass --tp_overlap too or drop "
                f"the {MODEL_AXIS} axis"
            )
        raise ValueError(
            f"the decomposed overlap schedules compose over data×model "
            f"only; mesh also has {extra} — drop the extra axes or the "
            "overlap flags"
        )
    if tp and mesh.shape.get(MODEL_AXIS, 1) <= 1:
        raise ValueError(
            "--tp_overlap decomposes the tensor-parallel collectives of "
            f"a '{MODEL_AXIS}' mesh axis, but the mesh is "
            f"{dict(mesh.shape)} (data-only / model:1) — there is no TP "
            "matmul to overlap; add model:N to --mesh or drop --tp_overlap"
        )
    return mesh


# -- the shared custom-vjp skeleton ----------------------------------------

def _slice_layer(stacked: Any, k: jax.Array) -> Any:
    """Layer ``k`` of a stacked ``(num_layers, ...)`` tree."""
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, k, 0, keepdims=False), stacked)


def num_stacked_layers(stacked: Any, what: str) -> int:
    leaves = jax.tree.leaves(stacked)
    if not leaves:
        raise ValueError(f"{what}: empty stacked parameter tree")
    return int(leaves[0].shape[0])


def decomposed_scan(schedule: Any,
                    apply_fn: Callable[[Any, jax.Array, jax.Array, Any],
                                       jax.Array],
                    stacked: Any, x: jax.Array, extras: Any, *,
                    residual: Any | None = None,
                    comm_rng: jax.Array | None = None) -> jax.Array:
    """Drive ``apply_fn(layer_params, y, k, extras)`` over the stacked
    layers under ``schedule`` — THE shared custom-vjp skeleton every
    decomposed execution path rides (``--fsdp_overlap``,
    ``--ddp_overlap``, ``--tp_overlap`` and their compositions).

    Forward: one ``lax.scan`` whose carry holds ``(activations,
    schedule-owned weight state)``; the schedule's :meth:`fwd_weights`
    runs *before* the layer's compute, so anything it issues (the fsdp
    layer-(k+1) gather) is dataflow-independent of this iteration's dots.
    ``run_fwd`` additionally saves each layer's INPUT activation — the
    only O(L) residual.

    Backward (the custom-vjp rule — never autodiff through the forward
    scan, which would stack every iteration's gathered state into an
    O(L) residual): a reverse scan that recomputes each block from its
    saved boundary activation (implicit block remat — ``--remat``
    composes free), lets the schedule prefetch the next (earlier)
    layer's weight state under this layer's backward compute, and drains
    this layer's weight grads *inside the iteration* — scatter into the
    sharded stacked layout (fsdp), compressed cross-replica reduce
    (ddp), or the plain per-layer slot write whose ``data``-psum of TP
    weight grads shard_map's transpose emits per layer (tp).

    ``extras`` carries every traced auxiliary input the block consumes
    (attention mask, dropout rng): custom_vjp forbids closing over
    tracers, so they ride as explicit primal args with symbolic-zero
    cotangents. ``residual``/``comm_rng`` thread the r9 error-feedback
    state: the updated residual leaves the backward through the residual
    input's cotangent slot (the only in-jit channel for
    backward-produced state).
    """
    num_layers = num_stacked_layers(stacked, "decomposed_scan")
    ks = jnp.arange(num_layers, dtype=jnp.int32)

    @jax.custom_vjp
    def run(stacked, x, extras, residual, comm_rng):
        wc0 = schedule.fwd_init(stacked)

        def body(carry, k):
            y, wc = carry
            # schedule state FIRST: anything issued here (the fsdp
            # prefetch gather) is independent of this layer's compute by
            # construction, visible as such in the lowered loop body.
            # named_scope = trace-time metadata only (r13): profiler
            # traces and HLO dumps show the schedule phase instead of
            # anonymous op soup; zero runtime cost
            with jax.named_scope("sched_weights"):
                w, wc = schedule.fwd_weights(stacked, wc, k)
            with jax.named_scope("sched_block_fwd"):
                y = schedule.fwd_apply(apply_fn, w, y, k, extras)
            return (y, wc), None

        (y, _), _ = lax.scan(body, (x, wc0), ks)
        return y

    def run_fwd(stacked, x, extras, residual, comm_rng):
        wc0 = schedule.fwd_init(stacked)

        def body(carry, k):
            y, wc = carry
            with jax.named_scope("sched_weights"):
                w, wc = schedule.fwd_weights(stacked, wc, k)
            with jax.named_scope("sched_block_fwd"):
                y_out = schedule.fwd_apply(apply_fn, w, y, k, extras)
            # save each layer's INPUT activation: the boundary residual
            # the backward recomputes from
            return (y_out, wc), y

        (y, _), xs = lax.scan(body, (x, wc0), ks)
        return y, (stacked, xs, extras, residual, comm_rng)

    def run_bwd(res, gy):
        stacked, xs, extras, residual, comm_rng = res
        wc0 = schedule.bwd_init(stacked)
        gacc0 = schedule.gacc_init(stacked)

        def body(carry, inputs):
            gy, wc, gacc = carry
            k, x_k, res_k = inputs
            key_k = (None if comm_rng is None
                     else jax.random.fold_in(comm_rng, k))
            with jax.named_scope("sched_block_bwd"):
                gy, wc, gacc, ys = schedule.bwd_step(
                    apply_fn, stacked, wc, gacc, k, x_k, gy, extras,
                    res_k, key_k)
            return (gy, wc, gacc), ys

        (gx, _, gacc), ys = lax.scan(
            body, (gy, wc0, gacc0), (ks, xs, residual), reverse=True)
        with jax.named_scope("sched_grad_finalize"):
            grads, res_ct = schedule.finalize(gacc, ys)
        if residual is None:
            res_ct = None
        key_ct = (None if comm_rng is None
                  else np.zeros(np.shape(comm_rng), jax.dtypes.float0))
        from .overlap import _zero_cotangent

        return grads, gx, _zero_cotangent(extras), res_ct, key_ct

    run.defvjp(run_fwd, run_bwd)
    return run(stacked, x, extras, residual, comm_rng)


# -- per-axis schedule contributions ---------------------------------------

class PlainSchedule:
    """Null weight schedule (``--tp_overlap`` alone): slice layer ``k``
    from the (replicated-over-data, possibly model-sharded) stacked tree;
    apply at the GSPMD level (the block's ring collective matmuls carry
    their own shard_map regions); grads stack per layer out of the
    reverse scan — each layer's TP weight-grad psum over ``data`` (the
    shard_map transpose of the ring ops' kernel specs) drains inside its
    own iteration instead of a post-backward wall."""

    def fwd_init(self, stacked):
        return ()

    def fwd_weights(self, stacked, wc, k):
        return _slice_layer(stacked, k), ()

    def fwd_apply(self, apply_fn, w, y, k, extras):
        return apply_fn(w, y, k, extras)

    def bwd_init(self, stacked):
        return ()

    def gacc_init(self, stacked):
        return ()

    def bwd_step(self, apply_fn, stacked, wc, gacc, k, x_k, gy, extras,
                 res_k, key_k):
        w = _slice_layer(stacked, k)
        _, pull = jax.vjp(
            lambda w_, y_: apply_fn(w_, y_, k, extras), w, x_k)
        gw, gx = pull(gy)
        return gx, (), (), (gw, None)

    def finalize(self, gacc, ys):
        gws, _ = ys
        return gws, None


class FsdpSchedule:
    """Decomposed-FSDP contribution (the r8 pipeline): the fwd carry
    holds the NEXT layer's gathered weights, the bwd carry the PREVIOUS
    layer's; each bwd iteration scatters its layer's grads straight into
    the sharded stacked layout. ``tp_specs`` (fsdp×tp) threads the
    Megatron model-axis placement through the gather/scatter region
    specs, so the data-axis collectives leave the model sharding intact
    and the block's ring ppermutes pipeline independently of them."""

    def __init__(self, mesh: Mesh, stacked: Any, num_layers: int,
                 tp_specs: Any | None = None):
        from .overlap import make_layer_gather

        validate_schedule_mesh(mesh, fsdp=True, tp=tp_specs is not None)
        self.num_layers = num_layers
        self.gather, self.scatter = make_layer_gather(
            mesh, stacked, num_layers, tp_specs=tp_specs)

    def fwd_init(self, stacked):
        return self.gather(stacked, jnp.asarray(0, jnp.int32))

    def fwd_weights(self, stacked, wc, k):
        # prefetch FIRST: independent of this layer's compute
        w_next = self.gather(
            stacked, jnp.minimum(k + 1, self.num_layers - 1))
        return wc, w_next

    def fwd_apply(self, apply_fn, w, y, k, extras):
        return apply_fn(w, y, k, extras)

    def bwd_init(self, stacked):
        return self.gather(stacked, jnp.asarray(self.num_layers - 1,
                                                jnp.int32))

    def gacc_init(self, stacked):
        return jax.tree.map(jnp.zeros_like, stacked)

    def bwd_step(self, apply_fn, stacked, wc, gacc, k, x_k, gy, extras,
                 res_k, key_k):
        # prefetch the PREVIOUS layer's weights under this layer's
        # backward compute — the mirror of the forward pipeline
        w_prev = self.gather(stacked, jnp.maximum(k - 1, 0))
        _, pull = jax.vjp(
            lambda w_, y_: apply_fn(w_, y_, k, extras), wc, x_k)
        gw, gx = pull(gy)
        # per-layer drain: the cross-replica reduction GSPMD emits to
        # satisfy the scatter region's data-replicated in-spec, then the
        # owner-shard write — layer k's grads reach the sharded stacked
        # layout while layer k−1's backward still has compute in flight
        gacc = jax.tree.map(jnp.add, gacc, self.scatter(gw, k))
        return gx, w_prev, gacc, None

    def finalize(self, gacc, ys):
        return gacc, None


class DdpSchedule:
    """Compressed-DDP contribution (the r9 path): the whole per-layer
    block vjp runs inside a ``shard_map`` region — over ``data`` alone
    (replicated params), or over ``data × model`` when composed with tp
    (``tp_specs`` set): the block then uses the LOCAL ring kernels and
    the drain merges TP's per-layer ``data``-psum of weight grads with
    the compressed reduce into one exchange. Leaves replicated over
    ``model`` (LayerNorms, row biases) hold per-seq-chunk partials and
    are psum'd over ``model`` before the data-axis reduce."""

    def __init__(self, mesh: Mesh, stacked: Any, num_layers: int,
                 extras_specs: Any, *, grad_comm: str = "fp32",
                 chunk: int | None = None, tp_specs: Any | None = None,
                 residual: Any | None = None,
                 comm_rng: jax.Array | None = None):
        from .compress import CHUNK, GRAD_COMM_MODES

        tp = tp_specs is not None
        validate_schedule_mesh(mesh, ddp=True, tp=tp)
        if grad_comm not in GRAD_COMM_MODES:
            raise ValueError(f"unknown grad_comm mode {grad_comm!r}; "
                             f"expected one of {GRAD_COMM_MODES}")
        if grad_comm != "fp32" and comm_rng is None:
            raise ValueError(f"grad_comm={grad_comm!r} needs comm_rng for "
                             "stochastic rounding")
        if residual is not None and grad_comm == "fp32":
            raise ValueError("error-feedback residual with grad_comm=fp32 "
                             "is a no-op by construction; drop one of the "
                             "two")
        self.mesh = mesh
        self.n = mesh.shape.get(DATA_AXIS, 1)
        self.grad_comm = grad_comm
        self.chunk = chunk if chunk is not None else CHUNK
        self.extras_specs = extras_specs
        self.tp = tp
        if tp:
            self.layer_specs = jax.tree.map(
                lambda s: P(*tuple(s)[1:]), tp_specs,
                is_leaf=lambda s: isinstance(s, P))
            self.x_spec = P(DATA_AXIS, MODEL_AXIS, None)
        else:
            self.layer_specs = jax.tree.map(
                lambda _: P(), _slice_layer(stacked, jnp.asarray(0)))
            self.x_spec = P(DATA_AXIS)
        res_slice = (None if residual is None
                     else _slice_layer(residual, jnp.asarray(0)))
        # residual layout: (data, padded) replicated-param leaves, or
        # (data, model, padded_local) under the composed ddp×tp drain
        # (compress.residual_shape_tp — each (data, model) coordinate
        # compensates exactly the local shard it quantizes)
        res_spec = P(DATA_AXIS, MODEL_AXIS) if tp else P(DATA_AXIS)
        self.res_specs = jax.tree.map(lambda _: res_spec, res_slice)
        self.has_key = comm_rng is not None

    def _region(self, fn, in_specs, out_specs):
        from .shard_map_compat import shard_map

        return shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)

    def fwd_init(self, stacked):
        return ()

    def fwd_weights(self, stacked, wc, k):
        return _slice_layer(stacked, k), ()

    def fwd_apply(self, apply_fn, w, y, k, extras):
        region = self._region(
            lambda w_, y_, k_, e: apply_fn(w_, y_, k_, e),
            (self.layer_specs, self.x_spec, P(), self.extras_specs),
            self.x_spec)
        return region(w, y, k, extras)

    def bwd_init(self, stacked):
        return ()

    def gacc_init(self, stacked):
        return ()

    def bwd_step(self, apply_fn, stacked, wc, gacc, k, x_k, gy, extras,
                 res_k, key_k):
        from .compress import _reduce_tree

        def region_body(w, x_k, gy, k, e, res_k, key):
            # the whole per-layer vjp runs on the local shard(s): every
            # op is per-example (and, under tp, ring-local), so these
            # are the true per-replica partials a compressed reduce must
            # start from
            _, pull = jax.vjp(
                lambda w_, y_: apply_fn(w_, y_, k, e), w, x_k)
            gw, gx = pull(gy)
            if self.tp:
                # model-replicated leaves (LayerNorms, row biases) hold
                # per-seq-chunk partials — complete them over `model`
                # first; model-sharded kernels are already complete per
                # shard. Then ONE data-axis exchange drains both TP's
                # weight-grad psum and the DDP bucket reduce.
                gw = jax.tree.map(
                    lambda g, spec: (g if spec_mentions(spec, MODEL_AXIS)
                                     else lax.psum(g, MODEL_AXIS)),
                    gw, self.layer_specs,
                )
            gw_sum, res_new = _reduce_tree(
                gw, res_k, key, self.grad_comm, DATA_AXIS, self.n,
                self.chunk)
            return gw_sum, gx, res_new

        region = self._region(
            region_body,
            (self.layer_specs, self.x_spec, self.x_spec, P(),
             self.extras_specs, self.res_specs,
             P() if self.has_key else None),
            (self.layer_specs, self.x_spec, self.res_specs))
        gw_sum, gx, res_new = region(
            _slice_layer(stacked, k), x_k, gy, k, extras, res_k, key_k)
        # per-layer drain: gw_sum is fully reduced HERE, inside the
        # iteration — independent of every earlier layer's backward
        return gx, (), (), (gw_sum, res_new)

    def finalize(self, gacc, ys):
        gws, res = ys
        return gws, res


class PipelineSchedule:
    """Pipeline contribution (the r16 fourth schedule axis): owns the
    slot table, the boundary-ppermute send/recv state and the dx/dw
    split policy for a ``pipe`` mesh axis.

    Unlike the three scan contributions above, the pipeline does not
    iterate over *layers* — it iterates over schedule *slots*, with the
    per-stage layer scan nested INSIDE each slot's work unit (the
    stage-local ``--scan_layers``). Its driver is therefore
    ``parallel/pipeline.pipelined_loss`` (one fused slot loop whose
    carry holds the schedule-owned state: send buffers, activation/
    grad/tap stores, grad accumulators) rather than
    :func:`decomposed_scan`; what it shares with the other three is the
    framework surface — this class plugs the pipe axis into
    :func:`validate_schedule_mesh`, ``describe()``'s unified overlap
    block and the ``--hlo_report`` tripwire
    (``obs/hlo_report.check_overlap_expectations``).

    Composition today: pipe×data (the microbatch dim shards over
    ``data`` inside the same region) × at most one of tp/fsdp/ddp
    inside a stage (r22 boundary-hoisted collective waves, 1f1b only —
    ``pipelined_loss(compose=...)``). Pass the in-stage decomposition
    flags here so the mesh check matches the run's actual composition;
    what stays refused is named in :func:`validate_schedule_mesh`.
    """

    def __init__(self, mesh: Mesh, kind: str, n_micro: int, *,
                 tp: bool = False, ddp: bool = False, fsdp: bool = False):
        from .pipeline import PIPE_SCHEDULES, build_pipe_table

        if kind not in PIPE_SCHEDULES:
            raise ValueError(
                f"unknown pipe schedule {kind!r}; expected one of "
                f"{PIPE_SCHEDULES}")
        validate_schedule_mesh(mesh, pipe=True, tp=tp, ddp=ddp, fsdp=fsdp)
        self.compose = ("tp" if tp else "ddp" if ddp
                        else "fsdp" if fsdp else "none")
        self.mesh = mesh
        self.kind = kind
        self.n_micro = n_micro
        self.n_stages = mesh.shape[PIPE_AXIS]
        # gpipe is the masked fill/drain loop — no slot table
        self.table = (None if kind == "gpipe"
                      else build_pipe_table(kind, n_micro, self.n_stages))

    def bubble_fraction(self) -> float:
        from .pipeline import schedule_bubble_fraction

        return schedule_bubble_fraction(self.kind, self.n_micro,
                                        self.n_stages)

    def wire_bytes_per_step(self, mb: int, seq: int, embed: int,
                            itemsize: int = 4) -> int:
        """Boundary-activation bytes one training step moves over the
        pipe axis (the r9 ``grad_wire_mb`` convention applied to PP),
        counted as single-hop buffer sends of ``(mb, seq, embed)`` per
        stage: the fused slot loops issue TWO ppermutes per slot (fwd
        activation down + bwd grad up), gpipe's masked loop ONE per
        tick (fwd ticks send activations; the AD-transposed backward
        ticks send grads). In-stage compose waves (tp all-reduces, ddp
        reduces, fsdp gather/scatter) ride the *other* axes and are
        accounted by their own helpers
        (``collective_matmul.tp_wire_bytes_per_step`` et al.)."""
        buf = mb * seq * embed * itemsize
        if self.table is not None:
            hops = 2 * self.table.n_slots
        else:
            hops = 2 * (self.n_micro + self.n_stages - 1)
        return hops * self.n_stages * buf

    def tp_wave_bytes_per_step(self, mb: int, seq: int, embed: int,
                               layers_per_stage: int, model: int,
                               itemsize: int = 4) -> int:
        """Static MODEL-axis wire estimate for the r22 pipe×tp compose
        wave, per training step across all stages.

        The psum-form Megatron stage (models/gpt_pipe.py) issues two
        model-axis all-reduces per layer in the forward sweep — which
        runs EVERY slot (on B slots it is the recompute) — and two more
        per layer in the guarded backward segments of each B slot (one
        B slot per microbatch per stage). Each ring all-reduce moves
        ``2(n-1)/n`` × the ``(mb, seq, embed)`` activation per
        participant. This is the figure ``obs/attribution.py``'s
        ``static_cost_model`` uses to split the shared all-reduce
        census between the data and model axes on pipe×tp meshes —
        an estimate for attribution, not an exactness contract.
        """
        if model <= 1:
            return 0
        buf = mb * seq * embed * itemsize
        if self.table is not None:
            slots = self.table.n_slots
        else:
            slots = 2 * (self.n_micro + self.n_stages - 1)
        psums = 2 * layers_per_stage * (slots + self.n_micro)
        per_rank = 2 * (model - 1) / model
        return int(psums * self.n_stages * buf * per_rank)


# -- composed-schedule HLO evidence ----------------------------------------


def hlo_composed_evidence(hlo_text: str) -> dict[str, Any]:
    """Witness that a composed (fsdp×tp) lowering carries BOTH axes'
    collectives compute-independent in ONE scanned body.

    Since r12 a thin delegate to ``obs/hlo_report.composed_evidence``
    (the two-family operand walk + nested-computation reachability moved
    there so the production ``--hlo_report`` tripwire and the
    ``BENCH_MODE=overlap3d`` leg share ONE analysis). Semantics and keys
    unchanged: ``independent_gather_bodies`` / ``independent_ring_bodies``
    / ``bodies_with_both_independent`` and the headline boolean
    ``composed_overlap_independent``."""
    from ..obs.hlo_report import composed_evidence

    return composed_evidence(hlo_text)
