"""Sharding rules: logical model axes → mesh axes.

The reference's only parallelism is data parallelism (SURVEY.md §2b:
DDP ``/root/reference/ddp.py:194-195``, DataParallel ``ddp.py:189-191``);
everything else in its inventory table is "No". The TPU framework keeps
the mesh extensible (SURVEY.md §2b asks for an open model axis), and this
module is where extensibility becomes mechanism:

- Model code annotates weights with *logical* axis names
  (``nn.with_logical_partitioning`` in ``models/transformer.py``:
  ``embed``, ``mlp``, ``heads``, ``kv``, ``vocab``).
- This module maps logical names onto whatever mesh axes exist. A
  ``data``-only mesh replicates all weights (pure DDP); adding
  ``model`` to the mesh spec turns on Megatron-style tensor parallelism
  — column-split fc1/qkv, row-split fc2/out — with **zero model-code
  changes**. XLA/GSPMD inserts the all-reduces on the row-split matmuls.
- ``seq`` shards activation sequence dims (context parallelism; the
  attention part is ``parallel/ring.py``).

Design note: gradients and SGD optimizer state inherit param shardings
through XLA propagation (the train step is jitted with sharded params as
inputs), so no separate optimizer partitioning pass is needed.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
from jax.sharding import Mesh

from ..runtime.context import (
    DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
)

#: logical axis -> preferred mesh axes, in priority order. A rule applies
#: only if the mesh has that axis; otherwise the dim is replicated.
DEFAULT_RULES: tuple[tuple[str, str | None], ...] = (
    ("batch", DATA_AXIS),
    ("seq_act", SEQ_AXIS),   # activation sequence dim (context parallel)
    ("mlp", MODEL_AXIS),     # fc1 column-split
    ("heads", MODEL_AXIS),   # attention head-split
    ("vocab", MODEL_AXIS),   # embedding vocab-split
    ("expert", EXPERT_AXIS),  # MoE expert-stack dim (models/moe.py)
    ("pipe_stage", PIPE_AXIS),  # pipeline stage-stack dim (models/gpt_pipe.py)
    ("layers", None),        # scan-over-layers stacked layer dim
                             # (models/transformer.py scan_layers):
                             # replicated under DDP/TP — every rank runs
                             # every layer; FSDP instead splits it via
                             # fsdp_reshard(prefer_dim=0)
    ("embed", None),         # row dim of fc1/qkv: replicated (activations
                             # stay unsharded along embed between blocks)
    ("kv", None),
)


def active_rules(mesh: Mesh) -> tuple[tuple[str, str | None], ...]:
    """Drop rules whose mesh axis does not exist (or has size 1)."""
    sizes = mesh.shape
    return tuple(
        (logical, axis if axis in sizes and sizes[axis] > 1 else None)
        for logical, axis in DEFAULT_RULES
    )


def logical_shardings(tree: Any, mesh: Mesh,
                      rules: Sequence[tuple[str, str | None]] | None = None):
    """NamedShardings for a pytree whose leaves may be ``nn.Partitioned``.

    The returned tree matches the *unboxed* structure (each ``Partitioned``
    box collapses to one sharding leaf). Unannotated leaves (MLP/ResNet
    weights, scalars, rng keys) map to ``P()`` — fully replicated, the DDP
    baseline.
    """
    rules = tuple(rules if rules is not None else active_rules(mesh))
    specs = nn.get_partition_spec(tree)
    return nn.logical_to_mesh_sharding(specs, mesh, rules)


def shard_tree(tree: Any, mesh: Mesh,
               rules: Sequence[tuple[str, str | None]] | None = None):
    """Unbox + ``device_put`` a pytree onto the mesh per its logical
    annotations. Returns plain arrays (no ``Partitioned`` wrappers): the
    logical names have done their job once shardings are on the data."""
    shardings = logical_shardings(tree, mesh, rules)
    return jax.device_put(nn.meta.unbox(tree), shardings)


def fsdp_split_dim(shape: Sequence[int], data_size: int,
                   prefer_dim: int | None = None,
                   free: Sequence[bool] | None = None) -> int | None:
    """Which dim of ``shape`` the FSDP split lands on, or None.

    The single source of truth for the split-dim choice, shared between
    :func:`_shard_free_dim_over_data` (which places the data) and
    ``parallel/overlap.py`` (which must compute the SAME layout statically
    to build matching ``shard_map`` specs — a mismatch there would mean a
    silent reshard at every gather). Rules: only ``free`` dims whose size
    ``data_size`` divides are candidates; ``prefer_dim`` wins when it
    qualifies; otherwise the largest dim wins, ties keeping the earliest.
    """
    if data_size == 1 or not shape:
        return None
    free = [True] * len(shape) if free is None else list(free)

    def ok(i):
        return free[i] and shape[i] >= data_size and shape[i] % data_size == 0

    if prefer_dim is not None and prefer_dim < len(shape) and ok(prefer_dim):
        return prefer_dim
    best = None
    for i, dim in enumerate(shape):
        if ok(i) and (best is None or dim > shape[best]):
            best = i
    return best


def _shard_free_dim_over_data(tree: Any, mesh: Mesh,
                              prefer_dim: int | None = None) -> Any:
    """Shard each leaf's *largest* dividable free dim over ``data``.

    Leaves already placed on the mesh (param-mirrored shardings under TP)
    keep their existing axes; ``data`` is only added to a dim that is
    unsharded and whose size the data-axis size divides. Among candidate
    dims the largest wins (VERDICT r4 weak #6: first-dividable gave a
    (4, 8192) leaf at data=4 a degenerate 1-row shard where dim-1 yields
    2048-wide slices — better layouts for the all-gather and for MXU
    tiling after the gather). Ties keep the earliest dim, preserving
    round-4 checkpoint layouts for the common square case. Leaves with no
    dividable dim (scalars, odd shapes) stay as they are — correctness
    never depends on a leaf being sharded.

    ``prefer_dim``: when set, a leaf whose dim ``prefer_dim`` is free and
    dividable splits THERE regardless of size — the scan-over-layers hook:
    stacked weights all share the leading ``(num_layers, ...)`` dim, so
    preferring it gives FSDP one uniform split axis across the whole block
    stack (and layer-boundary all-gathers that match the scan schedule)
    instead of a per-leaf assortment of largest dims.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_size = mesh.shape.get(DATA_AXIS, 1)
    if data_size == 1:
        return tree

    def widen(x):
        if not hasattr(x, "sharding") or x.ndim == 0:
            return x
        spec = list(getattr(x.sharding, "spec", P()))
        spec += [None] * (x.ndim - len(spec))
        used: set[str] = set()
        for s in spec:
            if s is not None:
                used.update((s,) if isinstance(s, str) else s)
        if DATA_AXIS in used:
            return x

        best = fsdp_split_dim(x.shape, data_size, prefer_dim,
                              free=[s is None for s in spec])
        if best is not None:
            spec[best] = DATA_AXIS
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        return x

    return jax.tree.map(widen, tree)


def zero1_reshard(opt_state: Any, mesh: Mesh,
                  prefer_dim: int | None = None) -> Any:
    """ZeRO-1: shard optimizer state over the ``data`` axis.

    The reference replicates optimizer state on every rank (``optim.SGD``
    over all params, ``/root/reference/ddp.py:183``; SURVEY.md §2b marks
    ZeRO "No"). Here momentum/Adam state memory is cut by the DP degree.
    Inside the jitted step GSPMD partitions the optimizer update over
    ``data`` and inserts the all-gather of updates onto the replicated
    params: ZeRO-1 semantics without a wire protocol, the same way
    sharding-induced psum replaced DDP.
    """
    return _shard_free_dim_over_data(opt_state, mesh, prefer_dim)


def fsdp_reshard(tree: Any, mesh: Mesh,
                 prefer_dim: int | None = None) -> Any:
    """FSDP / ZeRO-3: shard params (and their optimizer mirrors) over
    ``data``.

    Applied to *params* as well as optimizer state, this is the full
    ZeRO-3 memory split: every rank holds 1/DP of the weights, gradients
    and optimizer state. GSPMD supplies the runtime protocol from the
    shardings alone — the forward all-gathers each weight just before
    use, the backward reduce-scatters gradients straight into the shard
    layout, and the optimizer update runs shard-local. The reference has
    no analogue (SURVEY.md §2b: ZeRO/FSDP "No"); PyTorch needs a wrapper
    module and hand-scheduled gather/scatter hooks for the same semantics.

    ``prefer_dim=0`` (passed by the trainer under ``--scan_layers``) makes
    the stacked leading layer dim the split axis wherever it divides — the
    whole block stack shards uniformly at layer granularity.
    """
    return _shard_free_dim_over_data(tree, mesh, prefer_dim)


def describe(mesh: Mesh, config: Any = None,
             params: Any = None, model: Any = None) -> dict[str, Any]:
    """Human-readable sharding summary for the startup log.

    With ``config`` (a ``TrainingConfig``) the summary also names the
    active FSDP execution mode — ``"decomposed-prefetch"`` under
    ``--fsdp_overlap`` (explicit one-layer-ahead gathers,
    ``parallel/overlap.py``) vs ``"gspmd-default"`` — and, when ``params``
    are supplied as well, a histogram of which dim each leaf's FSDP split
    landed on (``{"dim0": 12, "unsplit": 3}``-style), so a run's log
    records the layer-granular-vs-within-layer layout decision.

    On meshes with a live ``model`` axis the summary names the TP
    execution mode (``"ring-decomposed"`` under ``--tp_overlap``,
    ``parallel/collective_matmul.py``, vs ``"gspmd-default"``), and with
    ``model`` (the Flax module — the engine passes ``task.model``) it
    reports the per-step model-axis wire bytes, stack and LM head split
    out — the r9 ``grad_wire_mb`` convention applied to the TP axis.
    """
    sizes = dict(mesh.shape)
    out: dict[str, Any] = {
        "mesh": sizes,
        "data_parallel": sizes.get(DATA_AXIS, 1),
        "tensor_parallel": sizes.get(MODEL_AXIS, 1),
        "context_parallel": sizes.get(SEQ_AXIS, 1),
        "expert_parallel": sizes.get(EXPERT_AXIS, 1),
    }
    if config is not None:
        tp_on = bool(getattr(config, "tp_overlap", False))
        if tp_on or sizes.get(MODEL_AXIS, 1) > 1:
            out["tp_mode"] = "ring-decomposed" if tp_on else "gspmd-default"
        if tp_on and model is not None:
            dims = {k: getattr(model, k, None)
                    for k in ("max_len", "num_heads", "head_dim",
                              "num_layers")}
            if all(v is not None for v in dims.values()):
                from .collective_matmul import tp_wire_bytes_per_step

                vocab = (getattr(model, "vocab_size", None)
                         if getattr(model, "fused_head", False) else None)
                # batch from the mesh in hand, not config.train_batch_size
                # (whose data size comes from the config.mesh string and
                # can disagree with the mesh argument)
                wires = tp_wire_bytes_per_step(
                    batch=(config.per_device_train_batch_size
                           * sizes.get(DATA_AXIS, 1)),
                    seq=dims["max_len"],
                    embed=dims["num_heads"] * dims["head_dim"],
                    num_layers=dims["num_layers"],
                    n=sizes.get(MODEL_AXIS, 1),
                    vocab=vocab,
                    itemsize=2 if getattr(config, "bf16", False) else 4,
                )
                out["tp_wire_mb_stack"] = round(wires["stack"] / 1e6, 3)
                out["tp_wire_mb_head"] = round(wires["head"] / 1e6, 3)
                out["tp_wire_mb_per_step"] = round(
                    (wires["stack"] + wires["head"]) / 1e6, 3)
        pipe_size = sizes.get(PIPE_AXIS, 1)
        if (pipe_size > 1
                and str(getattr(config, "model", "")
                        ).startswith("gpt-pipe")):
            # r16 pipeline block: which schedule, how many microbatches
            # actually pipeline (the gcd clamp made visible), the
            # schedule model's bubble fraction at that geometry, and the
            # boundary-activation wire budget (r9 grad_wire convention)
            from .pipeline import (
                effective_pipe_microbatches, schedule_bubble_fraction,
            )

            sched = getattr(config, "pipe_schedule", "gpipe")
            requested = int(getattr(config, "pipe_microbatches", 1))
            data_size = sizes.get(DATA_AXIS, 1)
            # per-replica batch = train_batch_size / data = the
            # per-device figure; the clamp is THE shared helper, so
            # this logged value tracks the task's schedule exactly
            per_replica = max(
                getattr(config, "per_device_train_batch_size", 1), 1)
            eff = effective_pipe_microbatches(requested, per_replica)
            out["pipe_mode"] = sched
            out["pipe_stages"] = pipe_size
            out["pipe_microbatches"] = requested
            out["effective_microbatches"] = eff
            out["pipe_bubble_frac_static"] = round(
                schedule_bubble_fraction(sched, max(eff, 1), pipe_size), 4)
            if params is not None:
                wpe = nn.meta.unbox(params).get("wpe")
                if wpe is not None and getattr(wpe, "ndim", 0) == 2:
                    # best-effort like every other describe() figure: a
                    # mesh PipelineSchedule refuses (extra axes the task
                    # itself tolerates) must not crash the startup log
                    try:
                        from .schedule import PipelineSchedule

                        seq, embed = int(wpe.shape[0]), int(wpe.shape[1])
                        mb = max(per_replica // max(eff, 1), 1)
                        wire = PipelineSchedule(
                            mesh, sched, max(eff, 1),
                            tp=getattr(config, "tp_overlap", False),
                            ddp=getattr(config, "ddp_overlap", False),
                            fsdp=getattr(config, "fsdp_overlap", False),
                        ).wire_bytes_per_step(
                                mb, seq, embed,
                                itemsize=2 if getattr(config, "bf16",
                                                      False) else 4)
                        out["pipe_wire_mb_per_step"] = round(wire / 1e6, 3)
                    except Exception:  # noqa: BLE001 - logging only
                        pass
        if getattr(config, "fsdp", False):
            out["fsdp_mode"] = ("decomposed-prefetch"
                                if getattr(config, "fsdp_overlap", False)
                                else "gspmd-default")
        elif getattr(config, "zero1", False):
            out["fsdp_mode"] = "zero1"
        if getattr(config, "ddp_overlap", False):
            # which wire the DDP grad reduce rides, and how many bytes:
            # the run log must show the compression is actually active
            # (mirrors fsdp_mode above). Stacked-layer grads ride the
            # compressed per-layer path; everything outside the scanned
            # stack (embeddings, heads, final norms) keeps GSPMD's fp32
            # psum — both totals are reported so the split is visible.
            out["ddp_mode"] = "per-layer-overlapped-reduce"
            out["grad_comm"] = getattr(config, "grad_comm", "fp32")
            out["grad_error_feedback"] = bool(
                getattr(config, "grad_error_feedback", False))
            if params is not None:
                from .compress import wire_bytes_per_step
                from .stacking import LAYER_AXIS

                unboxed = nn.meta.unbox(params)
                n = sizes.get(DATA_AXIS, 1)
                flat, _ = jax.tree_util.tree_flatten_with_path(unboxed)

                def _in_stack(path):
                    return any(
                        getattr(p, "key", getattr(p, "name", None))
                        == LAYER_AXIS
                        for p in path
                    )

                stacked = [leaf for path, leaf in flat if _in_stack(path)]
                rest = [leaf for path, leaf in flat if not _in_stack(path)]
                # GSPMD fp32 ring all-reduce moves ~2x the data
                rest_bytes = sum(2 * 4 * leaf.size for leaf in rest)
                comp = wire_bytes_per_step(stacked, n, out["grad_comm"])
                base = wire_bytes_per_step(stacked, n, "fp32")
                out["grad_wire_mb_per_step"] = round(
                    (comp + rest_bytes) / 1e6, 3)
                out["grad_wire_mb_fp32"] = round(
                    (base + rest_bytes) / 1e6, 3)
        if getattr(config, "quant_compute", "off") != "off":
            # r17 low-precision compute block (the r9 grad_wire / r10
            # tp_wire accounting convention): mode, narrow paths,
            # master-weight semantics and — under tp — the quantized
            # ring wire next to the fp32 figure. Best-effort like every
            # other describe() figure.
            try:
                from .quant_schedule import describe_quant

                quant_block = describe_quant(config, model, mesh)
                if quant_block:
                    out["quant"] = quant_block
            except Exception:  # noqa: BLE001 - logging only
                out["quant"] = {
                    "mode": getattr(config, "quant_compute", "off")}
        # unified overlap summary (r11): one coherent block for a composed
        # run instead of three disjoint per-axis fragments. The legacy
        # per-axis keys above (fsdp_mode / ddp_mode / tp_mode /
        # grad_wire_* / tp_wire_*) remain as aliases — the bench-record
        # contract tests read them — and the block adds the combined
        # explicit-collective wire total.
        modes = {}
        if "fsdp_mode" in out:
            modes["fsdp"] = out["fsdp_mode"]
        if "ddp_mode" in out:
            modes["ddp"] = out["ddp_mode"]
        if "tp_mode" in out:
            modes["tp"] = out["tp_mode"]
        if "pipe_mode" in out:
            modes["pipe"] = out["pipe_mode"]
        if modes:
            # "decomposed" = an explicitly-scheduled axis: the three
            # scan contributions, plus the pipeline's fused slot
            # schedules (gpipe's masked loop is the baseline, like
            # gspmd-default is for the others)
            decomposed = [k for k, v in modes.items()
                          if v not in (None, "gspmd-default", "zero1",
                                       "gpipe")]
            wire_parts = {}
            if "grad_wire_mb_per_step" in out:
                wire_parts["grad_mb"] = out["grad_wire_mb_per_step"]
            if "tp_wire_mb_per_step" in out:
                wire_parts["tp_mb"] = out["tp_wire_mb_per_step"]
            if "pipe_wire_mb_per_step" in out:
                wire_parts["pipe_mb"] = out["pipe_wire_mb_per_step"]
            out["overlap"] = {
                "schedule": modes,
                "decomposed_axes": decomposed,
                "composed": len(decomposed) >= 2,
                **wire_parts,
                "wire_mb_per_step": round(sum(wire_parts.values()), 3),
            }
        if getattr(config, "fsdp", False) and params is not None:
            # read the PLACED shardings, not a re-derivation: under TP some
            # dims already carry the model axis and the chooser would lie
            # about them — the log must record where the data split
            # actually landed
            hist: dict[str, int] = {}
            for leaf in jax.tree.leaves(nn.meta.unbox(params)):
                spec = tuple(getattr(getattr(leaf, "sharding", None),
                                     "spec", ()) or ())
                key = "unsplit"
                for i, s in enumerate(spec):
                    names = (s,) if isinstance(s, str) else tuple(s or ())
                    if DATA_AXIS in names:
                        key = f"dim{i}"
                        break
                hist[key] = hist.get(key, 0) + 1
            out["fsdp_split_dims"] = dict(sorted(hist.items()))
    return out
