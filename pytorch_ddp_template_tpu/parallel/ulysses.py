"""Ulysses sequence parallelism: all-to-all attention over the ``seq`` axis.

The second context-parallel engine next to ``parallel/ring.py`` (the
reference has neither — SURVEY.md §5.7: "sequence length is not a
concept"). Where ring attention rotates kv chunks around the mesh with
``n`` ppermute hops, Ulysses (DeepSpeed-Ulysses) re-shards ONCE:

    activations arrive sequence-sharded   (B, S/n, H,   D)
    all-to-all  → head-sharded, full seq  (B, S,   H/n, D)
    ...dense attention per shard (any local impl: XLA, blockwise, flash)
    all-to-all  → back to sequence-sharded

Trade-off vs ring: 2 all-to-alls of the qkv/out tensors instead of n
neighbour exchanges — fewer, larger collectives (better at small n or
when ICI all-to-all bandwidth is strong), and the *local* attention is a
single dense call so the Pallas flash kernel applies unmodified. The cost:
heads must divide the seq-axis size, and peak memory holds the full
sequence per shard for the sharded heads.

Masking: after the first all-to-all each shard sees the FULL key sequence,
so a key-padding mask is just the global (B, S) mask — all-gathered over
``seq`` (bools: negligible bytes) and applied by the local attention.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .shard_map_compat import shard_map

from ..runtime.context import SEQ_AXIS


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    batch_axis: str | None = None,
    kv_mask: jax.Array | None = None,
    impl: str = "auto",
) -> jax.Array:
    """All-to-all sequence-parallel attention on global ``(B, S, H, D)``.

    Same calling convention as :func:`~.ring.ring_attention` (globally
    shaped arrays; sequence dim sharded over ``seq``, batch over ``data``),
    so the two context-parallel engines are drop-in interchangeable.
    Requires ``H % mesh.shape['seq'] == 0``.
    """
    from ..runtime.context import DATA_AXIS, MODEL_AXIS

    sizes = mesh.shape
    n = sizes.get(SEQ_AXIS, 1)
    if n == 1:  # no seq axis: plain local attention
        from ..ops.attention import attention

        mask = None if kv_mask is None else kv_mask[:, None, None, :]
        return attention(q, k, v, mask=mask, causal=causal, impl=impl)
    heads = q.shape[2]
    # under combined TP+SP the heads dim arrives split over `model`
    # (parallel/sharding.py heads->model rule); keep it split through the
    # all-to-all rather than paying a model-axis all-gather + redundant
    # per-shard attention (mirrors ring.py's heads_axis logic)
    model_size = sizes.get(MODEL_AXIS, 1)
    heads_axis = (
        MODEL_AXIS if model_size > 1 and heads % model_size == 0 else None
    )
    local_heads = heads // model_size if heads_axis else heads
    if local_heads % n:
        raise ValueError(
            f"ulysses needs per-model-shard heads ({local_heads}) divisible "
            f"by seq-axis size ({n}); use ring attention for this config"
        )
    if batch_axis is None:
        batch_axis = DATA_AXIS if sizes.get(DATA_AXIS, 1) > 1 else None
    spec = P(batch_axis, SEQ_AXIS, heads_axis, None)
    mask_spec = P(batch_axis, SEQ_AXIS)

    def local(q, k, v, m):
        # (B, S/n, H, D) -> (B, S, H/n, D): scatter heads, gather seq
        def scatter_heads(x):
            return lax.all_to_all(x, SEQ_AXIS, split_axis=2, concat_axis=1,
                                  tiled=True)

        ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        mask = None
        if m is not None:
            # every shard needs the FULL key mask once seq is gathered
            full = lax.all_gather(m, SEQ_AXIS, axis=1, tiled=True)
            mask = full[:, None, None, :]
        from ..ops.attention import attention

        out = attention(ql, kl, vl, mask=mask, causal=causal, impl=impl)
        # (B, S, H/n, D) -> (B, S/n, H, D): gather heads, scatter seq
        return lax.all_to_all(out, SEQ_AXIS, split_axis=1, concat_axis=2,
                              tiled=True)

    if kv_mask is None:
        fn = lambda q, k, v: local(q, k, v, None)
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
    fn = lambda q, k, v, m: local(q, k, v, m)
    return shard_map(fn, mesh=mesh,
                     in_specs=(spec, spec, spec, mask_spec),
                     out_specs=spec, check_vma=False)(
        q, k, v, kv_mask.astype(bool)
    )
