"""Decomposed tensor-parallel collective matmuls (``--tp_overlap``).

Under plain ``mesh=...,model:N`` the Megatron-style layout
(``parallel/sharding.py``: column-split fc1/qkv, row-split fc2/out —
Shoeybi et al., *Megatron-LM*) leaves the collectives to GSPMD, whose
default dataflow serialises every transformer block: "matmul → blocking
psum/all-gather → matmul". The ICI sits idle during the dots and the dots
wait on the wire. Wang et al. (*Overlap Communication with Dependent
Computation via Decomposition*, ASPLOS 2023) show the fix: decompose each
matmul+collective pair into ring steps whose single-hop ``ppermute``
transfers hide under the partial dots — the same rotate-after-consume
machinery ``parallel/ring.py`` uses for ring attention, applied to the TP
projections themselves.

Layout: between the collective matmuls, activations live **sequence-
sharded over the ``model`` axis** (Megatron-LM sequence parallelism).
Token-local ops (LayerNorm, residual adds, dropout, gelu) partition
trivially on that layout; attention runs at the GSPMD level with heads
sharded over ``model`` exactly as before. The two op shapes:

- **all-gather-matmul** (column-split fc1/qkv): the input ``(B, T, E)`` is
  seq-sharded; each device's weight shard holds a slice of the output
  features. Instead of gathering T up front, each ring step consumes the
  *held* activation chunk with a partial dot (writing that chunk's rows of
  the output) while the next chunk rides a single-hop ``ppermute``. The
  per-chunk dot is the same full-E contraction GSPMD's gathered matmul
  performs, so this path is **bit-exact** vs the default.
- **matmul-reduce-scatter** (row-split fc2/out): each device's partial
  product would need one blocking psum under GSPMD. Here an accumulator
  rotates around the ring: at step ``r`` device ``i`` adds its partial dot
  for seq chunk ``(i - r - 1) mod n`` to the incoming accumulator, so
  after ``n`` steps each device holds its own chunk *fully reduced* — the
  psum never materialises as one blocking collective, and the output is
  already in the seq-sharded layout the next column matmul consumes.
  (Numerics: the cross-device sum is associated in ring order instead of
  XLA's all-reduce order — last-ulp differences only.)

Both ops carry a hand-written ``jax.custom_vjp`` (the r8/r9 pattern:
``parallel/overlap.py``, ``parallel/compress.py``) so the backward
pipelines the *transposed* collectives the same way instead of autodiffing
into a serialised schedule: the column backward runs one ring that
simultaneously reduce-scatters ``dx`` (rotating accumulator) and rotates
the saved input chunks under the ``dw`` partial dots; the row backward
rotates the output cotangent once, writing ``dh`` rows and accumulating
``dw`` from the same held chunk. Weight cotangents are psum'd over
``data`` *inside the region* — the DDP gradient reduce for the TP shards
rides per-layer inside the backward, never as a trailing blocking wall.

In every ring body the ``ppermute`` operands are loop-carried state, never
a same-iteration dot product — the schedulability witness
``parallel/overlap.hlo_overlap_evidence`` checks for, and what the XLA
latency-hiding scheduler (``--xla_overlap_flags``) needs to run the hop
under the dots. ``bench.py BENCH_MODE=tp`` records that evidence plus
bit/last-ulp parity and the FLOPs-matched neutrality ratio.

Scope (refused with intent): ``--scan_layers`` transformer stacks on
``data×model`` meshes. ``seq``/``pipe``/``expert`` axes, MoE blocks and
``--ddp_overlap``/``--fsdp`` need in-region handling this v1 does not
implement. The divisibility contract (T, heads, mlp width by the model
size) fails at trace time with named numbers, not an opaque shard_map
shape error.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import DATA_AXIS, MODEL_AXIS
from .ring import ring_perm, ring_source
from .shard_map_compat import shard_map


def validate_tp_mesh(mesh: Mesh | None) -> Mesh:
    """Refuse meshes the decomposed-TP path cannot serve, with intent.

    The ring regions rotate over ``model`` and shard the batch dim over
    ``data`` only; a missing/size-1 ``model`` axis means there is nothing
    to decompose, and a live ``seq``/``pipe``/``expert`` axis would be
    silently unsharded by the region specs.
    """
    if mesh is None:
        raise ValueError(
            "--tp_overlap needs the device mesh threaded into the model "
            "(models/registry.py does this; pass mesh= when building "
            "directly)"
        )
    if mesh.shape.get(MODEL_AXIS, 1) <= 1:
        raise ValueError(
            "--tp_overlap decomposes the tensor-parallel collectives of a "
            f"'{MODEL_AXIS}' mesh axis, but the mesh is "
            f"{dict(mesh.shape)} (data-only / model:1) — there is no TP "
            "matmul to overlap; add model:N to --mesh or drop --tp_overlap"
        )
    extra = {name: size for name, size in mesh.shape.items()
             if name not in (DATA_AXIS, MODEL_AXIS) and size > 1}
    if extra:
        raise ValueError(
            f"--tp_overlap supports data+model meshes only; mesh also has "
            f"{extra} — drop the extra axes or drop --tp_overlap (a live "
            "pipe axis composes with TP through the pipelined entries "
            "only: --model gpt-pipe-* routes pipe×tp via "
            "parallel/pipeline.py, not these ring regions)"
        )
    return mesh


def _batch_axis(mesh: Mesh) -> str | None:
    return DATA_AXIS if mesh.shape.get(DATA_AXIS, 1) > 1 else None


def _check_divisible(what: str, value: int, n: int) -> None:
    if value % n:
        raise ValueError(
            f"--tp_overlap needs {what} ({value}) divisible by the model-"
            f"axis size ({n}) so the ring chunks are uniform; adjust the "
            "mesh or the model geometry"
        )


# -- local ring kernels (run INSIDE shard_map over the model axis) ---------
#
# Structure note: the ``jax.custom_vjp`` sits on the LOCAL (per-shard)
# function and ``shard_map`` wraps it from outside — not the other way
# round. Autodiff then differentiates *through* shard_map (whose jvp/
# transpose rules are solid, and whose transpose SUMS each cotangent over
# the mesh axes its input spec does not mention — the cross-replica
# weight-grad reduce comes free, per-layer, inside the backward), while
# the custom rules still pin the per-shard backward to hand-written ring
# schedules. The inverted nesting (custom_vjp around shard_map) leaks
# tracers on this jaxlib when the region body carries an inner lax.scan
# and the op runs inside flax's lifted nn.scan under jax.grad — the
# shard_map-internal operand reshape is captured across the custom_vjp
# boundary (observed UnexpectedTracerError; see tests/test_collective_
# matmul.py's scanned-grad case, which pins the working composition).
#
# Chunk-index conventions, shared with parallel/ring.py:
# * rotate-after-consume (all-gather side): the chunk held at step r
#   originated at shard ``ring_source(my, r, n) = (my - r) % n``; the
#   ppermute input is the loop-carried chunk, never this step's dot.
# * rotate-at-start (reduce-scatter side): the accumulator arriving at
#   device i at step r belongs to seq chunk ``(i - r - 1) % n``; after the
#   final step (r = n-1) that index is i — each device ends holding its
#   own chunk fully reduced. The ppermute input is the loop-carried
#   accumulator; the partial dot feeding the add is independent of it.

def _ring_size() -> int:
    from .ring import axis_size

    return axis_size(MODEL_AXIS)


def _dot2(a: jax.Array, w: jax.Array) -> jax.Array:
    """``(..., K) @ (K, F) -> (..., F)`` contracting the last dim."""
    return lax.dot_general(a, w, (((a.ndim - 1,), (0,)), ((), ())))


def _ag_matmul_local(chunk: jax.Array, wcat: jax.Array) -> jax.Array:
    """All-gather-matmul: seq chunk ``(B, t, E)`` x ``(E, F)`` -> full-seq
    ``(B, n*t, F)``, one output slice per ring step."""
    n = _ring_size()
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    b, t, _ = chunk.shape
    out = jnp.zeros((b, n * t, wcat.shape[-1]),
                    jnp.result_type(chunk.dtype, wcat.dtype))

    def body(carry, r):
        out, chunk = carry
        src = ring_source(my, r, n)
        # the dot consumes only the held chunk; the rotation below has no
        # data dependence on it — the hop hides under the next dot
        part = _dot2(chunk, wcat)
        out = lax.dynamic_update_slice_in_dim(out, part, src * t, axis=1)
        chunk = lax.ppermute(chunk, MODEL_AXIS, perm)
        return (out, chunk), None

    (out, _), _ = lax.scan(body, (out, chunk), jnp.arange(n))
    return out


def _mm_rs_local(h: jax.Array, w: jax.Array) -> jax.Array:
    """Matmul-reduce-scatter: full-seq ``(B, n*t, K)`` x ``(K, E)`` ->
    fully-reduced own seq chunk ``(B, t, E)``, partials reduced around the
    ring (the psum never exists as one blocking collective)."""
    n = _ring_size()
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    t = h.shape[1] // n
    acc = jnp.zeros((h.shape[0], t, w.shape[-1]),
                    jnp.result_type(h.dtype, w.dtype))

    def body(acc, r):
        # rotate FIRST: the ppermute consumes only the loop-carried
        # accumulator; this step's partial dot is independent of it
        acc = lax.ppermute(acc, MODEL_AXIS, perm)
        c = (my - r - 1) % n
        h_c = lax.dynamic_slice_in_dim(h, c * t, t, axis=1)
        return acc + _dot2(h_c, w), None

    acc, _ = lax.scan(body, acc, jnp.arange(n))
    return acc


# -- quantized ring kernels (--quant_compute int8|fp8, ops/quant.py) -------
#
# The decomposed rings are where quantized *compute* compounds with
# quantized *wire* (the ROADMAP's "quantize once per chunk and the ring
# rotates the narrow tensor"): each payload is quantized ONCE before the
# loop — the ppermute then carries the int8/fp8 tensor plus its f32
# per-row scales (4/E overhead per element), and the partial dots consume
# the narrow operands directly where the per-channel scales factor out of
# the contraction (forward column/row partials, backward dx/dh). Running
# accumulators (the fwd row reduce-scatter, the bwd column dx) cannot stay
# narrow across hops without per-hop requantization — they carry
# (q, scale) and dequant→add→requant each step (bounded by one quantum
# per hop; re-derived from fp32 masters next step, so nothing
# accumulates across steps). Contractions whose scale axis is the
# *batch* dims (the dw partials against a rotated chunk) dequantize
# first — a per-(b,t) scale cannot factor out of a (b,t) contraction;
# the wire stays narrow either way. --hlo_report's quant tripwire pins
# the hoisting: at least one narrow-ppermute loop body must contain NO
# convert-to-narrow (the once-per-chunk witness).

def _quantize_for_ring(x: jax.Array, quant: str, *, axes=-1,
                       grad: bool = False):
    from ..ops.quant import quantize_channel

    return quantize_channel(x, quant, axes=axes, grad=grad)


def _deq(q: jax.Array, s: jax.Array) -> jax.Array:
    from ..ops.quant import dequantize

    return dequantize(q, s)


def _col_math_q(x_c, kernels, biases, quant):
    """Quantized all-gather-matmul: the held chunk is quantized once
    (per-token-row over E), the weights once (per output channel over E);
    the ring rotates (q, scale) and every partial dot runs narrow."""
    from ..ops.quant import quant_dot

    n = _ring_size()
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    sizes = [math.prod(k.shape[1:]) for k in kernels]
    wcat = jnp.concatenate(
        [k.reshape(k.shape[0], -1) for k in kernels], axis=1)
    wq, ws = _quantize_for_ring(wcat, quant, axes=0)   # scale (1, Fl)
    xq, xs = _quantize_for_ring(x_c, quant, axes=-1)   # scale (B, t, 1)
    b, t, _ = x_c.shape
    out = jnp.zeros((b, n * t, wcat.shape[-1]),
                    jnp.result_type(x_c.dtype, wcat.dtype))

    def body(carry, r):
        out, xq, xs = carry
        src = ring_source(my, r, n)
        part = quant_dot(xq, xs, wq, ws, out_dtype=out.dtype)
        out = lax.dynamic_update_slice_in_dim(out, part, src * t, axis=1)
        # the hop carries the NARROW tensor + its scales — both are
        # loop-carried state, independent of this step's dot
        xq = lax.ppermute(xq, MODEL_AXIS, perm)
        xs = lax.ppermute(xs, MODEL_AXIS, perm)
        return (out, xq, xs), None

    (out, _, _), _ = lax.scan(body, (out, xq, xs), jnp.arange(n))
    outs, off = [], 0
    for k, bias, sz in zip(kernels, biases, sizes):
        y = out[..., off:off + sz] + bias.reshape(-1)
        outs.append(y.reshape(*y.shape[:-1], *k.shape[1:]))
        off += sz
    return tuple(outs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _col_local_q(x_c, kernels, biases, quant):
    return _col_math_q(x_c, kernels, biases, quant)


def _col_local_q_fwd(x_c, kernels, biases, quant):
    return _col_math_q(x_c, kernels, biases, quant), (x_c, kernels)


def _col_local_q_bwd(quant, res, gys):
    """Quantized mirror of ``_col_local_bwd``: the cotangent is quantized
    once (e5m2 under fp8) and its dx partials run narrow against the
    per-input-channel-scaled ``w^T``; the dx reduce-scatter accumulator
    rotates narrow with a per-hop requant; the saved input chunk rotates
    narrow and dequantizes only for its dw partial (a (b,t)-contraction
    no per-row scale factors out of). Weight/bias cotangents leave the
    region per-shard exactly as in the fp32 kernel."""
    from ..ops.quant import quant_dot

    x_c, kernels = res
    n = _ring_size()
    sizes = [math.prod(k.shape[1:]) for k in kernels]
    wcat = jnp.concatenate(
        [k.reshape(k.shape[0], -1) for k in kernels], axis=1)
    gcat = jnp.concatenate(
        [g.reshape(*g.shape[:2], -1) for g in gys], axis=-1)
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    t = x_c.shape[1]
    # hoisted quantizations: cotangent rows over Fl (grad dtype), w^T
    # input channels over Fl, the saved chunk rows over E (wire payload)
    gq, gs = _quantize_for_ring(gcat, quant, axes=-1, grad=True)
    wTq, wTs = _quantize_for_ring(
        jnp.swapaxes(wcat, 0, 1), quant, axes=0)    # (Fl, E), scale (1, E)
    cq, cs = _quantize_for_ring(x_c, quant, axes=-1)
    dxq, dxs = _quantize_for_ring(
        jnp.zeros(x_c.shape, jnp.float32), quant, axes=-1, grad=True)
    dw = jnp.zeros((wcat.shape[0], wcat.shape[1]), jnp.float32)

    def body(carry, r):
        dxq, dxs, cq, cs, dw = carry
        # dx: rotate-at-start of the NARROW accumulator, then
        # dequant → add this chunk's narrow partial → requant
        dxq = lax.ppermute(dxq, MODEL_AXIS, perm)
        dxs = lax.ppermute(dxs, MODEL_AXIS, perm)
        c = (my - r - 1) % n
        g_c = lax.dynamic_slice_in_dim(gq, c * t, t, axis=1)
        g_c_s = lax.dynamic_slice_in_dim(gs, c * t, t, axis=1)
        part = quant_dot(g_c, g_c_s, wTq, wTs, out_dtype=jnp.float32)
        dxq, dxs = _quantize_for_ring(_deq(dxq, dxs) + part, quant,
                                      axes=-1, grad=True)
        # dw: the narrow chunk rotates (rotate-after-consume); its dw
        # partial contracts (b, t), so it dequantizes for the dot
        src = ring_source(my, r, n)
        g_src = lax.dynamic_slice_in_dim(gcat, src * t, t, axis=1)
        dw = dw + lax.dot_general(
            _deq(cq, cs), g_src.astype(jnp.float32),
            (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)
        cq = lax.ppermute(cq, MODEL_AXIS, perm)
        cs = lax.ppermute(cs, MODEL_AXIS, perm)
        return (dxq, dxs, cq, cs, dw), None

    (dxq, dxs, _, _, dw), _ = lax.scan(
        body, (dxq, dxs, cq, cs, dw), jnp.arange(n))
    dx = _deq(dxq, dxs)
    dks, dbs, off = [], [], 0
    for k, g, sz in zip(kernels, gys, sizes):
        dks.append(dw[:, off:off + sz].reshape(k.shape).astype(k.dtype))
        dbs.append(jnp.sum(g.astype(jnp.float32), axis=(0, 1))
                   .astype(g.dtype))
        off += sz
    return dx.astype(x_c.dtype), tuple(dks), tuple(dbs)


_col_local_q.defvjp(_col_local_q_fwd, _col_local_q_bwd)


def _row_math_q(h_l, w_l, b, quant):
    """Quantized matmul-reduce-scatter: operands quantized once (rows
    over K, output channels over K), partial dots narrow, and the
    rotating accumulator carried as (q, scale) with a per-hop requant —
    the psum never exists, and neither does a wide wire."""
    from ..ops.quant import quant_dot

    n = _ring_size()
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    h2 = h_l.reshape(*h_l.shape[:2], -1)
    w2 = w_l.reshape(-1, w_l.shape[-1])
    t = h2.shape[1] // n
    hq, hs = _quantize_for_ring(h2, quant, axes=-1)   # (B, nt, 1)
    wq, ws = _quantize_for_ring(w2, quant, axes=0)    # (1, E)
    accq, accs = _quantize_for_ring(
        jnp.zeros((h2.shape[0], t, w2.shape[-1]), jnp.float32), quant,
        axes=-1)

    def body(carry, r):
        accq, accs = carry
        # rotate FIRST (narrow accumulator + scales are the only
        # loop-carried ppermute operands), then dequant→add→requant
        accq = lax.ppermute(accq, MODEL_AXIS, perm)
        accs = lax.ppermute(accs, MODEL_AXIS, perm)
        c = (my - r - 1) % n
        h_c = lax.dynamic_slice_in_dim(hq, c * t, t, axis=1)
        h_c_s = lax.dynamic_slice_in_dim(hs, c * t, t, axis=1)
        part = quant_dot(h_c, h_c_s, wq, ws, out_dtype=jnp.float32)
        accq, accs = _quantize_for_ring(_deq(accq, accs) + part, quant,
                                        axes=-1)
        return (accq, accs), None

    (accq, accs), _ = lax.scan(body, (accq, accs), jnp.arange(n))
    return (_deq(accq, accs) + b).astype(
        jnp.result_type(h_l.dtype, w_l.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _row_local_q(h_l, w_l, b, quant):
    return _row_math_q(h_l, w_l, b, quant)


def _row_local_q_fwd(h_l, w_l, b, quant):
    return _row_math_q(h_l, w_l, b, quant), (h_l, w_l)


def _row_local_q_bwd(quant, res, g):
    """Quantized mirror of ``_row_local_bwd``: the seq-sharded cotangent
    chunk is quantized once (e5m2 under fp8, per row over E) and rotates
    narrow; its dh partials run narrow against the per-K-channel-scaled
    ``w^T``; the dw partial dequantizes the held chunk (a (b,t)
    contraction). One rotation, two transposed collectives, narrow
    wire."""
    from ..ops.quant import quant_dot

    h_l, w_l = res
    n = _ring_size()
    h2 = h_l.reshape(*h_l.shape[:2], -1)
    w2 = w_l.reshape(-1, w_l.shape[-1])
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    t = g.shape[1]
    gq, gs = _quantize_for_ring(g.astype(jnp.float32), quant, axes=-1,
                                grad=True)
    wTq, wTs = _quantize_for_ring(
        jnp.swapaxes(w2, 0, 1), quant, axes=0)      # (E, K), scale (1, K)
    dh = jnp.zeros(h2.shape, jnp.float32)
    dw = jnp.zeros(w2.shape, jnp.float32)

    def body(carry, r):
        dh, gq, gs, dw = carry
        src = ring_source(my, r, n)
        part = quant_dot(gq, gs, wTq, wTs, out_dtype=jnp.float32)
        dh = lax.dynamic_update_slice_in_dim(dh, part, src * t, axis=1)
        h_src = lax.dynamic_slice_in_dim(h2, src * t, t, axis=1)
        dw = dw + lax.dot_general(
            h_src.astype(jnp.float32), _deq(gq, gs),
            (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)
        gq = lax.ppermute(gq, MODEL_AXIS, perm)
        gs = lax.ppermute(gs, MODEL_AXIS, perm)
        return (dh, gq, gs, dw), None

    (dh, _, _, dw), _ = lax.scan(body, (dh, gq, gs, dw), jnp.arange(n))
    db = jnp.sum(g.astype(jnp.float32), axis=(0, 1))
    return (dh.reshape(h_l.shape).astype(h_l.dtype),
            dw.reshape(w_l.shape).astype(w_l.dtype),
            db.astype(g.dtype))


_row_local_q.defvjp(_row_local_q_fwd, _row_local_q_bwd)


# -- column op: y_i = AG(x) @ w_i + b_i (fc1 / fused qkv) ------------------

def _col_math(x_c, kernels, biases):
    sizes = [math.prod(k.shape[1:]) for k in kernels]  # local widths
    wcat = jnp.concatenate(
        [k.reshape(k.shape[0], -1) for k in kernels], axis=1)
    out = _ag_matmul_local(x_c, wcat)
    outs, off = [], 0
    for k, b, sz in zip(kernels, biases, sizes):
        y = out[..., off:off + sz] + b.reshape(-1)
        outs.append(y.reshape(*y.shape[:-1], *k.shape[1:]))
        off += sz
    return tuple(outs)


@jax.custom_vjp
def _col_local(x_c, kernels, biases):
    return _col_math(x_c, kernels, biases)


def _col_local_fwd(x_c, kernels, biases):
    return _col_math(x_c, kernels, biases), (x_c, kernels)


def _col_local_bwd(res, gys):
    """One ring serving both transposed collectives: the ``dx``
    reduce-scatter accumulator rotates at start of each step while the
    saved input chunk rotates after its ``dw`` partial dot — every
    ppermute operand is loop-carried, so both hops can run under the
    step's dots. Weight/bias cotangents leave the region per-shard;
    shard_map's transpose sums them over the ``data`` axis (their specs
    do not mention it) — the cross-replica grad reduce, per layer,
    inside the backward."""
    x_c, kernels = res
    n = _ring_size()
    sizes = [math.prod(k.shape[1:]) for k in kernels]
    wcat = jnp.concatenate(
        [k.reshape(k.shape[0], -1) for k in kernels], axis=1)
    gcat = jnp.concatenate(
        [g.reshape(*g.shape[:2], -1) for g in gys], axis=-1)
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    t = x_c.shape[1]
    dx = jnp.zeros(x_c.shape, jnp.result_type(gcat.dtype, wcat.dtype))
    dw = jnp.zeros((wcat.shape[0], wcat.shape[1]), jnp.float32)

    def body(carry, r):
        dx, chunk, dw = carry
        # dx: reduce-scatter of gcat @ wcat^T — rotate-at-start
        dx = lax.ppermute(dx, MODEL_AXIS, perm)
        c = (my - r - 1) % n
        g_c = lax.dynamic_slice_in_dim(gcat, c * t, t, axis=1)
        dx = dx + lax.dot_general(
            g_c, wcat, (((g_c.ndim - 1,), (1,)), ((), ())))
        # dw: the saved input chunk rotates (rotate-after-consume) under
        # its partial dot with the matching cotangent slice
        src = ring_source(my, r, n)
        g_src = lax.dynamic_slice_in_dim(gcat, src * t, t, axis=1)
        dw = dw + lax.dot_general(
            chunk, g_src, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)
        chunk = lax.ppermute(chunk, MODEL_AXIS, perm)
        return (dx, chunk, dw), None

    (dx, _, dw), _ = lax.scan(body, (dx, x_c, dw), jnp.arange(n))
    dks, dbs, off = [], [], 0
    for k, g, sz in zip(kernels, gys, sizes):
        dks.append(dw[:, off:off + sz].reshape(k.shape).astype(k.dtype))
        dbs.append(jnp.sum(g.astype(jnp.float32), axis=(0, 1))
                   .astype(g.dtype))
        off += sz
    return dx.astype(x_c.dtype), tuple(dks), tuple(dbs)


_col_local.defvjp(_col_local_fwd, _col_local_bwd)


def _check_quant(quant: str) -> None:
    from ..ops.quant import QUANT_COMPUTE_MODES

    if quant not in QUANT_COMPUTE_MODES:
        raise ValueError(
            f"unknown quant_compute mode {quant!r}; expected one of "
            f"{QUANT_COMPUTE_MODES}")


def tp_column_dense(x: jax.Array, kernels: Sequence[jax.Array],
                    biases: Sequence[jax.Array], mesh: Mesh, *,
                    quant: str = "off") -> list[jax.Array]:
    """Ring-overlapped column-split dense layer(s).

    ``x``: ``(B, T, E)``, seq-sharded over ``model`` (dim 1). Each
    ``kernels[i]``: ``(E, F, *rest)`` with the first feature dim ``F``
    sharded over ``model``; ``biases[i]``: ``(F, *rest)``. Returns one
    ``(B, T, F, *rest)`` output per kernel, feature-sharded over ``model``.

    Passing several kernels fuses them into ONE ring: the activation
    rotates once and every projection's partial dot consumes the same held
    chunk (the fused-qkv path — a third of the separate-rings wire).

    ``quant`` (``--quant_compute``): ``int8``/``fp8`` runs the quantized
    ring kernel — the chunk is quantized once before the loop, the
    ppermute carries the narrow tensor + per-row scales, and the partial
    dots consume the narrow operands (``ops/quant.py``).
    """
    _check_quant(quant)
    n = mesh.shape[MODEL_AXIS]
    ba = _batch_axis(mesh)
    _check_divisible("sequence length", x.shape[1], n)
    for k in kernels:
        _check_divisible("feature width", k.shape[1], n)
    x_spec = P(ba, MODEL_AXIS, None)
    k_specs = tuple(P(None, MODEL_AXIS, *([None] * (k.ndim - 2)))
                    for k in kernels)
    b_specs = tuple(P(MODEL_AXIS, *([None] * (k.ndim - 2)))
                    for k in kernels)
    y_specs = tuple(P(ba, None, MODEL_AXIS, *([None] * (k.ndim - 2)))
                    for k in kernels)
    fn = (_col_local if quant == "off"
          else lambda x_c, ks, bs: _col_local_q(x_c, ks, bs, quant))
    out = shard_map(fn, mesh=mesh,
                    in_specs=(x_spec, k_specs, b_specs),
                    out_specs=y_specs, check_vma=False)(
        x, tuple(kernels), tuple(biases))
    return list(out)


def tp_column_dense_local(x_c: jax.Array, kernels: Sequence[jax.Array],
                          biases: Sequence[jax.Array], *,
                          quant: str = "off") -> list[jax.Array]:
    """Local (per-shard) form of :func:`tp_column_dense` for callers
    ALREADY inside a ``shard_map`` region that includes the ``model``
    axis (the ddp×tp composed schedule, ``parallel/schedule.py``): the
    same ring kernel, same custom_vjp backward, no second region. Inputs
    are the per-shard chunks — ``x_c`` the held seq chunk ``(B_l, t,
    E)``, kernels/biases the local feature shards."""
    _check_quant(quant)
    if quant == "off":
        return list(_col_local(x_c, tuple(kernels), tuple(biases)))
    return list(_col_local_q(x_c, tuple(kernels), tuple(biases), quant))


# -- row op: y = RS(h @ w) + b (fc2 / out projection) ----------------------

def _row_math(h_l, w_l, b):
    h2 = h_l.reshape(*h_l.shape[:2], -1)
    w2 = w_l.reshape(-1, w_l.shape[-1])
    # each device adds the replicated bias to its own reduced chunk
    # exactly once — the same "add after psum" the default path does
    return _mm_rs_local(h2, w2) + b


@jax.custom_vjp
def _row_local(h_l, w_l, b):
    return _row_math(h_l, w_l, b)


def _row_local_fwd(h_l, w_l, b):
    return _row_math(h_l, w_l, b), (h_l, w_l)


def _row_local_bwd(res, g):
    """One rotation of the seq-sharded output cotangent serves both
    transposed collectives: each step writes the held chunk's ``dh`` rows
    (all-gather-matmul against ``w^T``) and accumulates its ``dw``
    partial from the same chunk. ``db`` is the local sum only —
    shard_map's transpose sums it over BOTH mesh axes (its spec is
    ``P()``), and ``dw`` over ``data``."""
    h_l, w_l = res
    n = _ring_size()
    h2 = h_l.reshape(*h_l.shape[:2], -1)
    w2 = w_l.reshape(-1, w_l.shape[-1])
    my = lax.axis_index(MODEL_AXIS)
    perm = ring_perm(n)
    t = g.shape[1]
    dh = jnp.zeros(h2.shape, jnp.result_type(g.dtype, w2.dtype))
    dw = jnp.zeros(w2.shape, jnp.float32)

    def body(carry, r):
        dh, chunk, dw = carry
        src = ring_source(my, r, n)
        # dh rows for the held chunk: all-gather-matmul vs w^T
        part = lax.dot_general(
            chunk, w2, (((chunk.ndim - 1,), (1,)), ((), ())))
        dh = lax.dynamic_update_slice_in_dim(dh, part, src * t, axis=1)
        # dw partial from the SAME held chunk — one rotation, two
        # transposed collectives
        h_src = lax.dynamic_slice_in_dim(h2, src * t, t, axis=1)
        dw = dw + lax.dot_general(
            h_src, chunk, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)
        chunk = lax.ppermute(chunk, MODEL_AXIS, perm)
        return (dh, chunk, dw), None

    (dh, _, dw), _ = lax.scan(body, (dh, g, dw), jnp.arange(n))
    db = jnp.sum(g.astype(jnp.float32), axis=(0, 1))
    return (dh.reshape(h_l.shape).astype(h_l.dtype),
            dw.reshape(w_l.shape).astype(w_l.dtype),
            db.astype(g.dtype))


_row_local.defvjp(_row_local_fwd, _row_local_bwd)


def tp_row_dense(h: jax.Array, kernel: jax.Array, bias: jax.Array,
                 mesh: Mesh, *, quant: str = "off") -> jax.Array:
    """Ring-overlapped row-split dense layer.

    ``h``: ``(B, T, K, *rest)`` with the first contraction dim ``K``
    sharded over ``model``; ``kernel``: ``(K, *rest, E)`` row-sharded on
    ``K``; ``bias``: ``(E,)`` replicated. Returns ``(B, T, E)``
    seq-sharded over ``model`` — the partial products are reduced around
    the ring straight into the layout the next column matmul consumes.

    ``quant`` (``--quant_compute``): ``int8``/``fp8`` quantizes the
    operands once, runs the partial dots narrow, and rotates the
    accumulator as (q, scale) with a per-hop requant — the fused
    dequant→dot→requant form of the reduce-scatter (``ops/quant.py``).
    """
    _check_quant(quant)
    n = mesh.shape[MODEL_AXIS]
    ba = _batch_axis(mesh)
    _check_divisible("sequence length", h.shape[1], n)
    _check_divisible("contraction width", h.shape[2], n)
    if h.shape[2] != kernel.shape[0]:
        raise ValueError(
            f"tp_row_dense: input contraction dims {h.shape[2:]} do not "
            f"match kernel {kernel.shape[:-1]}"
        )
    h_spec = P(ba, None, MODEL_AXIS, *([None] * (h.ndim - 3)))
    k_spec = P(MODEL_AXIS, *([None] * (kernel.ndim - 1)))
    y_spec = P(ba, MODEL_AXIS, None)
    fn = (_row_local if quant == "off"
          else lambda h_, w_, b_: _row_local_q(h_, w_, b_, quant))
    return shard_map(fn, mesh=mesh,
                     in_specs=(h_spec, k_spec, P()),
                     out_specs=y_spec, check_vma=False)(h, kernel, bias)


def tp_row_dense_local(h_l: jax.Array, kernel: jax.Array,
                       bias: jax.Array, *,
                       quant: str = "off") -> jax.Array:
    """Local (per-shard) form of :func:`tp_row_dense` for callers ALREADY
    inside a ``shard_map`` region that includes the ``model`` axis (the
    ddp×tp composed schedule): ``h_l`` is the local contraction shard
    ``(B_l, T, K_l, *rest)``, ``kernel`` the local row shard, ``bias``
    replicated (added once per reduced chunk, as in the region form)."""
    _check_quant(quant)
    if quant == "off":
        return _row_local(h_l, kernel, bias)
    return _row_local_q(h_l, kernel, bias, quant)


# -- wire accounting -------------------------------------------------------

#: ring payload streams per block per step: forward = fused-qkv AG + fc1 AG
#: + out RS + fc2 RS (4); backward = column dx-accumulator + column input
#: rotation (x2 for qkv and fc1) + one cotangent rotation each for out and
#: fc2 (the fused dh/dw rings) = 6
STACK_RINGS_FWD = 4
STACK_RINGS_BWD = 6


def tp_wire_bytes_per_step(*, batch: int, seq: int, embed: int,
                           num_layers: int, n: int, vocab: int | None = None,
                           itemsize: float = 4,
                           quant: str = "off") -> dict[str, int]:
    """Estimated model-axis TP bytes on the wire per optimizer step.

    One ring op moves ``(n-1)/n`` of its full activation per model group:
    every participant sends ``n-1`` chunks of ``batch_local * t * embed``,
    which totals ``(n-1) * batch * seq * embed * itemsize`` across the job
    (independent of the data-axis size — the rings run once per data
    group on 1/data of the batch). The stack runs
    :data:`STACK_RINGS_FWD` + :data:`STACK_RINGS_BWD` such payload streams
    per layer; the LM head (``vocab`` set) rotates its
    (hidden, targets, online-stats) bundle forward and the
    (hidden, targets, cotangent, lse, dhidden-accumulator) bundle
    backward. Mirrors ``parallel/compress.wire_bytes_per_step``'s
    total-bytes convention: the fp32-vs-decomposed *ratios* are exact,
    absolute numbers are the upper bound with nothing kept local.

    Weight-grad psums over ``data`` are DDP bytes, not TP bytes, and are
    deliberately not counted here (``describe()`` reports them via the r9
    ``grad_wire_mb`` fields when compression is on).

    ``quant`` (``--quant_compute``): under ``int8``/``fp8`` every stack
    ring payload is the 1-byte narrow tensor plus its per-row f32 scales
    (one scale per ``embed`` elements — the 4/E overhead), fwd AND bwd
    (the accumulator streams requant before each hop). The LM head ring
    is not quantized in v1 and keeps its full-precision bundle.
    """
    stack_itemsize = itemsize
    if quant != "off":
        from ..ops.quant import quant_itemsize, quant_scale_overhead

        stack_itemsize = quant_itemsize(quant) + quant_scale_overhead(embed)
    per_ring = int((n - 1) * batch * seq * embed * stack_itemsize)
    stack = num_layers * (STACK_RINGS_FWD + STACK_RINGS_BWD) * per_ring
    head = 0
    if vocab is not None:
        tokens = (n - 1) * batch * seq
        # fwd bundle: hidden (E*itemsize) + targets (i32) + m/l/label/
        # best_v (f32) + best_i (i32) per token
        head += tokens * (embed * itemsize + 4 + 5 * 4)
        # bwd bundle: hidden + dhidden accumulator (f32) + targets + gy +
        # lse per token
        head += tokens * (embed * itemsize + embed * 4 + 3 * 4)
    return {"stack": int(stack), "head": int(head)}


def tp_decode_wire_bytes_per_step(*, slots: int, embed: int,
                                  num_layers: int, n: int,
                                  head: bool = True, itemsize: float = 4,
                                  quant: str = "off") -> int:
    """Model-axis TP bytes on the wire for ONE serving decode step —
    the forward-only slice of :func:`tp_wire_bytes_per_step` with slots
    as the ring's sequence axis (``serve/model.tp_decode_forward``):
    :data:`STACK_RINGS_FWD` payload streams per layer, each rotating
    ``(n-1)`` chunks of ``slots/n * embed`` per participant, plus the
    rotating-argmax head bundle (hidden chunk + running (best_v f32,
    best_i i32) per lane) when ``head`` is set. No backward streams —
    serving never takes a gradient, so the custom_vjp rings never run.

    ``quant``: under ``int8``/``fp8`` both the stack chunks and the
    head's hidden cargo ride the narrow wire (1-byte payload + per-row
    f32 scales, the 4/E overhead); the argmax stats stay wide — they
    are 8 bytes per lane against ``embed`` per lane of hidden.
    """
    stack_itemsize = itemsize
    if quant != "off":
        from ..ops.quant import quant_itemsize, quant_scale_overhead

        stack_itemsize = quant_itemsize(quant) + quant_scale_overhead(embed)
    lanes = (n - 1) * slots  # chunk-rows rotated across the job per ring
    total = num_layers * STACK_RINGS_FWD * int(lanes * embed * stack_itemsize)
    if head:
        total += int(lanes * (embed * stack_itemsize + 2 * 4))
    return int(total)


# -- HLO schedule evidence -------------------------------------------------

def hlo_tp_evidence(hlo_text: str) -> dict[str, Any]:
    """Ring-schedule witness for a compiled ``--tp_overlap`` program.

    Since r12 a thin delegate to ``obs/hlo_report.ring_evidence`` (the
    loop-body operand walk narrowed to ``collective-permute`` — the only
    collective the ring kernels issue on the hot path): a dot-carrying
    loop body whose ppermute operands reach only loop-carried state is a
    ring step the latency-hiding scheduler may run under the dots.
    Headline counts: ``ring_bodies`` (dot-carrying bodies with any
    ppermute) and ``independent_ring_bodies`` (all of whose ppermutes are
    compute-independent). Callers compare a forward-only lowering against
    the full train step to attribute bodies to fwd vs bwd (instruction
    text alone cannot).
    """
    from ..obs.hlo_report import ring_evidence

    return ring_evidence(hlo_text)
