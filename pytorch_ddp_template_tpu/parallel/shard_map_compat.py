"""``shard_map`` across jax versions.

Two spellings moved under us: the function lives at ``jax.shard_map`` on
current jax but ``jax.experimental.shard_map.shard_map`` before 0.5, and
the replication-check kwarg renamed ``check_rep`` → ``check_vma``. Callers
here use the modern spelling; this wrapper maps it onto whatever the
installed jax accepts.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # pre-0.5 jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    kwargs = {}
    if check_vma is not None:
        if "check_vma" in _PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _PARAMS:
            kwargs["check_rep"] = check_vma
        # neither spelling: the check cannot be disabled; proceed without
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
