"""Minimal expert parallelism over the ``expert`` mesh axis.

Companion to ``pipeline.py`` (VERDICT.md round-3 weak #7: every mesh axis
must have a mechanism or go): the reference has no MoE anywhere (its model
is a 2-layer MLP), so this is a capability-envelope proof, not a Switch
Transformer. The canonical expert-parallel dataflow, TPU-native:

- experts live sharded over the ``expert`` axis (one expert's FFN weights
  per rank, the way a stacked ``lax.scan`` MoE block would shard);
- each rank routes its local tokens (top-1 argmax gate), packs them into a
  fixed-capacity per-destination buffer (static shapes — XLA cannot
  compile data-dependent token counts), and ``lax.all_to_all`` ships the
  buffers so every rank receives exactly the tokens routed to *its*
  expert;
- the expert FFN runs on its tokens, a second ``all_to_all`` returns the
  results, and each rank unpacks into original token order.

Capacity semantics match production MoE: tokens beyond ``capacity`` per
(source rank → expert) pair are dropped (output 0 — the residual stream
carries them in a real model); the test constructs balanced routing where
nothing drops and equality with dense per-token expert application is
exact.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import EXPERT_AXIS
from .stacking import check_leading_axis, stack_params


def stack_expert_params(per_expert: list[Any], mesh: Mesh) -> Any:
    """Stack per-expert pytrees on a leading axis sharded over ``expert``."""
    return stack_params(per_expert, mesh, EXPERT_AXIS)


def expert_apply(
    expert_params: Any,
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    gate_w: jax.Array,
    x: jax.Array,
    mesh: Mesh,
    *,
    capacity: int | None = None,
    batch_axis: str | None = None,
) -> jax.Array:
    """Top-1-routed expert computation with all_to_all dispatch/combine.

    Args:
      expert_params: pytree with leading expert axis of size ``E`` (see
        :func:`stack_expert_params`), sharded over ``expert``.
      expert_fn: ``(params_of_one_expert, (n, d) tokens) -> (n, d)``.
      gate_w: ``(d, E)`` router weights, replicated.
      x: ``(T, d)`` tokens, sharded over ``(batch_axis?, expert)`` on the
        token dim. ``T`` must divide by the product of those axis sizes.
      capacity: max tokens any one source rank may send to one expert;
        default = each rank's local token count (top-1 then never drops).
      batch_axis: optional data-parallel mesh axis to ALSO split tokens
        over — each data group then dispatches only its own tokens to its
        (replicated-over-data) experts, instead of replicating the global
        token set and duplicating expert compute per data rank.

    Returns ``(T, d)``: per-token expert outputs (dropped tokens → 0).
    """
    n_experts = mesh.shape[EXPERT_AXIS]
    check_leading_axis(expert_params, n_experts, "expert axis")
    tokens, d = x.shape
    groups = n_experts * (mesh.shape[batch_axis] if batch_axis else 1)
    if tokens % groups:
        raise ValueError(f"token count {tokens} not divisible by {groups}")
    local = tokens // groups
    cap = local if capacity is None else capacity

    from .shard_map_compat import shard_map

    def per_device(params, x_local):
        params = jax.tree.map(lambda a: a[0], params)
        xl = x_local  # (local, d): this rank's tokens
        dest = jnp.argmax(xl @ gate_w, axis=-1)  # (local,) expert ids

        # pack: per destination expert, up to `cap` token slots. rank[t] =
        # position of token t within its destination's quota (capacity
        # overflow → parked in a dead slot and masked out).
        onehot = jax.nn.one_hot(dest, n_experts, dtype=jnp.int32)
        rank_in_dest = (jnp.cumsum(onehot, axis=0) - 1)[
            jnp.arange(local), dest
        ]
        keep = rank_in_dest < cap
        slot = jnp.where(keep, dest * cap + rank_in_dest, n_experts * cap)
        send = jnp.zeros((n_experts * cap + 1, d), xl.dtype).at[slot].set(xl)
        send = send[:-1].reshape(n_experts, cap, d)

        # dispatch: after all_to_all, axis 0 = source rank, rows = tokens
        # every source routed to MY expert
        recv = lax.all_to_all(send, EXPERT_AXIS, split_axis=0, concat_axis=0)
        out = expert_fn(params, recv.reshape(n_experts * cap, d))
        out = out.reshape(n_experts, cap, d)

        # combine: send results back to their source ranks, unpack
        back = lax.all_to_all(out, EXPERT_AXIS, split_axis=0, concat_axis=0)
        flat = jnp.concatenate(
            [back.reshape(n_experts * cap, d),
             jnp.zeros((1, d), xl.dtype)]  # dead slot for dropped tokens
        )
        y_local = flat[slot] * keep[:, None].astype(xl.dtype)
        return y_local

    in_param_spec = jax.tree.map(
        lambda a: P(EXPERT_AXIS, *([None] * (a.ndim - 1))), expert_params
    )
    token_spec = P((batch_axis, EXPERT_AXIS)) if batch_axis else P(EXPERT_AXIS)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(in_param_spec, token_spec),
        out_specs=token_spec,
        check_vma=False,
    )(expert_params, x)
