"""Shared helpers for leading-dim weight stacking.

Two families live here:

- per-*rank* stacking (``stack_params``/``check_leading_axis``): pipeline
  stages (one per ``pipe`` rank) and MoE experts (one per ``expert`` rank);
- per-*layer* stacking for scan-over-layers
  (``models/transformer.py scan_layers``): convert between the unrolled
  ``layer_{i}`` param layout and the scanned single-subtree layout whose
  leaves carry a leading ``(num_layers, ...)`` dim. These walk arbitrary
  pytrees (params AND their optimizer-state mirrors), preserve
  ``AxisMetadata`` boxes (the scan axis name is added/removed exactly the
  way ``nn.scan``'s ``metadata_params`` does it), and back both
  ``Task.init``'s scanned-equals-restacked-unrolled init and
  ``tools/convert_checkpoint.py``.
"""

from __future__ import annotations

import re
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_params(per_item: list[Any], mesh: Mesh, axis: str) -> Any:
    """Stack per-item pytrees on a new leading axis sharded over ``axis``
    — each rank of that mesh axis holds exactly one item's weights."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_item)
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        ),
        stacked,
    )


def check_leading_axis(params: Any, n: int, axis_desc: str) -> None:
    """Refuse a stacked-params/mesh-axis size mismatch: sharding >1 item
    per rank and slicing ``[0]`` would silently drop the rest."""
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(params)}
    if leading != {n}:
        raise ValueError(
            f"params leading axis {sorted(leading)} != {axis_desc} size "
            f"{n}; stack exactly one item per rank"
        )


# -- scan-over-layers layout conversion ---------------------------------

_LAYER_KEY = re.compile(r"^layer_(\d+)$")

#: default name of both the stacked subtree key and the logical axis of
#: its leading dim (matches models/transformer.py SCAN_LAYER_AXIS)
LAYER_AXIS = "layers"


def _is_box(x: Any) -> bool:
    return isinstance(x, nn.meta.AxisMetadata)


def _rebuild(tree: list | tuple, children: list) -> Any:
    """Reconstruct a sequence node with converted children — NamedTuples
    (live optax states like ``ScaleByAdamState``) need splat construction,
    plain lists/tuples take an iterable."""
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return type(tree)(*children)
    return type(tree)(children)


def stack_layer_tree(per_layer: list[Any], axis_name: str = LAYER_AXIS) -> Any:
    """Stack per-layer pytrees on a new leading dim. Boxed leaves
    (``nn.Partitioned``/``LogicallyPartitioned``) gain ``axis_name`` at
    position 0 through the box's own ``add_axis`` — byte-identical to what
    ``nn.scan(metadata_params={PARTITION_NAME: axis_name})`` produces."""

    def _stack(*xs):
        if _is_box(xs[0]):
            stacked = xs[0].replace_boxed(jnp.stack([b.unbox() for b in xs]))
            return stacked.add_axis(0, {nn.meta.PARTITION_NAME: axis_name})
        return jnp.stack(xs)

    return jax.tree.map(_stack, *per_layer, is_leaf=_is_box)


def unstack_layer_tree(stacked: Any, axis_name: str = LAYER_AXIS) -> list[Any]:
    """Split a stacked layer tree back into per-layer pytrees (inverse of
    :func:`stack_layer_tree`); the leading-dim size must agree on every
    leaf (a ragged stack means the tree was never layer-stacked)."""
    leaves = jax.tree.leaves(stacked, is_leaf=_is_box)
    sizes = {(leaf.unbox() if _is_box(leaf) else leaf).shape[0]
             for leaf in leaves}
    if len(sizes) != 1:
        raise ValueError(
            f"stacked layer tree has inconsistent leading dims {sorted(sizes)}"
        )
    (num_layers,) = sizes

    def _slice(i):
        def take(x):
            if _is_box(x):
                sliced = x.remove_axis(0, {nn.meta.PARTITION_NAME: axis_name})
                return sliced.replace_boxed(x.unbox()[i])
            return x[i]
        return jax.tree.map(take, stacked, is_leaf=_is_box)

    return [_slice(i) for i in range(num_layers)]


def _layer_dict_size(tree: Any) -> int | None:
    """``num_layers`` when ``tree`` is a dict of exactly ``layer_0 ..
    layer_{L-1}``, else None."""
    if not isinstance(tree, dict) or not tree:
        return None
    idx = []
    for k in tree:
        m = _LAYER_KEY.match(str(k))
        if m is None:
            return None
        idx.append(int(m.group(1)))
    return len(idx) if sorted(idx) == list(range(len(idx))) else None


def restack_layer_trees(tree: Any, axis_name: str = LAYER_AXIS) -> Any:
    """Unrolled → scanned: every ``{layer_0 .. layer_{L-1}}`` dict in the
    tree becomes ``{axis_name: stacked}``. Works on params and on
    optimizer-state mirrors (any pytree whose dicts use the layer keys)."""
    if _layer_dict_size(tree) is not None:
        n = _layer_dict_size(tree)
        per = [restack_layer_trees(tree[f"layer_{i}"], axis_name)
               for i in range(n)]
        return {axis_name: stack_layer_tree(per, axis_name)}
    if isinstance(tree, dict):
        return {k: restack_layer_trees(v, axis_name) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return _rebuild(tree, [restack_layer_trees(v, axis_name)
                               for v in tree])
    return tree


def unroll_layer_trees(tree: Any, axis_name: str = LAYER_AXIS) -> Any:
    """Scanned → unrolled: every ``{axis_name: stacked}`` dict becomes
    ``{layer_0 .. layer_{L-1}}`` (inverse of :func:`restack_layer_trees`)."""
    if isinstance(tree, dict):
        if set(tree) == {axis_name}:
            per = unstack_layer_tree(tree[axis_name], axis_name)
            return {f"layer_{i}": unroll_layer_trees(p, axis_name)
                    for i, p in enumerate(per)}
        return {k: unroll_layer_trees(v, axis_name) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return _rebuild(tree, [unroll_layer_trees(v, axis_name)
                               for v in tree])
    return tree


def detect_layer_layout(tree: Any, axis_name: str = LAYER_AXIS) -> str:
    """``"scanned"``, ``"unrolled"``, or ``"none"`` — which layer layout a
    (params or whole-state) pytree carries. Drives the fail-with-intent
    checks in ``train/engine.py`` and ``tools/convert_checkpoint.py``."""
    found = {"none"}

    def walk(t):
        if isinstance(t, dict):
            if set(t) == {axis_name}:
                found.add("scanned")
            if _layer_dict_size(t) is not None:
                found.add("unrolled")
            for v in t.values():
                walk(v)
        elif isinstance(t, (list, tuple)):
            for v in t:
                walk(v)

    walk(tree)
    if "scanned" in found and "unrolled" in found:
        raise ValueError("tree mixes scanned and unrolled layer layouts")
    for kind in ("scanned", "unrolled"):
        if kind in found:
            return kind
    return "none"


# -- pipelined stage-stack layout conversion (r16) -----------------------

#: subtree key under which the pipelined entries stack their block
#: weights ``(n_stages, layers_per_stage, ...)`` (models/gpt_pipe.py) —
#: params AND their optimizer-state mirrors carry the same key
PIPE_STACK_KEY = "blocks"


def _map_pipe_stacks(tree: Any, fn) -> Any:
    """Apply ``fn`` to every raw pipelined ``blocks`` subtree (params
    and optimizer mirrors alike; layer-form blocks and everything else
    pass through). ``fn`` receives the whole subtree."""
    if isinstance(tree, dict):
        return {
            k: (fn(v) if k == PIPE_STACK_KEY and not _is_layer_form(v)
                else _map_pipe_stacks(v, fn))
            for k, v in tree.items()
        }
    if isinstance(tree, (list, tuple)):
        return _rebuild(tree, [_map_pipe_stacks(v, fn) for v in tree])
    return tree


def _is_layer_form(v: Any) -> bool:
    """A blocks subtree already in one of the r7 layer layouts (the
    scanned ``{"layers": ...}`` dict or unrolled ``layer_{i}`` dicts) —
    as opposed to the raw pipelined ``(P, layers_per_stage, ...)``
    module tree."""
    return isinstance(v, dict) and (
        set(v) == {LAYER_AXIS} or _layer_dict_size(v) is not None)


def detect_pipe_stages(tree: Any) -> int | None:
    """Leading stage-axis size of the raw pipelined ``blocks`` subtrees,
    or None when the tree has none (a non-pipelined checkpoint, or one
    already converted to a layer layout). Mixed sizes refuse: they
    would mean a corrupt or hand-edited state."""
    sizes: set[int] = set()

    def walk(t):
        if isinstance(t, dict):
            for k, v in t.items():
                if k == PIPE_STACK_KEY and not _is_layer_form(v):
                    for leaf in jax.tree.leaves(v):
                        if getattr(leaf, "ndim", 0) >= 2:
                            sizes.add(int(leaf.shape[0]))
                else:
                    walk(v)
        elif isinstance(t, (list, tuple)):
            for v in t:
                walk(v)

    walk(tree)
    if len(sizes) > 1:
        raise ValueError(
            f"pipelined stage stacks disagree on the stage count "
            f"{sorted(sizes)} — refusing a corrupt state tree")
    return sizes.pop() if sizes else None


def repipe_stage_trees(tree: Any, n_stages_to: int) -> Any:
    """Restack every ``(P, layers_per_stage, ...)`` blocks subtree to
    ``n_stages_to`` stages — the reshape is lossless and involutive
    (layer order is row-major in both layouts, so stage boundaries move
    without reordering layers). Refuses a layer count the new stage
    count does not divide."""
    p_from = detect_pipe_stages(tree)
    if p_from is None:
        raise ValueError(
            "state holds no pipelined stage stack (no 'blocks' subtree "
            "with a leading stage axis) — nothing to repipe; pipelined "
            "layouts come from the gpt-pipe entries")

    def leaf(a):
        if getattr(a, "ndim", 0) < 2:
            return a
        total = a.shape[0] * a.shape[1]
        if total % n_stages_to:
            raise ValueError(
                f"cannot restack {total} layers onto {n_stages_to} "
                f"stages: {total} % {n_stages_to} != 0 — pick a stage "
                "count that divides the layer count")
        return a.reshape(n_stages_to, total // n_stages_to, *a.shape[2:])

    return _map_pipe_stacks(tree, lambda v: jax.tree.map(leaf, v))


def pipe_to_layer_stack(tree: Any) -> Any:
    """Pipelined → scanned: each raw blocks subtree's ``(P,
    layers_per_stage, ...)`` leading dims merge into one ``(num_layers,
    ...)`` stacked layer dim spelled in the r7 scanned layout
    (``{"layers": ...}``) — so ``detect_layer_layout`` recognises the
    result and ``unroll_layer_trees`` takes it the rest of the way to
    the unrolled form. Per-layer order preserved (row-major), bit-exact
    and involutive with :func:`layer_stack_to_pipe`."""
    if detect_pipe_stages(tree) is None:
        raise ValueError(
            "state holds no pipelined stage stack (no 'blocks' subtree "
            "with a leading stage axis) — nothing to convert")
    return _map_pipe_stacks(
        tree, lambda v: {LAYER_AXIS: jax.tree.map(
            lambda a: (a.reshape(a.shape[0] * a.shape[1], *a.shape[2:])
                       if getattr(a, "ndim", 0) >= 2 else a), v)})


def convert_tree_layout(tree: Any, to: str,
                        pipe_stages: int | None = None, *,
                        strict: bool = True) -> Any:
    """Restack a whole state pytree into layout ``to`` (``"scanned"`` /
    ``"unrolled"`` / ``"pipelined"``) — the converter core shared by
    ``tools/convert_checkpoint.py`` (offline) and the r18
    reshard-on-restore path inside ``CheckpointManager`` (in-process).

    ``strict=True`` (the CLI contract) refuses a no-op conversion and a
    tree with no layer stack at all; ``strict=False`` (the restore
    contract) returns such trees unchanged — a restore that needs no
    conversion is a success, not an error.
    """
    pipe_p = detect_pipe_stages(tree)
    have = "pipelined" if pipe_p else detect_layer_layout(tree)
    if to == "pipelined":
        if pipe_stages is None or pipe_stages < 2:
            raise ValueError(
                "--to pipelined needs --pipe_stages N (N >= 2): the "
                "stage count of the target pipe mesh axis")
        if have == "pipelined":
            if pipe_stages == pipe_p:
                if not strict:
                    return tree
                raise ValueError(
                    f"checkpoint is already stacked for {pipe_p} "
                    "pipeline stages; converting would be a no-op")
            return repipe_stage_trees(tree, pipe_stages)
        if have == "none":
            raise ValueError(
                "checkpoint holds no 'blocks' layer stack to split into "
                "pipeline stages — pipelined layouts serve the gpt-pipe "
                "entries only"
            )
        if have == "unrolled":
            tree = restack_layer_trees(tree)
        return layer_stack_to_pipe(tree, pipe_stages)
    if have == "pipelined":
        tree = pipe_to_layer_stack(tree)  # now the scanned spelling
        return tree if to == "scanned" else unroll_layer_trees(tree)
    if have == "none":
        if not strict:
            return tree  # MLP/ResNet states have no layer stack to move
        raise ValueError(
            "checkpoint holds no transformer layer stack (neither layer_{i} "
            "subtrees nor a stacked 'layers' subtree) — nothing to convert; "
            "--scan_layers applies to the transformer families only"
        )
    if have == to:
        if not strict:
            return tree
        raise ValueError(
            f"checkpoint is already in the {to} layout; converting would be "
            "a no-op — point --src at the other layout or skip the step"
        )
    return (restack_layer_trees(tree) if to == "scanned"
            else unroll_layer_trees(tree))


def layer_stack_to_pipe(tree: Any, n_stages: int) -> Any:
    """Scanned → pipelined: split each blocks subtree's ``{"layers":
    (num_layers, ...)}`` stack into the raw ``(n_stages,
    layers_per_stage, ...)`` stage stacking."""
    found = [False]

    def leaf(a):
        if getattr(a, "ndim", 0) < 1:
            return a
        if a.shape[0] % n_stages:
            raise ValueError(
                f"cannot split {a.shape[0]} layers onto {n_stages} "
                f"stages: {a.shape[0]} % {n_stages} != 0")
        return a.reshape(n_stages, a.shape[0] // n_stages, *a.shape[1:])

    def convert(v):
        if isinstance(v, dict) and set(v) == {LAYER_AXIS}:
            found[0] = True
            return jax.tree.map(leaf, v[LAYER_AXIS])
        return v

    def walk(t):
        if isinstance(t, dict):
            return {k: (convert(v) if k == PIPE_STACK_KEY else walk(v))
                    for k, v in t.items()}
        if isinstance(t, (list, tuple)):
            return _rebuild(t, [walk(v) for v in t])
        return t

    out = walk(tree)
    if not found[0]:
        raise ValueError(
            "state holds no scanned 'blocks' layer stack to split into "
            "stages (expected blocks = {\"layers\": stacked} — convert "
            "unrolled checkpoints to the scanned layout first, or pass "
            "a pipelined one directly)")
    return out
