"""Shared helper: stack per-rank pytrees on a leading mesh-axis-sharded
dim. Used by the pipeline (one stage per ``pipe`` rank) and expert (one
expert per ``expert`` rank) mechanisms."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_params(per_item: list[Any], mesh: Mesh, axis: str) -> Any:
    """Stack per-item pytrees on a new leading axis sharded over ``axis``
    — each rank of that mesh axis holds exactly one item's weights."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_item)
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        ),
        stacked,
    )


def check_leading_axis(params: Any, n: int, axis_desc: str) -> None:
    """Refuse a stacked-params/mesh-axis size mismatch: sharding >1 item
    per rank and slicing ``[0]`` would silently drop the rest."""
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(params)}
    if leading != {n}:
        raise ValueError(
            f"params leading axis {sorted(leading)} != {axis_desc} size "
            f"{n}; stack exactly one item per rank"
        )
