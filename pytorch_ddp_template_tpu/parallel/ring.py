"""Ring attention: exact attention over sequences sharded across chips.

The reference has no long-context capability at all (SURVEY.md §5.7: "no
attention anywhere; sequence length is not a concept"); for the TPU
framework long context is first-class. This is the context-parallel
engine: shard the sequence over the ``seq`` mesh axis and rotate kv
chunks around the ring with ``lax.ppermute`` while each chip accumulates
the online-softmax state for its local queries (Liu et al., Ring
Attention; the recurrence itself is shared with
``ops.attention.blockwise_attention``).

Why ppermute: neighbour exchange rides single ICI hops — bandwidth-optimal
on the TPU torus, and XLA overlaps each chunk's transfer with the previous
chunk's compute. After ``n_shards`` rotations every query has seen every
key exactly once: *exact* attention, O(seq/n) memory per chip, no
O(seq^2) anything.

Causal masking stays correct because chunk offsets are derived from the
ring step: at rotation ``r`` the chunk held by shard ``i`` originated at
shard ``(i - r) mod n``, so absolute kv positions are
``src * chunk_len + iota``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import Mesh, PartitionSpec as P

from .shard_map_compat import shard_map


def _axis_size(axis_name) -> int:
    """Static size of the named mesh axis (``lax.axis_size`` where it
    exists; pre-0.5 jax exposes it as the ``core.axis_frame`` value)."""
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis_name))
    from jax._src import core as _core

    frame = _core.axis_frame(axis_name)
    return int(frame if isinstance(frame, int) else frame.size)


#: public spelling — parallel/collective_matmul.py and ops/lm_head.py share
#: the ring machinery below; the underscore name stays for old importers
axis_size = _axis_size


def ring_perm(n: int) -> list[tuple[int, int]]:
    """The single-hop neighbour permutation ``i -> i+1 (mod n)`` every ring
    in this codebase rotates by (attention kv chunks here; activation
    chunks and reduce accumulators in ``parallel/collective_matmul.py``;
    the hidden/state bundle in ``ops/lm_head.py``). One hop per step rides
    one ICI link — bandwidth-optimal on the torus."""
    return [(i, (i + 1) % n) for i in range(n)]


def ring_source(my, r, n: int):
    """Origin shard of the chunk device ``my`` holds after ``r`` rotations
    of :func:`ring_perm` with the *rotate-after-consume* schedule (consume
    the held chunk, then ppermute it): at step ``r`` the chunk in hand
    started at ``(my - r) mod n``. Works on ints and traced arrays."""
    return (my - r) % n

from ..ops.attention import (
    online_softmax_finish,
    online_softmax_init,
    online_softmax_update,
)
from ..runtime.context import SEQ_AXIS


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Per-shard body: call INSIDE ``shard_map`` (or ``pjit``-of-shard_map).

    Args:
      q, k, v: local chunks ``(B, S_local, H, D)`` of the globally
        ``(B, S, H, D)``-shaped arrays, sequence-sharded over ``axis_name``.
      kv_mask: optional bool ``(B, S_local)`` validity of the *local keys*
        (padding support); it rotates around the ring with its kv chunk so
        each shard masks remote chunks correctly.
    Returns the local output chunk ``(B, S_local, H, D)``.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = d ** -0.5
    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # (B,H,S,D)
    perm = ring_perm(n)
    has_mask = kv_mask is not None

    def body(carry, r):
        state, kc, vc, mc = carry if has_mask else (*carry, None)
        src = ring_source(my, r, n)  # origin shard of the held chunk
        state = online_softmax_update(
            state,
            qf,
            kc.astype(jnp.float32).transpose(0, 2, 1, 3),
            vc.astype(jnp.float32).transpose(0, 2, 1, 3),
            q_offset=my * s_loc,
            k_offset=src * s_loc,
            causal=causal,
            mask_block=None if mc is None else mc[:, None, None, :],
        )
        # rotate AFTER consuming; XLA overlaps this ppermute with the next
        # iteration's compute (it has no data dependence on the update)
        if has_mask:
            kc, vc, mc = lax.ppermute((kc, vc, mc), axis_name, perm)
            return (state, kc, vc, mc), None
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        return (state, kc, vc), None

    state = online_softmax_init(b, h, s_loc, d)
    init = (state, k, v, kv_mask) if has_mask else (state, k, v)
    carry, _ = lax.scan(body, init, jnp.arange(n))
    return online_softmax_finish(carry[0], q.dtype).transpose(0, 2, 1, 3)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    batch_axis: str | None = None,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Ring attention over globally-shaped ``(B, S, H, D)`` arrays.

    Wraps :func:`ring_attention_local` in ``shard_map`` with the batch dim
    over ``batch_axis`` (defaults to the mesh's data axis if present) and
    the sequence dim over ``seq``. Safe to call under an enclosing ``jit``:
    GSPMD sees a manual region and stitches shardings at the boundary.

    ``kv_mask``: optional bool ``(B, S)`` key validity (True keeps) —
    padded batches; sharded over ``seq`` like the kv it masks.
    """
    from ..runtime.context import DATA_AXIS, MODEL_AXIS

    sizes = mesh.shape
    if batch_axis is None:
        batch_axis = DATA_AXIS if sizes.get(DATA_AXIS, 1) > 1 else None
    # under combined TP+SP the heads dim arrives split over `model`
    # (parallel/sharding.py heads->model rule); keep it split through the
    # ring rather than paying an all-gather + redundant per-shard compute
    model_size = sizes.get(MODEL_AXIS, 1)
    heads_axis = MODEL_AXIS if model_size > 1 and q.shape[2] % model_size == 0 else None
    spec = P(batch_axis, SEQ_AXIS, heads_axis, None)

    if kv_mask is None:
        fn = functools.partial(ring_attention_local, axis_name=SEQ_AXIS,
                               causal=causal)
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)

    def fn(q, k, v, m):
        return ring_attention_local(q, k, v, axis_name=SEQ_AXIS,
                                    causal=causal, kv_mask=m)

    mask_spec = P(batch_axis, SEQ_AXIS)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec, mask_spec),
                     out_specs=spec, check_vma=False)(
        q, k, v, kv_mask.astype(bool)
    )
