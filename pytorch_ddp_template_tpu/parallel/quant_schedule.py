"""Low-precision compute glue (``--quant_compute``): validation,
wire/FLOPs accounting and the ``describe()`` block for the quantized
paths — the r17 sibling of the r9 ``grad_wire_mb`` and r10
``tp_wire_mb`` conventions.

The compute itself lives in ``ops/quant.py`` (scaled narrow dots, the
Pallas fused kernel) and ``parallel/collective_matmul.py`` (the
quantized ring kernels); ``models/transformer.py`` routes the block
matmuls. This module is where the run's *evidence* comes from:

- :func:`quant_paths` — which execution paths actually run narrow for a
  given config (block-dense vs ring kernels), so the startup log names
  what is quantized rather than implying everything is;
- :func:`describe_quant`'s wire block — the model-axis ring wire under
  quantization (``collective_matmul.tp_wire_bytes_per_step(quant=)``:
  narrow payload + per-row scale overhead) next to the wide figure
  actually run (fp32, or bf16 under ``--bf16``) so the ratio is
  visible, plus the vs-fp32 ratio the acceptance bar reads (<= 0.5x);
- :func:`quant_flops_fraction` — the share of the step's matmul FLOPs
  running narrow (the block's four projections; attention itself and
  the LM head stay wide in v1), which the per-dtype peak tables in
  ``obs/attribution.py`` turn into an MFU headroom figure;
- :func:`describe_quant` — the startup-log block ``describe()`` embeds.

Import discipline: everything here is cheap host math over config
values; no tracing, safe at startup.
"""

from __future__ import annotations

from typing import Any

from ..runtime.context import DATA_AXIS, MODEL_AXIS

def quant_paths(config: Any) -> list[str]:
    """Which execution paths run narrow under this config: the block
    dense matmuls always (that is what the flag means), the ring
    collective matmuls when composed with ``--tp_overlap``. The LM head
    and attention stay wide in v1 (documented in README)."""
    if getattr(config, "quant_compute", "off") == "off":
        return []
    paths = ["block_dense" if not getattr(config, "tp_overlap", False)
             else "ring_collective_matmul"]
    return paths


def quant_flops_fraction(*, seq: int, embed: int, mlp_dim: int,
                         num_layers: int,
                         vocab: int | None = None) -> float:
    """Fraction of one token's matmul FLOPs that run narrow: the four
    block projections (qkv = 3·E², out = E², fc1/fc2 = 2·E·mlp — per
    layer) over those plus attention's 2·T·E score/value dots per layer
    and the (optional) vocab head AMORTISED over the stack — the head
    runs once per model, not once per layer, so it divides by
    ``num_layers``. The honest numerator for the per-dtype MFU headroom
    (``obs/attribution.py``), since attention and the head stay wide."""
    e = float(embed)
    narrow = 4.0 * e * e + 2.0 * e * float(mlp_dim)
    wide = 2.0 * float(seq) * e  # attention score + value dots per token
    if vocab:
        wide += e * float(vocab) / max(int(num_layers), 1)
    total = narrow + wide
    return narrow / total if total else 0.0


def describe_quant(config: Any, model: Any, mesh) -> dict[str, Any]:
    """The ``describe()`` quant block (r9/r10 wire-accounting
    convention): mode, narrow paths, master-weight semantics, the
    narrow-vs-wide FLOPs split, and — under ``--tp_overlap`` — the ring
    wire bytes next to the wide figure the run would otherwise send
    (keyed by its actual dtype: bf16 under ``--bf16``, else fp32) with
    the ratio the acceptance bar reads."""
    mode = getattr(config, "quant_compute", "off")
    if mode == "off":
        return {}
    out: dict[str, Any] = {
        "mode": mode,
        "paths": quant_paths(config),
        # the load-bearing semantic: the optimizer only ever sees fp32
        "master_weights": "fp32",
        "narrow_dtypes": ("s8" if mode == "int8"
                          else "e4m3(values)/e5m2(cotangents)"),
    }
    dims = {k: getattr(model, k, None)
            for k in ("max_len", "num_heads", "head_dim", "num_layers",
                      "mlp_dim")}
    if all(v is not None for v in dims.values()):
        embed = dims["num_heads"] * dims["head_dim"]
        vocab = (getattr(model, "vocab_size", None)
                 if getattr(model, "fused_head", False) else None)
        out["narrow_flops_frac"] = round(quant_flops_fraction(
            seq=dims["max_len"], embed=embed, mlp_dim=dims["mlp_dim"],
            num_layers=dims["num_layers"], vocab=vocab), 4)
        sizes = dict(mesh.shape)
        if getattr(config, "tp_overlap", False) and \
                sizes.get(MODEL_AXIS, 1) > 1:
            kw = dict(
                batch=(config.per_device_train_batch_size
                       * sizes.get(DATA_AXIS, 1)),
                seq=dims["max_len"], embed=embed,
                num_layers=dims["num_layers"], n=sizes[MODEL_AXIS],
                vocab=vocab,
                itemsize=2 if getattr(config, "bf16", False) else 4,
            )
            from .collective_matmul import tp_wire_bytes_per_step

            wide = tp_wire_bytes_per_step(**kw)
            narrow = tp_wire_bytes_per_step(quant=mode, **kw)
            wide_dtype = "bf16" if getattr(config, "bf16", False) else "fp32"
            out["tp_wire_mb_stack_quant"] = round(narrow["stack"] / 1e6, 3)
            out[f"tp_wire_mb_stack_{wide_dtype}"] = round(
                wide["stack"] / 1e6, 3)
            out["tp_wire_wide_dtype"] = wide_dtype
            out["tp_wire_stack_ratio"] = round(
                narrow["stack"] / max(wide["stack"], 1), 4)
            if wide_dtype != "fp32":
                # the acceptance bar (<= 0.5x) is defined vs fp32 — emit
                # that figure too so a bf16 run's ~0.52x vs-bf16 ratio
                # cannot be misread as failing the bar
                wide_fp32 = tp_wire_bytes_per_step(
                    **{**kw, "itemsize": 4})
                out["tp_wire_stack_ratio_vs_fp32"] = round(
                    narrow["stack"] / max(wide_fp32["stack"], 1), 4)
    return out
