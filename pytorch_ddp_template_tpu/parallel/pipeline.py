"""Minimal pipeline parallelism over the ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2b: "PP: No"), and
``PIPE_AXIS`` existed only as a name — this module gives the axis a real
mechanism so the mesh surface stays honest (VERDICT.md round-3 weak #7):
a GPipe-style fill/drain schedule for *homogeneous* stages, expressed the
TPU-native way — one SPMD program under ``shard_map``, microbatch
activations flowing stage-to-stage over ``lax.ppermute`` (ICI
neighbour hops on hardware), the schedule a ``lax.fori_loop`` over
``M + P - 1`` ticks with masked inactivity in the bubbles.

Scope (deliberate): equal-shaped stages (the transformer layer-stack
case), no 1F1B interleaving — a mechanism proof sized to the capability
envelope, not a Megatron replacement. It *is* trainable: the fill/drain
loop has a static trip count, so JAX rewrites the ``fori_loop`` to a
``scan`` at trace time (a While loop proper would not be reverse-mode
differentiable) and AD flows through the ``ppermute`` hops — ``jax.grad``
through ``pipeline_apply`` matches sequential-stage gradients to float32
tolerance (tests/test_pipeline.py). ``stage_params`` carries a stacked leading stage
axis sharded over ``pipe``, which is exactly how a layer-stacked
``lax.scan`` transformer would shard its weights for PP.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import DATA_AXIS, PIPE_AXIS
from .stacking import check_leading_axis, stack_params


def stack_stage_params(per_stage: list[Any], mesh: Mesh) -> Any:
    """Stack per-stage pytrees on a new leading axis and shard it over
    ``pipe`` — each pipeline rank holds only its own stage's weights."""
    return stack_params(per_stage, mesh, PIPE_AXIS)


def pipeline_apply(
    stage_params: Any,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    x: jax.Array,
    mesh: Mesh,
) -> jax.Array:
    """Run ``x`` through ``P`` pipelined stages; returns the final stage's
    outputs.

    Args:
      stage_params: pytree whose leaves have a leading stage axis of size
        ``P`` (see :func:`stack_stage_params`), sharded over ``pipe``.
      stage_fn: ``(params_of_one_stage, microbatch) -> microbatch`` with
        matching in/out shapes (homogeneous stages).
      x: ``(M, mb, ...)`` microbatched input, replicated over ``pipe``.
      mesh: mesh containing a ``pipe`` axis of size ``P``.

    Schedule: tick ``t`` runs microbatch ``t - p`` on stage ``p`` when
    ``0 <= t - p < M``; activations hop ``p → p+1`` between ticks via
    ``ppermute``. Total ``M + P - 1`` ticks — the textbook GPipe bubble.

    When the mesh also has a ``data`` axis (>1), the microbatch dim is
    sharded over it: each data replica pipelines its own batch shard
    (pipe × data composition with real DP speedup, not replicated
    compute). Requires ``mb % data_size == 0``.
    """
    n_stages = mesh.shape[PIPE_AXIS]
    n_micro = x.shape[0]
    check_leading_axis(stage_params, n_stages, "pipe axis")
    data_size = mesh.shape.get(DATA_AXIS, 1)
    if data_size > 1 and x.shape[1] % data_size:
        raise ValueError(
            f"pipeline microbatch size {x.shape[1]} not divisible by the "
            f"data axis size {data_size}; adjust batch size or the "
            "microbatch count"
        )

    from .shard_map_compat import shard_map

    def per_device(params, x_local):
        # shard_map hands each rank its stage slice with the (length-1)
        # stage axis intact; strip it
        params = jax.tree.map(lambda a: a[0], params)
        p = lax.axis_index(PIPE_AXIS)
        mb_shape = x_local.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            prev_out, ys = carry
            recv = lax.ppermute(prev_out, PIPE_AXIS, perm)
            feed = x_local[jnp.clip(t, 0, n_micro - 1)]
            my_in = jnp.where(p == 0, feed, recv)
            out = stage_fn(params, my_in)
            active = (t >= p) & (t - p < n_micro)
            out = jnp.where(active, out, jnp.zeros_like(out))
            # the last stage banks its finished microbatch each tick
            slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            collect = active & (p == n_stages - 1)
            ys = jnp.where(collect, lax.dynamic_update_index_in_dim(
                ys, out, slot, axis=0), ys)
            return out, ys

        init = (jnp.zeros(mb_shape, x_local.dtype),
                jnp.zeros((n_micro, *mb_shape), x_local.dtype))
        _, ys = lax.fori_loop(0, n_micro + n_stages - 1, tick, init)
        return ys[None]  # leading stage axis for the out_spec

    batch_spec = P(None, DATA_AXIS) if data_size > 1 else P()
    out_spec = P(PIPE_AXIS, None, DATA_AXIS) if data_size > 1 else P(PIPE_AXIS)
    in_param_spec = jax.tree.map(
        lambda a: P(PIPE_AXIS, *([None] * (a.ndim - 1))), stage_params
    )
    out = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(in_param_spec, batch_spec),
        out_specs=out_spec,
        check_vma=False,
    )(stage_params, x)
    # (P, M, mb, ...): every rank banked a buffer; only the last stage's
    # holds the pipeline output
    return out[-1]
