"""Pipeline parallelism over the ``pipe`` mesh axis: GPipe, 1F1B and
zero-bubble schedules for *homogeneous* stages.

Round 4 gave ``PIPE_AXIS`` its first mechanism — the GPipe fill/drain
loop (:func:`pipeline_apply`): one SPMD program under ``shard_map``,
microbatch activations hopping stage-to-stage over single-hop
``lax.ppermute`` (ICI neighbour hops on hardware), reverse-mode AD
through the loop supplying the backward. Its two structural costs are
textbook: the fill/drain bubble wastes ``(P-1)/(M+P-1)`` of the
schedule twice (forward and backward), and AD through the tick loop
saves every tick's residuals — O(M) activation residency per stage.

This round adds the two schedules that fix them, driven from explicit
**slot tables** (:func:`build_pipe_table`, host-side numpy — the same
tick/slot maps the Megatron-LM and zero-bubble papers draw):

- **1F1B** (Narayanan et al., SC'21): forward and backward interleave
  in ONE slot loop — each slot a stage runs exactly one unit of work
  (``lax.switch`` over {F, B, idle}; only the selected branch
  executes), with the per-microbatch loss computed on the last stage
  inside the schedule so backward can start while later microbatches
  are still filling. Backward recomputes each stage from its saved
  boundary activation (the r8-r11 recompute-from-boundary convention),
  so activation residency drops to the in-flight count — O(P), pinned
  by the live-range bench leg.
- **ZB** (Qi et al., ICLR'24, ZB-H1-flavoured): backward splits into
  the activation-grad pass **dx** (stays on the critical path — it is
  what unblocks the upstream stage; the zb slot loop carries only
  {F, BDX}, so its steady slots are cheaper than 1F1B's fused-B ones)
  and the weight-grad pass **dw** (no cross-stage consumer, so it is
  deferred wholesale: the dx pass stashes its taps per microbatch and
  the dw units drain *after* the loop as ONE batched wave over them —
  the drain region, doing exactly the work the bubble used to waste).
  The split shares one recompute: the dx pass
  captures each linear site's input activation and output gradient
  (the ZB paper's stashed (x, g) pairs, implemented as primal taps +
  zero-valued output probes whose cotangents ARE the output grads),
  and the dw wave is then pure products — no second recompute.

Schedule-owned state (send buffers, activation/grad/tap stores, grad
accumulators) rides the slot loop's carry; the two boundary ppermutes
are issued at the TOP of every slot, before the consuming compute, so
the p2p hops hide under the adjacent microbatch's work exactly the way
TP hides its ring ppermutes (compute-independent in the lowered body —
the ``--hlo_report`` pipe tripwire checks this).

Gradients are computed **in the primal pass** of a ``custom_vjp``
(:func:`pipelined_loss`): 1F1B/ZB interleave B into the forward
schedule, so by the time the loss scalar exists every gradient does
too; the vjp rule just scales the stashed grads by the incoming loss
cotangent. The undifferentiated path (eval) runs the cheap F-only
GPipe loop instead.

``stage_params`` carries a stacked leading stage axis sharded over
``pipe`` — each rank holds only its own stage — and when the mesh also
has a live ``data`` axis the microbatch dim shards over it (pipe×data
composition with real DP speedup).

Round 22 removes the last structural refusal: the 1F1B slot loop now
composes with ONE of tp / ddp / fsdp inside a stage
(``pipelined_loss(compose=...)``). The rule that makes it safe on real
hardware is *boundary hoisting*: every cross-replica collective issues
at the slot boundary, uniformly across stages, never inside a
divergent-predicate branch — idle stages contribute zeros (a psum of
zeros is correct and uniform, where a skipped psum is a deadlock) and
gather full-but-unused operands (a gather of valid shards is likewise
uniform). Concretely:

- ``compose="tp"`` drops the ``lax.switch`` entirely: the stage
  forward sweep (``PipeStageKernel.tp_fwd`` — Megatron column/row
  partition with replicated activations, two model all-reduces per
  layer) runs UNGUARDED every slot — on F slots it is the forward, on
  B slots it is the recompute-from-boundary, on idle slots it is
  lockstep waste the bubble already pays for. The backward's purely
  local vjp segments are guarded per-slot (``lax.cond`` on the traced
  work id — divergent but collective-free), and its per-layer
  activation + LN-grad all-reduces sit BETWEEN the guards at the slot
  body's top level. ``jax.vjp`` is only ever applied to local segment
  functions, never across a psum.
- ``compose="ddp"`` keeps the switch (its branches were always
  collective-free) and moves the gradient reduction from the post-loop
  psum into a per-slot ``compress._reduce_tree`` wave at the slot
  bottom — fp32 is exact by linearity of the sum; bf16/int8 fold the
  (slot, stage) indices into the rounding key.
- ``compose="fsdp"`` stores each stage's weights data-sharded along
  the same free-dim placement the trainer uses, all-gathers them at
  the slot top and psum-scatters the per-slot gradient back to shards
  at the slot bottom — the pipelined twin of the decomposed-scan
  layer-ahead gather.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from .stacking import check_leading_axis, stack_params

#: the user-facing schedule names (--pipe_schedule)
PIPE_SCHEDULES = ("gpipe", "1f1b", "zb")

#: slot work ids (the table's vocabulary). B is 1F1B's fused backward
#: (dx+dw in one unit); BDX/BDW are ZB's split halves.
WORK_IDLE, WORK_F, WORK_B, WORK_BDX, WORK_BDW = 0, 1, 2, 3, 4

#: relative slot costs in forward-units for the makespan/bubble model:
#: a block backward is ~2x its forward; recompute-from-boundary adds 1F
#: to whichever pass recomputes. 1F1B's fused B = recompute + dx + dw;
#: ZB's dx pass = recompute + dx (the dw products are deferred), its dw
#: pass = the products alone.
WORK_COSTS = {
    WORK_IDLE: 0.0,
    WORK_F: 1.0,
    WORK_B: 3.0,
    WORK_BDX: 2.0,
    WORK_BDW: 1.0,
}


def effective_pipe_microbatches(requested: int, per_replica: int) -> int:
    """THE microbatch gcd clamp — the single copy both the task
    (``models/gpt_pipe.effective_microbatches``) and the startup
    telemetry (``parallel/sharding.describe``) use, so the logged
    figure can never drift from the schedule's: ``gcd(requested,
    per-replica batch)``, with a batch smaller than one example per
    replica clamping to 1 (which the task then REFUSES — full
    serialisation)."""
    return math.gcd(max(int(requested), 1), max(int(per_replica), 1))


def stack_stage_params(per_stage: list[Any], mesh: Mesh) -> Any:
    """Stack per-stage pytrees on a new leading axis and shard it over
    ``pipe`` — each pipeline rank holds only its own stage's weights."""
    return stack_params(per_stage, mesh, PIPE_AXIS)


def pipeline_apply(
    stage_params: Any,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    x: jax.Array,
    mesh: Mesh,
) -> jax.Array:
    """Run ``x`` through ``P`` pipelined stages; returns the final stage's
    outputs.

    Args:
      stage_params: pytree whose leaves have a leading stage axis of size
        ``P`` (see :func:`stack_stage_params`), sharded over ``pipe``.
      stage_fn: ``(params_of_one_stage, microbatch) -> microbatch`` with
        matching in/out shapes (homogeneous stages).
      x: ``(M, mb, ...)`` microbatched input, replicated over ``pipe``.
      mesh: mesh containing a ``pipe`` axis of size ``P``.

    Schedule: tick ``t`` runs microbatch ``t - p`` on stage ``p`` when
    ``0 <= t - p < M``; activations hop ``p → p+1`` between ticks via
    ``ppermute``. Total ``M + P - 1`` ticks — the textbook GPipe bubble.

    When the mesh also has a ``data`` axis (>1), the microbatch dim is
    sharded over it: each data replica pipelines its own batch shard
    (pipe × data composition with real DP speedup, not replicated
    compute). Requires ``mb % data_size == 0``.
    """
    n_stages = mesh.shape[PIPE_AXIS]
    n_micro = x.shape[0]
    check_leading_axis(stage_params, n_stages, "pipe axis")
    data_size = mesh.shape.get(DATA_AXIS, 1)
    if data_size > 1 and x.shape[1] % data_size:
        raise ValueError(
            f"pipeline microbatch size {x.shape[1]} not divisible by the "
            f"data axis size {data_size}; adjust batch size or the "
            "microbatch count"
        )

    from .shard_map_compat import shard_map

    def per_device(params, x_local):
        # shard_map hands each rank its stage slice with the (length-1)
        # stage axis intact; strip it
        params = jax.tree.map(lambda a: a[0], params)
        p = lax.axis_index(PIPE_AXIS)
        mb_shape = x_local.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            prev_out, ys = carry
            recv = lax.ppermute(prev_out, PIPE_AXIS, perm)
            feed = x_local[jnp.clip(t, 0, n_micro - 1)]
            my_in = jnp.where(p == 0, feed, recv)
            out = stage_fn(params, my_in)
            active = (t >= p) & (t - p < n_micro)
            out = jnp.where(active, out, jnp.zeros_like(out))
            # the last stage banks its finished microbatch each tick
            slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            collect = active & (p == n_stages - 1)
            ys = jnp.where(collect, lax.dynamic_update_index_in_dim(
                ys, out, slot, axis=0), ys)
            return out, ys

        init = (jnp.zeros(mb_shape, x_local.dtype),
                jnp.zeros((n_micro, *mb_shape), x_local.dtype))
        _, ys = lax.fori_loop(0, n_micro + n_stages - 1, tick, init)
        return ys[None]  # leading stage axis for the out_spec

    batch_spec = P(None, DATA_AXIS) if data_size > 1 else P()
    out_spec = P(PIPE_AXIS, None, DATA_AXIS) if data_size > 1 else P(PIPE_AXIS)
    in_param_spec = jax.tree.map(
        lambda a: P(PIPE_AXIS, *([None] * (a.ndim - 1))), stage_params
    )
    out = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(in_param_spec, batch_spec),
        out_specs=out_spec,
        check_vma=False,
    )(stage_params, x)
    # (P, M, mb, ...): every rank banked a buffer; only the last stage's
    # holds the pipeline output
    return out[-1]


# -- slot tables ------------------------------------------------------------

@dataclasses.dataclass
class PipeTable:
    """A compiled-schedule description: per (slot, stage) the work unit,
    microbatch index and store-slot assignments, plus the two arrival
    maps (which microbatch's activation/grad lands on the wire at each
    slot and which store slot it belongs in). Host-side numpy — the
    driver ships each row into the scanned loop as static data."""

    kind: str
    n_micro: int
    n_stages: int
    work: np.ndarray       # (T, P) work ids
    mb: np.ndarray         # (T, P) microbatch index (0 when idle)
    aslot: np.ndarray      # (T, P) activation-store slot for F/B/BDX
    gslot: np.ndarray      # (T, P) incoming-grad store slot for B/BDX
    arr_f_mb: np.ndarray   # (T, P) mb arriving on the fwd wire (-1 none)
    arr_f_slot: np.ndarray
    arr_g_mb: np.ndarray   # (T, P) mb arriving on the bwd wire (-1 none)
    arr_g_slot: np.ndarray
    n_aslots: int          # activation residency (the 1F1B O(P) story)
    n_gslots: int
    wave_units_per_stage: int  # zb: deferred dw units each stage drains
    #                            in the batched post-loop wave (= M)

    @property
    def n_slots(self) -> int:
        return int(self.work.shape[0])

    @property
    def wave_count(self) -> int:
        return self.wave_units_per_stage * self.n_stages

    def pretty(self) -> str:
        names = {WORK_IDLE: ".", WORK_F: "F", WORK_B: "B",
                 WORK_BDX: "X", WORK_BDW: "W"}
        lines = []
        for p in range(self.n_stages):
            row = ["." if self.work[t, p] == WORK_IDLE
                   else f"{names[int(self.work[t, p])]}{self.mb[t, p]}"
                   for t in range(self.n_slots)]
            lines.append(f"s{p}: " + " ".join(f"{c:>3}" for c in row))
        return "\n".join(lines)


def _stage_sequences(kind: str, n_micro: int, n_stages: int):
    """Per-stage ordered work skeletons — the classic 1F1B shape: stage
    ``p`` warms up with ``min(M, P-1-p)`` forwards, then strictly
    alternates F/B, then drains its remaining backwards. ZB uses the
    same skeleton with B -> BDX (the dw halves are scheduled
    separately, into bubbles and the post-loop wave)."""
    M, P = n_micro, n_stages
    bk = WORK_B if kind == "1f1b" else WORK_BDX
    seqs = []
    for p in range(P):
        w = min(M, P - 1 - p)
        seq = [(WORK_F, i) for i in range(w)]
        for i in range(w, M):
            seq.append((WORK_F, i))
            seq.append((bk, i - w))
        for i in range(M - w, M):
            seq.append((bk, i))
        seqs.append(seq)
    return seqs


def build_pipe_table(kind: str, n_micro: int, n_stages: int) -> PipeTable:
    """Build + verify the slot table for ``kind`` in {"1f1b", "zb"}.

    Slot semantics: at the top of every slot each stage forwards its
    send buffers one hop (fwd activations down, bwd grads up), then
    executes at most ONE work unit. A unit produced at slot t is
    consumable downstream from slot t+1 (it lands in the consumer's
    store via the arrival maps, decoupling production cadence from
    consumption). ZB's slot loop carries only {F, BDX} — the dx chain
    IS the critical path — and every deferred dw unit drains in the
    post-loop wave (``wave_units_per_stage``), one batched product
    over the taps the dx pass emitted. (An earlier in-loop-dw variant
    threaded the tap store through the slot loop's carry/switch; on
    this host that threading cost more than the deferred products
    saved — the wave consumes the taps as write-once scan outputs
    instead.)
    """
    if kind not in ("1f1b", "zb"):
        raise ValueError(f"build_pipe_table: unknown schedule {kind!r}; "
                         "expected '1f1b' or 'zb' (gpipe has no slot "
                         "table — it is the masked fill/drain loop)")
    if n_micro < 1 or n_stages < 2:
        raise ValueError(
            f"build_pipe_table needs n_micro >= 1 and n_stages >= 2, got "
            f"M={n_micro}, P={n_stages}")
    M, P = n_micro, n_stages
    seqs = _stage_sequences(kind, M, P)
    ptr = [0] * P
    f_slot = np.full((P, M), -1, dtype=np.int64)
    b_slot = np.full((P, M), -1, dtype=np.int64)
    w_pending: list[list[int]] = [[] for _ in range(P)]

    rows_work, rows_mb = [], []
    t = 0
    while any(ptr[p] < len(seqs[p]) for p in range(P)):
        if t > 4 * (M + P) * (P + 2) + 16:  # defensive: never trip expected
            raise RuntimeError("pipe schedule did not converge")
        work_row, mb_row = [WORK_IDLE] * P, [0] * P
        for p in range(P):
            kindw, i = (seqs[p][ptr[p]] if ptr[p] < len(seqs[p])
                        else (WORK_IDLE, 0))
            ready = False
            if kindw == WORK_F:
                ready = p == 0 or 0 <= f_slot[p - 1, i] < t
            elif kindw in (WORK_B, WORK_BDX):
                ready = (0 <= f_slot[p, i] < t) and (
                    p == P - 1 or 0 <= b_slot[p + 1, i] < t)
            if ready:
                work_row[p], mb_row[p] = kindw, i
                ptr[p] += 1
                if kindw == WORK_F:
                    f_slot[p, i] = t
                else:
                    b_slot[p, i] = t
                    if kind == "zb":
                        w_pending[p].append(i)
        rows_work.append(work_row)
        rows_mb.append(mb_row)
        t += 1

    T = len(rows_work)
    work = np.array(rows_work, dtype=np.int32)
    mb = np.array(rows_mb, dtype=np.int32)

    arr_f_mb = np.full((T, P), -1, dtype=np.int32)
    arr_g_mb = np.full((T, P), -1, dtype=np.int32)
    for p in range(P):
        for i in range(M):
            if p + 1 < P:
                arr_f_mb[f_slot[p, i] + 1, p + 1] = i
            if p - 1 >= 0 and b_slot[p, i] + 1 < T:
                arr_g_mb[b_slot[p, i] + 1, p - 1] = i

    def alloc(intervals_per_stage):
        """Greedy interval packing per stage; SPMD-uniform slot count."""
        slots_map: dict[tuple[int, int], int] = {}
        n_total = 0
        for p, intervals in enumerate(intervals_per_stage):
            free: list[int] = []
            busy: list[tuple[int, int]] = []
            n_here = 0
            for start, end, key in sorted(intervals):
                busy.sort()
                while busy and busy[0][0] < start:
                    free.append(busy.pop(0)[1])
                if free:
                    s = min(free)
                    free.remove(s)
                else:
                    s, n_here = n_here, n_here + 1
                slots_map[key] = s
                busy.append((end, s))
            n_total = max(n_total, n_here)
        return slots_map, max(n_total, 1)

    a_ints = [[(f_slot[p, i] if p == 0 else f_slot[p - 1, i] + 1,
                b_slot[p, i], (p, i)) for i in range(M)]
              for p in range(P)]
    a_map, n_aslots = alloc(a_ints)
    g_ints = [[(b_slot[p + 1, i] + 1, b_slot[p, i], (p, i))
               for i in range(M)] if p < P - 1 else []
              for p in range(P)]
    g_map, n_gslots = alloc(g_ints)
    aslot = np.zeros((T, P), dtype=np.int32)
    gslot = np.zeros((T, P), dtype=np.int32)
    arr_f_slot = np.zeros((T, P), dtype=np.int32)
    arr_g_slot = np.zeros((T, P), dtype=np.int32)
    for tt in range(T):
        for p in range(P):
            i = int(mb[tt, p])
            w = int(work[tt, p])
            if w in (WORK_F, WORK_B, WORK_BDX):
                aslot[tt, p] = a_map[(p, i)]
            if w in (WORK_B, WORK_BDX) and p < P - 1:
                gslot[tt, p] = g_map[(p, i)]
            if arr_f_mb[tt, p] >= 0:
                arr_f_slot[tt, p] = a_map[(p, int(arr_f_mb[tt, p]))]
            if arr_g_mb[tt, p] >= 0:
                arr_g_slot[tt, p] = g_map[(p, int(arr_g_mb[tt, p]))]

    tab = PipeTable(kind, M, P, work, mb, aslot, gslot,
                    arr_f_mb, arr_f_slot, arr_g_mb, arr_g_slot,
                    n_aslots, n_gslots,
                    wave_units_per_stage=M if kind == "zb" else 0)
    _verify_table(tab, f_slot, b_slot)
    return tab


def _verify_table(tab: PipeTable, f_slot, b_slot) -> None:
    """Structural invariants — every unit exactly once, dependencies
    strictly ordered (zb's dw units all live in the wave)."""
    M, P = tab.n_micro, tab.n_stages
    for p in range(P):
        for i in range(M):
            assert 0 <= f_slot[p, i] < b_slot[p, i]
            if p > 0:
                assert f_slot[p - 1, i] < f_slot[p, i]
            if p < P - 1:
                assert b_slot[p + 1, i] < b_slot[p, i]
    counts: dict[tuple[int, int, int], int] = {}
    for t in range(tab.n_slots):
        for p in range(P):
            w = int(tab.work[t, p])
            if w != WORK_IDLE:
                key = (p, int(tab.mb[t, p]), w)
                counts[key] = counts.get(key, 0) + 1
    assert all(c == 1 for c in counts.values())


def schedule_makespan(kind: str, n_micro: int, n_stages: int,
                      costs: dict[int, float] | None = None
                      ) -> tuple[float, float]:
    """``(span, useful)`` of one schedule at (M, P) under the lockstep
    makespan model: each slot lasts as long as its most expensive
    branch across stages (a stage that finished early waits at the
    next slot's boundary ppermute); the zb dw wave extends the span by
    one stage's wave, running concurrently on every stage. Units are
    whatever ``costs`` is in (:data:`WORK_COSTS` forward-units by
    default; the bench legs pass measured per-branch times, making
    this the "static schedule model + measured device time" figure the
    r13 attribution convention asks for). GPipe's loop is masked, not
    slotted — its span is the closed form ``(M+P-1)`` fwd + bwd passes
    with every tick costing the full unit (masked ticks execute)."""
    M, P = n_micro, n_stages
    costs = {**WORK_COSTS, **(costs or {})}
    if kind == "gpipe":
        span = (M + P - 1) * (costs[WORK_F] + costs[WORK_B])
        useful = M * P * (costs[WORK_F] + costs[WORK_B])
        return span, useful
    tab = build_pipe_table(kind, M, P)
    span = sum(max(costs[int(w)] for w in row) for row in tab.work)
    useful = sum(costs[int(w)] for row in tab.work for w in row)
    if tab.wave_units_per_stage:
        span += tab.wave_units_per_stage * costs[WORK_BDW]
        useful += tab.wave_count * costs[WORK_BDW]
    return span, useful


def schedule_bubble_fraction(kind: str, n_micro: int, n_stages: int,
                             costs: dict[int, float] | None = None
                             ) -> float:
    """Static bubble fraction at (M, P): ``1 - useful / (P * span)``
    over the :func:`schedule_makespan` model. For gpipe this reduces
    to the textbook ``(P-1)/(M+P-1)`` (both passes bubble
    identically, so the fraction is pass-independent)."""
    M, P = n_micro, n_stages
    if P <= 1 or M < 1:
        return 0.0
    span, useful = schedule_makespan(kind, M, P, costs)
    return max(0.0, 1.0 - useful / (P * span))


# -- the fused 1F1B / ZB driver ---------------------------------------------

@dataclasses.dataclass
class PipeStageKernel:
    """The task's per-stage callbacks the fused schedules drive.

    All functions are pure; shapes are per-microbatch (``mb``-leading).

    - ``fwd(stage_w, x) -> y`` — one stage forward.
    - ``tail_fwd(tail_p, y, tgt, wt) -> (loss, hits)`` — the last
      stage's per-microbatch tail (final norm + head + loss sums).
    - ``tail_bwd(tail_p, y, tgt, wt) -> (gy, loss, hits, d_tail)`` —
      the tail's value-and-grad (seeds the backward).
    - ``fwd_tapped(stage_w, x, probes) -> (y, taps)`` (zb) — forward
      with zero-valued ``probes`` added at every linear-site output
      (their vjp cotangents ARE the per-site output grads) and the
      per-site input activations returned as ``taps``.
    - ``make_probes(stage_w, x_sds) -> probes`` (zb) — zero probes for
      a microbatch of shape/dtype ``x_sds``.
    - ``dw_from_taps(stage_w, taps, g_probes) -> gw`` (zb) — the
      deferred weight-grad products. Leaves of ``taps``/``g_probes``
      carry an extra LEADING axis which the implementation contracts:
      the post-loop wave feeds it the whole per-microbatch tap store
      (one entry per microbatch) in one batched product.
    - ``tp_fwd(stage_w, x, psum) -> (y, taps)`` (pipe×tp) — the phased
      stage forward over model-sharded weights: all cross-model sums
      go through the injected ``psum`` so the driver controls where
      they issue; ``taps`` are the per-layer boundary activations the
      backward sweep recomputes from.
    - ``tp_bwd(stage_w, taps, gy, psum, guard) -> (gx, gw)`` (pipe×tp)
      — the phased stage backward: every *local* vjp segment must be
      wrapped in the injected ``guard`` (the driver gates it on the
      slot's work id) and every cross-model sum must go through
      ``psum`` OUTSIDE any guard, so idle stages feed zeros into a
      uniform collective wave.
    """

    fwd: Callable
    tail_fwd: Callable
    tail_bwd: Callable
    fwd_tapped: Callable | None = None
    make_probes: Callable | None = None
    dw_from_taps: Callable | None = None
    tp_fwd: Callable | None = None
    tp_bwd: Callable | None = None


def _dyn(row, p):
    return lax.dynamic_index_in_dim(row, p, keepdims=False)


def _store_read(store, slot):
    return lax.dynamic_index_in_dim(store, slot, keepdims=False)


def _store_write(store, slot, value, pred):
    """Write ``value`` into ``store[slot]`` when ``pred`` — the no-write
    case rewrites the current slot contents (one slot of traffic, never
    the whole store)."""
    cur = _store_read(store, slot)
    return lax.dynamic_update_index_in_dim(
        store, jnp.where(pred, value, cur), slot, axis=0)


def pipelined_loss(table: PipeTable, kernel: PipeStageKernel,
                   stage_params: Any, tail_params: Any,
                   x_feed: jax.Array, tgt: jax.Array, wt: jax.Array,
                   mesh: Mesh, *, compose: str = "none",
                   stage_specs: Any | None = None,
                   grad_comm: str = "fp32",
                   comm_rng: jax.Array | None = None
                   ) -> tuple[jax.Array, jax.Array]:
    """Pipelined per-microbatch loss under ``table``'s fused schedule.

    Returns ``(loss_sum, hits_sum)`` — the per-microbatch tail sums
    accumulated across the schedule (psum'd over ``pipe`` and ``data``).

    Differentiation contract: the schedule interleaves backward into
    the forward pass, so under ``jax.grad`` the primal pass already
    produces every gradient; they ride the custom_vjp residuals and the
    backward rule scales them by the incoming loss cotangent. ``tgt``
    and ``wt`` are data, not parameters — their cotangents are symbolic
    zeros (the decomposed-scan extras convention).

    Without differentiation (eval) the cheap F-only fill/drain loop
    runs instead (:func:`pipeline_apply` + the per-microbatch tail),
    summing in the same per-microbatch order — the two paths agree.

    ``compose`` picks the in-stage parallelism riding the slot loop
    (1f1b only — see the module docstring for the boundary-hoisting
    invariant each mode obeys):

    - ``"none"``: pipe×data as before.
    - ``"tp"``: model-sharded stage weights via the kernel's phased
      ``tp_fwd``/``tp_bwd``; needs ``stage_specs`` (the per-leaf
      PartitionSpecs placing each stacked leaf over (pipe, model)).
    - ``"ddp"``: per-slot compressed gradient reduce over ``data``
      (``grad_comm`` in fp32/bf16/int8; lossy modes need ``comm_rng``).
    - ``"fsdp"``: data-sharded stage weights, slot-top all-gather +
      slot-bottom psum-scatter.
    """
    M, Pn = table.n_micro, table.n_stages
    kind = table.kind
    n_stages = mesh.shape[PIPE_AXIS]
    if n_stages != Pn:
        raise ValueError(
            f"pipelined_loss: table built for {Pn} stages but the mesh "
            f"pipe axis has {n_stages}")
    check_leading_axis(stage_params, Pn, "pipe axis")
    data_size = mesh.shape.get(DATA_AXIS, 1)
    if data_size > 1 and x_feed.shape[1] % data_size:
        raise ValueError(
            f"pipeline microbatch size {x_feed.shape[1]} not divisible "
            f"by the data axis size {data_size}; adjust batch size or "
            "the microbatch count")
    if kind == "zb" and (kernel.fwd_tapped is None
                         or kernel.dw_from_taps is None
                         or kernel.make_probes is None):
        raise ValueError("pipe_schedule=zb needs the tapped stage kernel "
                         "(fwd_tapped / make_probes / dw_from_taps)")
    if compose not in ("none", "tp", "ddp", "fsdp"):
        raise ValueError(
            f"pipelined_loss: unknown compose mode {compose!r}; expected "
            "'none', 'tp', 'ddp' or 'fsdp'")
    if compose != "none" and kind != "1f1b":
        raise ValueError(
            f"pipe×{compose} rides the 1f1b slot loop only: gpipe "
            "differentiates through the masked fill/drain loop (no slot "
            "boundary to hoist collectives to) and zb's bit-exact tapped "
            "twin has no decomposed form yet; use --pipe_schedule 1f1b")
    model_size = mesh.shape.get(MODEL_AXIS, 1)
    if compose == "tp":
        if kernel.tp_fwd is None or kernel.tp_bwd is None:
            raise ValueError(
                "pipe×tp needs the task's phased stage kernel "
                "(PipeStageKernel.tp_fwd / tp_bwd)")
        if model_size <= 1:
            raise ValueError(
                "compose='tp' needs a live model axis (>1) in the mesh")
        if stage_specs is None:
            raise ValueError(
                "compose='tp' needs stage_specs — the per-leaf "
                "PartitionSpecs placing each stacked block leaf over "
                "(pipe, model); see parallel.schedule.staged_tp_specs")
    if compose == "ddp":
        if grad_comm not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"pipelined_loss: unknown grad_comm {grad_comm!r}")
        if grad_comm != "fp32" and comm_rng is None:
            raise ValueError(
                "compose='ddp' with lossy grad_comm needs comm_rng (the "
                "per-step key the per-slot stochastic rounding folds "
                "slot and stage indices into)")

    rows = tuple(jnp.asarray(a) for a in
                 (table.work, table.mb, table.aslot,
                  table.gslot, table.arr_f_mb, table.arr_f_slot,
                  table.arr_g_mb, table.arr_g_slot))
    xs_rows = rows + (jnp.arange(table.n_slots, dtype=jnp.int32),)
    fwd_perm = [(i, (i + 1) % Pn) for i in range(Pn)]
    bwd_perm = [(i, (i - 1) % Pn) for i in range(Pn)]
    psum_axes = (PIPE_AXIS, DATA_AXIS) if data_size > 1 else (PIPE_AXIS,)

    from .shard_map_compat import shard_map
    from .overlap import UNSPLIT, _zero_cotangent

    if compose == "ddp":
        from .compress import CHUNK as _COMM_CHUNK, _reduce_tree
    if compose == "fsdp" and data_size > 1:
        from .sharding import fsdp_split_dim

        def _split_dim(a):
            # mirror the trainer-side fsdp placement chooser exactly
            # (same helper, same inputs): the leading stage dim is
            # pipe-blocked so only trailing dims are free; the largest
            # data-divisible free dim wins
            d = fsdp_split_dim(a.shape, data_size, prefer_dim=0,
                               free=[False] + [True] * (a.ndim - 1))
            return UNSPLIT if d is None else int(d)

        fsdp_dims = jax.tree.map(_split_dim, stage_params)
    else:
        fsdp_dims = jax.tree.map(lambda a: UNSPLIT, stage_params)
    # full (stage-local, data-unsplit) per-leaf shapes: fsdp branches
    # close over slot-gathered FULL weights, so their zero-gw default
    # must be full-shaped, not local-shard-shaped
    full_sds = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), stage_params)
    crng = comm_rng if comm_rng is not None else jax.random.PRNGKey(0)

    def per_device(stage_w, tail_p, x_local, tgt_local, wt_local, key):
        stage_w = jax.tree.map(lambda a: a[0], stage_w)
        p = lax.axis_index(PIPE_AXIS)
        last = p == Pn - 1
        mb_shape = x_local.shape[1:]
        dt = x_local.dtype

        if kind == "zb":
            probe0 = kernel.make_probes(
                stage_w, jax.ShapeDtypeStruct(mb_shape, dt))
            _, tap0 = jax.eval_shape(
                lambda x_, pr: kernel.fwd_tapped(stage_w, x_, pr),
                jax.ShapeDtypeStruct(mb_shape, dt), probe0)
            tap_pair0 = (tap0, probe0)
        else:
            tap_pair0 = ((), ())

        carry = {
            "y_send": jnp.zeros(mb_shape, dt),
            "g_send": jnp.zeros(mb_shape, dt),
            "acts": jnp.zeros((table.n_aslots, *mb_shape), dt),
            "gys": jnp.zeros((table.n_gslots, *mb_shape), dt),
            "dw": jax.tree.map(jnp.zeros_like, stage_w),
            "d_tail": jax.tree.map(jnp.zeros_like, tail_p),
            "dx": jnp.zeros((M, *mb_shape), dt),
            "loss": jnp.zeros((), jnp.float32),
            "hits": jnp.zeros((), jnp.float32),
            # zb: per-microbatch tap store (slot i = microbatch i; every
            # tap survives to the post-loop wave, so no slot reuse)
            "taps": jax.tree.map(
                lambda a: jnp.zeros((M, *a.shape), a.dtype), tap_pair0),
        }

        def zero_tail():
            return jax.tree.map(jnp.zeros_like, tail_p)

        def zero_gw():
            # fsdp: vjp runs against slot-gathered FULL weights
            if compose == "fsdp":
                return jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), full_sds)
            return jax.tree.map(jnp.zeros_like, stage_w)

        def deltas(y=None, g=None, gw=None, taps=None, dl=None, dh=None,
                   dtail=None):
            """Uniform switch-branch output: only small per-slot values
            plus the (mostly-zero) accumulator adds — the big stores
            stay OUT of the switch so branches never copy them."""
            return (
                y if y is not None else jnp.zeros(mb_shape, dt),
                g if g is not None else jnp.zeros(mb_shape, dt),
                gw if gw is not None else zero_gw(),
                taps if taps is not None else jax.tree.map(
                    lambda a: jnp.zeros(a.shape, a.dtype), tap_pair0),
                dl if dl is not None else jnp.zeros((), jnp.float32),
                dh if dh is not None else jnp.zeros((), jnp.float32),
                dtail if dtail is not None else zero_tail(),
            )

        def slot(c, xs):
            t_idx = xs[-1]
            work, mbi, asl, gsl, afm, afs, agm, ags = [
                _dyn(r, p) for r in xs[:-1]]
            # boundary hops FIRST, consuming last slot's send buffers:
            # dataflow-independent of this slot's compute by
            # construction, so the latency-hiding scheduler may run the
            # p2p under the adjacent microbatch's work
            with jax.named_scope("pipe_send"):
                recv_y = lax.ppermute(c["y_send"], PIPE_AXIS, fwd_perm)
                recv_g = lax.ppermute(c["g_send"], PIPE_AXIS, bwd_perm)
            acts = _store_write(c["acts"], afs, recv_y, afm >= 0)
            gys = _store_write(c["gys"], ags, recv_g, agm >= 0)
            mbc = jnp.clip(mbi, 0, M - 1)
            if compose == "fsdp" and data_size > 1:
                # slot-boundary gather wave, UNIFORM across stages: the
                # table is static but the work id is a traced predicate,
                # so a gather inside the switch would be divergent. Idle
                # stages gather too — the operand just goes unused.
                with jax.named_scope("pipe_fsdp_gather"):
                    w_slot = jax.tree.map(
                        lambda a, d: a if d == UNSPLIT else lax.all_gather(
                            a, DATA_AXIS, axis=d - 1, tiled=True),
                        stage_w, fsdp_dims)
            else:
                w_slot = stage_w

            def boundary_x():
                return jnp.where(p == 0, x_local[mbc],
                                 _store_read(acts, asl))

            def tail_or_recv(y):
                def w_tail(_):
                    return kernel.tail_bwd(tail_p, y, tgt_local[mbc],
                                           wt_local[mbc])

                def wo_tail(_):
                    return (_store_read(gys, gsl).astype(dt),
                            jnp.zeros((), jnp.float32),
                            jnp.zeros((), jnp.float32), zero_tail())

                return lax.cond(last, w_tail, wo_tail, None)

            is_f = work == WORK_F
            is_b = (work == WORK_B) | (work == WORK_BDX)

            if compose == "tp":
                def psum_model(v):
                    return lax.psum(v, MODEL_AXIS)

                def guard(fn):
                    # gate a purely-LOCAL segment on the slot's work id:
                    # divergent predicate, but collective-free by the
                    # kernel contract, so divergence is harmless
                    sds = jax.eval_shape(fn)
                    return lax.cond(
                        is_b, fn,
                        lambda: jax.tree.map(
                            lambda s: jnp.zeros(s.shape, s.dtype), sds))

                # phased TP slot body: NO switch. The forward sweep runs
                # unguarded every slot (F slots: the forward; B slots:
                # the recompute-from-boundary; idle slots: lockstep
                # waste the bubble already pays for), so its per-layer
                # model all-reduces issue uniformly across stages. The
                # tail and the backward's local vjp segments are
                # guarded; the backward's activation/LN-grad all-reduces
                # sit BETWEEN the guards at the slot body's top level,
                # fed zeros by idle stages.
                xb = boundary_x()
                with jax.named_scope("pipe_tp_fwd"):
                    y_new, taps_tp = kernel.tp_fwd(stage_w, xb, psum_model)
                gy, dl, dh, dtail_add = guard(lambda: tail_or_recv(y_new))
                with jax.named_scope("pipe_tp_bwd"):
                    g_new, gw_add = kernel.tp_bwd(
                        stage_w, taps_tp, gy, psum_model, guard)
                tap_new = jax.tree.map(
                    lambda a: jnp.zeros(a.shape, a.dtype), tap_pair0)
            else:
                def br_idle():
                    return deltas()

                def br_f():
                    with jax.named_scope("pipe_stage_fwd"):
                        y = kernel.fwd(w_slot, boundary_x())
                    return deltas(y=y)

                def br_b():  # 1f1b: fused bwd, recompute from boundary
                    x = boundary_x()
                    with jax.named_scope("pipe_stage_bwd"):
                        y, pull = jax.vjp(
                            lambda w_, x_: kernel.fwd(w_, x_), w_slot, x)
                        gy, dl, dh, dtail = tail_or_recv(y)
                        gw, gx = pull(gy)
                    return deltas(g=gx, gw=gw, dl=dl, dh=dh, dtail=dtail)

                def br_bdx():  # zb: dx only; (x, g) taps stashed for dw
                    x = boundary_x()
                    pr0 = jax.tree.map(
                        lambda a: jnp.zeros(a.shape, a.dtype), probe0)
                    with jax.named_scope("pipe_stage_dx"):
                        (y, taps), pull = jax.vjp(
                            lambda x_, pr: kernel.fwd_tapped(
                                stage_w, x_, pr),
                            x, pr0)
                        gy, dl, dh, dtail = tail_or_recv(y)
                        gx, g_probes = pull(
                            (gy, jax.tree.map(jnp.zeros_like, taps)))
                    return deltas(g=gx, taps=(taps, g_probes), dl=dl,
                                  dh=dh, dtail=dtail)

                if kind == "zb":
                    branches = [br_idle, br_f, br_idle, br_bdx]
                else:
                    branches = [br_idle, br_f, br_b, br_idle]
                y_new, g_new, gw_add, tap_new, dl, dh, dtail_add = (
                    lax.switch(work, branches))

            c2 = dict(c)
            c2["acts"] = _store_write(acts, asl, boundary_x(), is_f)
            c2["gys"] = gys
            c2["y_send"] = jnp.where(is_f, y_new, c["y_send"])
            c2["g_send"] = jnp.where(is_b, g_new, c["g_send"])
            if compose == "ddp" and data_size > 1:
                # slot-boundary reduce wave: every stage reduces its
                # per-slot gw over data UNIFORMLY — idle stages feed
                # zeros (a psum of zeros is correct and uniform, where
                # a skipped psum is a deadlock). fp32 is exact by
                # linearity; lossy modes fold (slot, stage) into the
                # rounding key.
                key_t = None
                if grad_comm != "fp32":
                    key_t = jax.random.fold_in(
                        jax.random.fold_in(key, t_idx), p)
                with jax.named_scope("pipe_ddp_reduce"):
                    gw_red, _ = _reduce_tree(
                        gw_add, None, key_t, grad_comm, DATA_AXIS,
                        data_size, _COMM_CHUNK)
                c2["dw"] = jax.tree.map(jnp.add, c["dw"], gw_red)
            elif compose == "fsdp" and data_size > 1:
                # slot-boundary scatter wave: the full per-slot gw
                # reduces back to each rank's shard (psum_scatter on
                # split leaves, plain psum on unsplit ones — the same
                # wave shape on every stage, every slot)
                with jax.named_scope("pipe_fsdp_scatter"):
                    gw_loc = jax.tree.map(
                        lambda g, d: (lax.psum(g, DATA_AXIS)
                                      if d == UNSPLIT else
                                      lax.psum_scatter(
                                          g, DATA_AXIS,
                                          scatter_dimension=d - 1,
                                          tiled=True)),
                        gw_add, fsdp_dims)
                c2["dw"] = jax.tree.map(jnp.add, c["dw"], gw_loc)
            else:
                c2["dw"] = jax.tree.map(jnp.add, c["dw"], gw_add)
            c2["d_tail"] = jax.tree.map(jnp.add, c["d_tail"], dtail_add)
            c2["dx"] = _store_write(c["dx"], mbc, g_new, is_b & (p == 0))
            c2["loss"] = c["loss"] + dl
            c2["hits"] = c["hits"] + dh
            if kind == "zb":
                c2["taps"] = jax.tree.map(
                    lambda s, v: _store_write(s, mbc, v,
                                              work == WORK_BDX),
                    c["taps"], tap_new)
            return c2, None

        c, _ = lax.scan(slot, carry, xs_rows)
        dw = c["dw"]
        if kind == "zb" and table.wave_units_per_stage:
            # the post-loop dw wave: ONE batched product over every
            # microbatch's stashed taps (leading axis = microbatch; the
            # dx chain was the critical path, this is the deferred
            # remainder — the drain region, doing the work the bubble
            # used to waste)
            with jax.named_scope("pipe_dw_wave"):
                gw = kernel.dw_from_taps(stage_w, c["taps"][0],
                                         c["taps"][1])
            dw = jax.tree.map(jnp.add, dw, gw)
        loss = lax.psum(c["loss"], psum_axes)
        hits = lax.psum(c["hits"], psum_axes)
        if data_size > 1 and compose not in ("ddp", "fsdp"):
            # ddp reduced per-slot, fsdp scattered per-slot — both
            # already carry the cross-data sum
            dw = jax.tree.map(lambda a: lax.psum(a, DATA_AXIS), dw)
        d_tail = jax.tree.map(lambda a: lax.psum(a, psum_axes),
                              c["d_tail"])
        return (loss, hits, jax.tree.map(lambda a: a[None], dw), d_tail,
                c["dx"][None])

    batch_spec = P(None, DATA_AXIS) if data_size > 1 else P()
    if compose == "tp":
        pspec = stage_specs
    elif compose == "fsdp" and data_size > 1:
        def _leafspec(a, d):
            ents: list[Any] = [None] * (a.ndim - 1)
            if d != UNSPLIT:
                ents[d - 1] = DATA_AXIS
            return P(PIPE_AXIS, *ents)

        pspec = jax.tree.map(_leafspec, stage_params, fsdp_dims)
    else:
        pspec = jax.tree.map(
            lambda a: P(PIPE_AXIS, *([None] * (a.ndim - 1))), stage_params)
    tspec = jax.tree.map(lambda a: P(), tail_params)
    dx_spec = (P(PIPE_AXIS, None, DATA_AXIS) if data_size > 1
               else P(PIPE_AXIS))
    region = shard_map(
        per_device, mesh=mesh,
        in_specs=(pspec, tspec, batch_spec, batch_spec, batch_spec, P()),
        out_specs=(P(), P(), pspec, tspec, dx_spec),
        check_vma=False,
    )

    @jax.custom_vjp
    def run(stage_w, tail_p, x, tgt, wt, key):
        # undifferentiated path: the cheap F-only fill/drain loop + the
        # per-microbatch tail, summed in schedule order (model/data
        # sharded weights are auto-gathered by the GPipe loop's
        # replicated in_specs — eval-only, so the waste is acceptable)
        ys = pipeline_apply(stage_w, kernel.fwd, x, mesh)
        loss = jnp.zeros((), jnp.float32)
        hits = jnp.zeros((), jnp.float32)
        for i in range(M):
            li, hi = kernel.tail_fwd(tail_p, ys[i], tgt[i], wt[i])
            loss, hits = loss + li, hits + hi
        return loss, hits

    def run_fwd(stage_w, tail_p, x, tgt, wt, key):
        loss, hits, dw, d_tail, dx = region(
            stage_w, tail_p, x, tgt, wt, key)
        return (loss, hits), (dw, d_tail, dx[0], tgt, wt, key)

    def run_bwd(res, cts):
        dw, d_tail, dx, tgt, wt, key = res
        gl, _ = cts  # hits is an argmax count: gradient zero a.e.
        scale = lambda t: jax.tree.map(
            lambda a: (a * gl).astype(a.dtype), t)
        return (scale(dw), scale(d_tail), scale(dx),
                _zero_cotangent(tgt), _zero_cotangent(wt),
                _zero_cotangent(key))

    run.defvjp(run_fwd, run_bwd)
    return run(stage_params, tail_params, x_feed, tgt, wt, crng)
