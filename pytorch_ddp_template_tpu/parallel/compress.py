"""Compressed, backward-overlapped gradient collectives for pure DDP
(``--ddp_overlap`` + ``--grad_comm {fp32,bf16,int8}`` +
``--grad_error_feedback``).

Since r22 the pipelined entries reuse :func:`_reduce_tree` (and
:data:`CHUNK`) for pipe×ddp: one masked per-slot reduce at the slot
boundary of the 1f1b loop (``parallel/pipeline.py``), keyed per
``(slot, leaf)`` for unbiased lossy wires; this module's own reverse
scan stays data-mesh-only.

Under plain replicated-param DDP the cross-replica gradient mean is left
entirely to GSPMD: the batch is sharded over ``data``, params are
replicated, and XLA inserts one fp32 all-reduce per gradient leaf after
backward (train/engine.py's "NCCL-DDP replacement"). PyTorch DDP's
signature perf feature — bucketed gradient all-reduce *overlapped with
backward compute* (Li et al., VLDB 2020) — and the 1-bit-SGD lineage of
*compressed* gradient exchange with error feedback (Seide et al., 2014)
both live below that abstraction. This module rebuilds them TPU-natively
on the round-8 decomposed-scan machinery (``parallel/overlap.py``):

- :func:`ddp_overlap_scan` drives the scanned transformer stack with a
  hand-written ``custom_vjp`` whose reverse ``lax.scan`` computes each
  layer's *per-replica* gradients inside a ``shard_map`` region over
  ``data`` and issues that layer's cross-replica reduce **inside the
  iteration** — layer k's reduce is dataflow-independent of layer k-1's
  backward compute, so the latency-hiding scheduler can drain it under
  the next layer's matmuls: the TPU-native form of DDP bucketing (one
  bucket per layer, pinned by construction rather than by hook order).

- The explicit reduce is where compression becomes possible at all:
  GSPMD's implicit psum is fp32-or-nothing, but a manual reduce can ship
  quantized bytes. ``grad_comm`` selects the wire format, executed as a
  quantized all-to-all (the reduce-scatter phase: each replica owns 1/n
  of every layer's flattened grads), an fp32 dequant-sum on the owner,
  and a re-quantized all-gather — bf16 halves and int8 quarters the
  bytes on the wire (:func:`wire_bytes_per_step`). int8 uses chunked
  symmetric per-bucket quantization (:data:`CHUNK`-wide buckets, scale =
  absmax/127) with stochastic rounding; bf16 uses stochastic
  mantissa-rounding. Both phases round stochastically, so each exchange
  is unbiased.

- ``--grad_error_feedback`` carries a per-replica residual tree
  (``TrainState.comm_residual``, leaves ``(L, data_size, padded)``
  sharded over ``data``): each replica adds its residual to its local
  grads before quantizing and keeps back exactly the error both
  quantization phases introduced, so the compression error telescopes —
  the sum of applied updates tracks the sum of true gradients to within
  one step's residual instead of a random walk. The residual rides the
  custom_vjp as a primal input whose *cotangent slot carries the updated
  residual out of the backward pass* (backward-only state cannot surface
  through any other in-jit channel); ``train/engine.py`` differentiates
  w.r.t. it and writes the cotangent back into ``TrainState``.

Scope (refused with intent elsewhere): replicated params on a data-only
mesh, ``--scan_layers`` stacks only. The embedding/head/final-LN grads
outside the scanned stack keep GSPMD's fp32 psum — compression covers
the O(num_layers) bulk, and ``parallel/sharding.describe`` logs both
byte totals so the split is visible. Dropout streams fold the layer
index and the data-axis coordinate (each replica draws its own mask for
its shard) — statistically equivalent to the ``nn.scan`` path, not
bit-interchangeable; parity tests pin the dropout-free math.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import DATA_AXIS
from .shard_map_compat import shard_map

#: supported wire formats for the per-layer gradient exchange
GRAD_COMM_MODES = ("fp32", "bf16", "int8")

#: int8 quantization bucket width: one fp32 scale per CHUNK values (the
#: 1.6% scale overhead keeps int8 at ~0.25x fp32 wire bytes while bounding
#: per-value error by its bucket's absmax/127, not the whole tensor's)
CHUNK = 256


def validate_ddp_mesh(mesh: Mesh | None, tp: bool = False) -> Mesh:
    """Refuse meshes the compressed-DDP path cannot serve, with intent.

    Delegates to the unified ``schedule.validate_schedule_mesh``:
    replicated-param data-only meshes alone, or data×model when composed
    with the TP ring schedule (``tp=True`` — the reduce region then runs
    over both axes with the block's local ring kernels inside it).
    """
    from .schedule import validate_schedule_mesh

    return validate_schedule_mesh(mesh, ddp=True, tp=tp)


# -- quantizers ------------------------------------------------------------

def stochastic_round_bf16(x: jax.Array, key: jax.Array) -> jax.Array:
    """fp32 -> bf16 with stochastic mantissa rounding (unbiased).

    Adds a uniform 16-bit integer below the kept mantissa and truncates:
    the carry promotes with probability equal to the dropped fraction, so
    ``E[sr(x)] == x`` exactly (magnitude-wise, hence value-wise — the
    sign bit never participates in the carry).
    """
    bits = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint16).astype(jnp.uint32)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


def quantize_int8(x: jax.Array, key: jax.Array,
                  chunk: int = CHUNK) -> tuple[jax.Array, jax.Array]:
    """Chunked symmetric int8 quantization with stochastic rounding.

    ``x``'s last dim must be a multiple of ``chunk``; returns
    ``(q int8 (..., nb, chunk), scale f32 (..., nb, 1))`` with
    ``scale = absmax/127`` per bucket (1.0 for all-zero buckets so the
    dequant stays exact zeros). ``floor(y + u)`` with ``u ~ U[0, 1)`` is
    unbiased for every real ``y``.
    """
    xb = x.reshape(*x.shape[:-1], x.shape[-1] // chunk, chunk)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    y = xb.astype(jnp.float32) / scale
    u = jax.random.uniform(key, y.shape, jnp.float32)
    q = jnp.clip(jnp.floor(y + u), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_int8`; returns the un-bucketed shape."""
    out = q.astype(jnp.float32) * scale
    return out.reshape(*q.shape[:-2], q.shape[-2] * q.shape[-1])


# -- the wire: quantized reduce-scatter -> dequant-sum -> all-gather -------

def padded_size(n_elems: int, data_size: int, chunk: int = CHUNK) -> int:
    """Flat length after padding to a multiple of ``data_size * chunk``
    (every replica's piece is a whole number of quantization buckets)."""
    unit = data_size * chunk
    return max(((n_elems + unit - 1) // unit) * unit, unit)


def residual_shape(stacked_shape: tuple[int, ...], data_size: int,
                   chunk: int = CHUNK) -> tuple[int, int, int]:
    """Residual leaf shape for a stacked ``(L, *s)`` param leaf:
    ``(L, data_size, padded)`` — one full flattened-grad residual per
    replica per layer, sharded over ``data`` on dim 1."""
    per_layer = int(np.prod(stacked_shape[1:])) if len(stacked_shape) > 1 else 1
    return (stacked_shape[0], data_size, padded_size(per_layer, data_size,
                                                     chunk))


def local_shard_elems(stacked_shape: tuple[int, ...], spec,
                      model_size: int) -> int:
    """Per-(model-)shard element count of one stacked ``(L, *s)`` leaf
    under a Megatron placement spec (the ddp×tp residual sizing, r17):
    dims whose spec entry names the model axis hold ``1/model_size`` of
    the leaf locally; model-replicated leaves (LayerNorms, row biases)
    keep their full width on every shard."""
    from ..runtime.context import MODEL_AXIS

    elems = 1
    entries = tuple(spec or ())
    entries = entries + (None,) * (len(stacked_shape) - len(entries))
    for dim, entry in zip(stacked_shape[1:], entries[1:]):
        names = (() if entry is None
                 else ((entry,) if isinstance(entry, str) else tuple(entry)))
        if MODEL_AXIS in names:
            if dim % model_size:
                raise ValueError(
                    f"model-sharded residual dim {dim} not divisible by "
                    f"the model-axis size {model_size}")
            dim //= model_size
        elems *= int(dim)
    return elems


def residual_shape_tp(stacked_shape: tuple[int, ...], data_size: int,
                      model_size: int, spec,
                      chunk: int = CHUNK) -> tuple[int, int, int, int]:
    """ddp×tp residual leaf shape: ``(L, data_size, model_size,
    padded_local)`` — each (data, model) coordinate keeps the
    compensation state for exactly the grads it quantizes (its local
    model shard of the leaf), sharded ``P(None, data, model)``."""
    local = local_shard_elems(stacked_shape, spec, model_size)
    return (stacked_shape[0], data_size, model_size,
            padded_size(local, data_size, chunk))


def init_residual(stacked: Any, data_size: int, chunk: int = CHUNK, *,
                  tp_specs: Any | None = None,
                  model_size: int = 1) -> Any:
    """Zero error-feedback residual tree mirroring a stacked param tree.

    ``tp_specs``/``model_size`` (the ddp×tp composition, r17): size each
    leaf for the model-SHARDED local grads the composed drain reduces
    (``residual_shape_tp``) instead of the replicated full width — the
    r11 named refusal, lifted."""
    if tp_specs is None:
        return jax.tree.map(
            lambda x: jnp.zeros(residual_shape(x.shape, data_size, chunk),
                                jnp.float32),
            stacked,
        )
    return jax.tree.map(
        lambda x, spec: jnp.zeros(
            residual_shape_tp(x.shape, data_size, model_size, spec, chunk),
            jnp.float32),
        stacked, tp_specs,
    )


def rebucket_residual(raw: np.ndarray,
                      new_shape: tuple[int, ...]) -> np.ndarray:
    """Re-bucket one saved EF-residual leaf ``(L, data_old, padded_old)``
    onto a new data-parallel degree ``(L, data_new, padded_new)`` — the
    r18 reshard-on-restore move for elastic restarts that change the
    replica count.

    What error feedback guarantees is the *telescoping sum*: the sum of
    residuals over replicas is the gradient mass not yet applied. The
    re-bucketing preserves exactly that invariant (float tolerance):
    sum the per-replica residuals, resize the flat payload (the region
    beyond the true element count is zero by construction — padding
    positions quantize zero grads to zero error), and split the total
    evenly across the new replicas. Per-replica attribution is NOT
    preserved (it cannot be: the replicas no longer exist), which is
    why this is a float-tolerance conversion, not a bit-exact one.
    Only same-rank 3-d leaves with a matching layer count qualify; the
    caller zero-initialises anything else (e.g. the 4-d ddp×tp layout,
    whose per-model-shard bucketing does not survive a model-axis
    change)."""
    raw = np.asarray(raw, dtype=np.float32)
    if raw.ndim != 3 or len(new_shape) != 3:
        raise ValueError(
            f"rebucket_residual handles (L, data, padded) leaves only, "
            f"got {raw.shape} -> {tuple(new_shape)}")
    if raw.shape[0] != new_shape[0]:
        raise ValueError(
            f"layer count changed {raw.shape[0]} -> {new_shape[0]}; the "
            "residual cannot be re-bucketed across a layer-stack change")
    _, d_new, p_new = new_shape
    total = raw.sum(axis=1)  # (L, padded_old): the telescoping invariant
    p_old = total.shape[1]
    if p_new >= p_old:
        total = np.pad(total, ((0, 0), (0, p_new - p_old)))
    else:
        total = total[:, :p_new]
    return np.repeat((total / d_new)[:, None, :], d_new, axis=1)


def _reduce_flat(flat: jax.Array, key: jax.Array | None, mode: str,
                 axis_name: str, n: int, chunk: int,
                 want_error: bool) -> tuple[jax.Array, jax.Array | None]:
    """Cross-replica SUM of one flat padded vector, in ``mode`` precision.

    Runs INSIDE a shard_map region over ``axis_name``. ``flat`` is this
    replica's local partial (error-compensated when EF is on). Pipeline:
    reshape to ``(n, piece)`` (row j is owner j's piece), quantize, ship
    via ``all_to_all`` (the reduce-scatter phase: only quantized bytes
    ride the wire), dequant-sum in fp32 on the owner, re-quantize the
    sum, ``all_gather`` it back, dequant. Returns the replicated sum and
    (when ``want_error``) this replica's total quantization error — the
    phase-1 error everywhere plus the phase-2 error folded into the
    owner's own row, so re-injecting it next step telescopes both.
    """
    pieces = flat.reshape(n, -1)
    if mode == "fp32":
        recv = lax.all_to_all(pieces, axis_name, 0, 0)
        s = recv.sum(axis=0)
        total = lax.all_gather(s, axis_name, axis=0)
        return total.reshape(-1), None
    k1, k2 = jax.random.split(key)
    if mode == "bf16":
        q = stochastic_round_bf16(pieces, k1)
        sent = q.astype(jnp.float32)
        recv = lax.all_to_all(q, axis_name, 0, 0)
        s = recv.astype(jnp.float32).sum(axis=0)
        q2 = stochastic_round_bf16(s, k2)
        summed = q2.astype(jnp.float32)
        total = lax.all_gather(q2, axis_name, axis=0).astype(jnp.float32)
    elif mode == "int8":
        q, sc = quantize_int8(pieces, k1, chunk)
        sent = dequantize_int8(q, sc)
        recvq = lax.all_to_all(q, axis_name, 0, 0)
        recvs = lax.all_to_all(sc, axis_name, 0, 0)
        s = dequantize_int8(recvq, jnp.broadcast_to(
            recvs, recvq.shape[:-1] + (1,))).sum(axis=0)
        q2, sc2 = quantize_int8(s[None], k2, chunk)
        summed = dequantize_int8(q2, sc2)[0]
        gq = lax.all_gather(q2[0], axis_name, axis=0)
        gs = lax.all_gather(sc2[0], axis_name, axis=0)
        total = dequantize_int8(gq, gs)
    else:
        raise ValueError(f"unknown grad_comm mode {mode!r}; "
                         f"expected one of {GRAD_COMM_MODES}")
    if not want_error:
        return total.reshape(-1), None
    # phase-1 error on every row; phase-2 error on the row this replica
    # OWNS (row me stays local in the all_to_all, so next step's
    # re-injection lands back in exactly the sum it mis-rounded)
    err = pieces - sent
    me = lax.axis_index(axis_name)
    own = (jnp.arange(n) == me).astype(jnp.float32)[:, None]
    err = err + own * (s - summed)[None, :]
    return total.reshape(-1), err.reshape(-1)


def _leaf_allreduce(g: jax.Array, e_loc: jax.Array | None,
                    key: jax.Array | None, mode: str, axis_name: str,
                    n: int, chunk: int) -> tuple[jax.Array,
                                                 jax.Array | None]:
    """Per-leaf compressed cross-replica sum (inside the region).

    ``g`` is the local partial grad (full leaf shape — or the local
    model shard under ddp×tp); ``e_loc`` the local residual
    ``(1, padded)`` (``(1, 1, padded)`` under ddp×tp) or None. Pads,
    compensates, reduces, unpads. The updated residual keeps ``e_loc``'s
    own shape, so both layouts round-trip through the cotangent slot."""
    flat = g.reshape(-1).astype(jnp.float32)
    pad = padded_size(flat.size, n, chunk)
    if pad != flat.size:
        flat = jnp.pad(flat, (0, pad - flat.size))
    if e_loc is not None:
        if e_loc.size != pad:
            raise ValueError(
                f"error-feedback residual leaf has {e_loc.size} elements "
                f"but the padded local grad needs {pad} — the residual "
                "was sized for a different layout/topology (init_residual "
                "sizes per-shard under ddp×tp)")
        flat = flat + e_loc.reshape(-1)
    total, err = _reduce_flat(flat, key, mode, axis_name, n, chunk,
                              want_error=e_loc is not None)
    out = total[: g.size].reshape(g.shape).astype(g.dtype)
    return out, None if err is None else err.reshape(e_loc.shape)


def _reduce_tree(gw: Any, res: Any | None, key: jax.Array | None, mode: str,
                 axis_name: str, n: int,
                 chunk: int) -> tuple[Any, Any | None]:
    """Tree-mapped :func:`_leaf_allreduce` with per-leaf key folds."""
    leaves, treedef = jax.tree.flatten(gw)
    res_leaves = (jax.tree.leaves(res) if res is not None
                  else [None] * len(leaves))
    if len(res_leaves) != len(leaves):
        raise ValueError(
            f"error-feedback residual has {len(res_leaves)} leaves but the "
            f"gradient tree has {len(leaves)} — the residual must mirror "
            "the stacked params it compensates"
        )
    outs, errs = [], []
    for i, (g, e) in enumerate(zip(leaves, res_leaves)):
        k_i = None if key is None else jax.random.fold_in(key, i)
        o, err = _leaf_allreduce(g, e, k_i, mode, axis_name, n, chunk)
        outs.append(o)
        errs.append(err)
    new_res = (None if res is None
               else jax.tree.unflatten(jax.tree.structure(res), errs))
    return jax.tree.unflatten(treedef, outs), new_res


def compressed_allreduce(partials: Any, mesh: Mesh, mode: str, *,
                         rng: jax.Array | None = None,
                         residual: Any | None = None,
                         chunk: int = CHUNK) -> tuple[Any, Any | None]:
    """Standalone compressed cross-replica SUM (unit-test surface + the
    building block :func:`ddp_overlap_scan` issues per layer).

    ``partials``: tree of ``(data_size, *s)`` arrays sharded over ``data``
    on dim 0 — row i is replica i's partial. ``residual``: tree of
    ``(data_size, padded)`` arrays (same sharding) or None. Returns
    ``(sums, new_residual)`` where each sums leaf is ``(data_size, *s)``
    with every row holding the identical reduced value.
    """
    validate_ddp_mesh(mesh)
    n = mesh.shape.get(DATA_AXIS, 1)
    if mode not in GRAD_COMM_MODES:
        raise ValueError(f"unknown grad_comm mode {mode!r}; "
                         f"expected one of {GRAD_COMM_MODES}")
    if mode != "fp32" and rng is None:
        raise ValueError(f"grad_comm={mode!r} needs an rng for stochastic "
                         "rounding")
    if residual is not None and mode == "fp32":
        # same refusal as ddp_overlap_scan: an fp32 exchange has no
        # quantization error to feed back, and the region would otherwise
        # die on an out_specs structure mismatch instead of saying so
        raise ValueError("error-feedback residual with grad_comm=fp32 is "
                         "a no-op by construction; drop one of the two")

    sh = P(DATA_AXIS)
    in_specs = (jax.tree.map(lambda _: sh, partials),
                jax.tree.map(lambda _: sh, residual),
                None if rng is None else P())
    out_specs = (jax.tree.map(lambda _: sh, partials),
                 jax.tree.map(lambda _: sh, residual))

    def region(parts, res, key):
        local = jax.tree.map(lambda x: x[0], parts)
        out, err = _reduce_tree(local, res, key, mode, DATA_AXIS, n, chunk)
        return jax.tree.map(lambda x: x[None], out), err

    return shard_map(region, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)(
        partials, residual, rng)


# -- the scan: per-layer backward with in-iteration compressed reduce ------


def ddp_overlap_scan(apply_fn: Callable[[Any, jax.Array, jax.Array, Any],
                                        jax.Array],
                     stacked: Any, x: jax.Array, extras: Any,
                     extras_specs: Any, mesh: Mesh, *,
                     grad_comm: str = "fp32",
                     residual: Any | None = None,
                     comm_rng: jax.Array | None = None,
                     chunk: int = CHUNK,
                     tp_specs: Any | None = None) -> jax.Array:
    """Run ``apply_fn(layer_params, y, k, extras)`` over the stacked
    layers with per-layer cross-replica grad reduces issued inside the
    backward scan iteration, in ``grad_comm`` wire precision.

    Since round 11 this is a thin wrapper assembling the ddp
    contribution (:class:`parallel.schedule.DdpSchedule`: the whole
    per-layer block vjp inside a ``shard_map`` region over ``data`` —
    the only level where unreduced per-replica partials are observable —
    with that layer's compressed reduce issued in the same iteration)
    onto the ONE shared custom-vjp skeleton
    (``parallel.schedule.decomposed_scan``). Same signature, same
    numerics as the r9 original; ``extras_specs`` gives each extras
    leaf's region spec (batch-sharded mask vs replicated rng), and
    ``residual``/``comm_rng`` thread the error-feedback state whose
    update leaves through the residual input's cotangent slot.

    ``tp_specs`` (ddp×tp composition) switches the region to
    ``data × model``: ``apply_fn`` must then use the LOCAL ring kernels
    (the encoder's ``tp_local`` path), and each layer's drain merges
    TP's ``data``-psum of weight grads with the compressed bucket reduce
    into one exchange.
    """
    from .schedule import DdpSchedule, decomposed_scan, num_stacked_layers

    num_layers = num_stacked_layers(stacked, "ddp_overlap_scan")
    schedule = DdpSchedule(
        mesh, stacked, num_layers, extras_specs, grad_comm=grad_comm,
        chunk=chunk, tp_specs=tp_specs, residual=residual,
        comm_rng=comm_rng)
    return decomposed_scan(schedule, apply_fn, stacked, x, extras,
                           residual=residual, comm_rng=comm_rng)


# -- evidence --------------------------------------------------------------

def wire_bytes_per_step(stacked: Any, data_size: int, mode: str,
                        chunk: int = CHUNK) -> int:
    """Estimated gradient bytes on the wire per optimizer step for a
    stacked ``(L, ...)`` tree under ``mode``.

    Counts both phases' payload (quantized reduce-scatter + re-quantized
    all-gather) over the padded flat length, plus the int8 per-bucket
    fp32 scales. An upper bound: the all_to_all keeps 1/data_size of the
    payload local, which this deliberately does not discount (the
    fp32-vs-quantized *ratios* are exact either way). The GSPMD fp32
    baseline costs ``2 * 4 * size`` per leaf (ring all-reduce moves ~2x
    the data).
    """
    if mode not in GRAD_COMM_MODES:
        raise ValueError(f"unknown grad_comm mode {mode!r}")
    total = 0
    for leaf in jax.tree.leaves(stacked):
        per_layer = int(np.prod(leaf.shape[1:])) if leaf.ndim > 1 else 1
        pad = padded_size(per_layer, data_size, chunk)
        if mode == "fp32":
            per = 2 * 4 * pad
        elif mode == "bf16":
            per = 2 * 2 * pad
        else:  # int8: values + one f32 scale per bucket, both phases
            per = 2 * (pad + 4 * (pad // chunk))
        total += int(leaf.shape[0]) * per
    return total


def hlo_comms_evidence(hlo_text: str, num_layers: int) -> dict[str, Any]:
    """Analyse compiled HLO for the per-layer in-scan reduce signature.

    Builds on ``parallel/overlap.hlo_overlap_evidence``'s loop-body
    dependency analysis, with ``all-to-all`` added to the collective set
    (the compressed reduce-scatter phase lowers to it). A dot-carrying
    scan body that contains reduce collectives executes them once per
    layer iteration; each iteration's reduce consumes only that layer's
    gradients, so the ``num_layers`` dynamic instances are mutually
    independent — the schedulable per-layer drain. Headline:
    ``inscan_reduce_collectives`` (= per-body count x trip count, the
    number of independent reduce launches per step) and
    ``per_layer_reduce`` (>= 1 reduce collective lives inside a
    dot-carrying loop body at all — under GSPMD-default DDP the grad
    all-reduce sits outside the scan instead).
    """
    from .overlap import hlo_overlap_evidence

    ev = hlo_overlap_evidence(
        hlo_text,
        collectives=("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all"),
    )
    bodies = ev["bodies"]
    per_body = max((r["collectives"] for r in bodies), default=0)
    return {
        "bodies": bodies,
        "bwd_body_collectives": per_body,
        "inscan_reduce_collectives": per_body * num_layers,
        "per_layer_reduce": per_body >= 1,
    }
