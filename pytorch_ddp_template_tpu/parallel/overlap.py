"""Decomposed FSDP: explicit per-layer weight gathers, pipelined one layer
ahead of compute (``--fsdp_overlap``).

Since r22 the pipelined entries reuse the primitives here — the
``fsdp_split_dim`` chooser (via ``parallel/sharding.py``), the
``UNSPLIT`` sentinel and ``_zero_cotangent`` — to run pipe×fsdp as
slot-boundary gather/scatter waves (``parallel/pipeline.py``); this
module's own prefetch scan stays data-mesh-only.

Under plain ``--fsdp`` the gather/scatter protocol is left entirely to
GSPMD, whose default dataflow is "all-gather layer k → compute layer k":
the ICI sits idle during every layer's matmuls and the matmuls wait on
every gather. ZeRO (Rajbhandari et al., 2020) and "Overlap Communication
with Dependent Computation via Decomposition" (Wang et al., ASPLOS 2023)
show the win comes from *decomposing* the schedule: issue layer k+1's
parameter gather while layer k computes, and drain layer k's gradient
reduction while layer k−1's backward runs. The scan-over-layers layout
(``--scan_layers``: every block weight stacked on a leading
``(num_layers, ...)`` dim, FSDP-split via ``fsdp_reshard(prefer_dim=0)``)
provides exactly the uniform per-layer structure this needs.

Mechanism (all through the ``shard_map_compat`` seam, over the ``data``
mesh axis):

- :func:`make_layer_gather` builds ``gather(stacked, k) -> layer_k`` as a
  ``shard_map`` region whose per-leaf body depends on where the FSDP
  split landed (``fsdp_split_dim`` — the same chooser ``fsdp_reshard``
  uses, so the specs match the layouts the trainer placed and no silent
  reshard happens at the boundary):

  * split on the stacked **layer dim** (the ``prefer_dim=0`` case,
    ``num_layers % data == 0``): the owner shard contributes its slice,
    everyone else zeros, one ``psum`` broadcasts it — a
    gather-at-layer-granularity;
  * split on a **within-layer** dim (the fallback when the layer count
    does not divide, e.g. 2-layer models on 8 chips): slice the layer
    locally, ``all_gather`` the split dim — the classic FSDP unshard;
  * unsplit leaves (odd shapes): a plain slice, no collective.

- The gather carries a ``jax.custom_vjp``: the backward is the symmetric
  scatter — the incoming per-layer cotangent (which GSPMD reduces across
  the ``data`` axis to satisfy the region's replicated in-spec: the
  per-layer gradient reduction) is written into the owner shard's slice /
  chunked back into the split-dim layout, i.e. a reduce-scatter of layer
  k's grads delivered straight into the sharded stacked layout. Explicit
  custom_vjp rather than shard_map transposition so the backward schedule
  is pinned by construction, not by transpose-rule internals.

- :func:`overlap_scan` drives the block over layers with a ``lax.scan``
  whose carry holds ``(activations, next layer's gathered weights)``: the
  body issues the gather for layer k+1 *before* layer k's compute, so the
  two are dataflow-independent inside one loop iteration and the XLA
  latency-hiding scheduler (``--xla_overlap_flags``) can run the
  collective under the matmuls. Reverse-mode through the scan gives the
  mirrored property: layer k's grad scatter is independent of layer k−1's
  backward compute. Gathered full weights never live longer than two
  layers (current + prefetched) — memory stays O(2/L) above sharded
  FSDP, never the O(1) full materialisation.

Numerics: the gather reproduces ``stacked[k]`` bit-exactly (a psum of one
non-zero contribution, or an all-gather of exact chunks), so the overlap
path is bit-identical to the GSPMD-default FSDP path in eval mode and
dropout-free training. With dropout active the per-layer streams are
folded from the scan index rather than ``nn.scan``'s split — statistically
equivalent, not bit-interchangeable (documented in README).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime.context import DATA_AXIS
from .shard_map_compat import shard_map
from .sharding import fsdp_split_dim

#: sentinel for "leaf not split over data" in the static dims tree
#: (None cannot ride in a pytree — it reads as an empty subtree)
UNSPLIT = -1


def validate_overlap_mesh(mesh: Mesh | None, tp: bool = False) -> Mesh:
    """Refuse meshes the decomposed path cannot serve, with intent.

    Delegates to the unified ``schedule.validate_schedule_mesh``:
    data-only meshes alone, or data×model when composed with the TP ring
    schedule (``tp=True`` — the gather/scatter region specs then carry
    the model placement instead of silently unsharding it).
    """
    from .schedule import validate_schedule_mesh

    return validate_schedule_mesh(mesh, fsdp=True, tp=tp)


def overlap_split_dims(stacked: Any, data_size: int,
                       tp_specs: Any | None = None) -> Any:
    """Static per-leaf FSDP split dims for a stacked ``(L, ...)`` tree.

    Mirrors ``fsdp_reshard(prefer_dim=0)`` leaf-for-leaf via the shared
    :func:`fsdp_split_dim` chooser; ``UNSPLIT`` marks replicated leaves.
    ``tp_specs`` (fsdp×tp) masks out the dims already carrying the
    ``model`` axis, exactly as ``fsdp_reshard``'s placed-sharding walk
    skips them — the chooser and the placement must agree or every
    gather would silently reshard.
    """
    if tp_specs is None:
        return jax.tree.map(
            lambda x: (lambda d: UNSPLIT if d is None else d)(
                fsdp_split_dim(x.shape, data_size, prefer_dim=0)),
            stacked,
        )

    def pick(x, spec):
        entries = list(tuple(spec or ())) + [None] * x.ndim
        free = [entries[i] is None for i in range(x.ndim)]
        d = fsdp_split_dim(x.shape, data_size, prefer_dim=0, free=free)
        return UNSPLIT if d is None else d

    from jax.sharding import PartitionSpec

    return jax.tree.map(pick, stacked, tp_specs,
                        is_leaf=lambda v: isinstance(v, PartitionSpec))


def make_layer_gather(mesh: Mesh, stacked: Any, num_layers: int,
                      tp_specs: Any | None = None,
                      ) -> tuple[Callable[[Any, jax.Array], Any],
                                 Callable[[Any, jax.Array], Any]]:
    """Build the ``(gather, scatter)`` pair for one stacked layer tree.

    ``gather(stacked, k) -> layer_k`` unshards layer ``k``'s weights;
    ``scatter(g, k) -> stacked-layout grad`` writes a full per-layer
    cotangent back into the sharded stacked layout (zeros elsewhere) —
    the scatter half of the reduce-scatter (the reduce is the GSPMD
    cross-replica sum the replicated in-spec forces on ``g``). Both are
    called as plain forward computations by :func:`overlap_scan`'s
    custom-vjp rules; nothing differentiates through them.

    ``stacked`` is used for shapes/structure only (trace-time); the
    returned callables take the live tree. Specs are computed from the
    same split-dim chooser ``fsdp_reshard(prefer_dim=0)`` uses, so on a
    state the trainer placed the region boundary is a no-op reshard.
    """
    data_size = mesh.shape.get(DATA_AXIS, 1)
    dims = overlap_split_dims(stacked, data_size, tp_specs)
    if tp_specs is None:
        tp_base = jax.tree.map(lambda x: P(*([None] * x.ndim)), stacked)
    else:
        tp_base = tp_specs

    def leaf_spec(x, d, tp_sp):
        # start from the TP placement (model axis on its Megatron dims,
        # or all-None without tp) and add the data split on top: the
        # region boundary is then a no-op reshard on a trainer-placed
        # state in BOTH regimes
        spec: list[Any] = list(tuple(tp_sp or ())) + [None] * x.ndim
        spec = spec[: x.ndim]
        if d != UNSPLIT:
            spec[d] = DATA_AXIS
        return P(*spec)

    def gathered_spec(x, tp_sp):
        # the gather drops the leading stacked layer dim; the model
        # placement shifts left with it (data is gathered away)
        spec: list[Any] = list(tuple(tp_sp or ()))[1:] + [None] * x.ndim
        return P(*spec[: x.ndim - 1])

    in_specs = jax.tree.map(leaf_spec, stacked, dims, tp_base)
    rep_specs = jax.tree.map(gathered_spec, stacked, tp_base)

    def _gather_leaf(local: jax.Array, k: jax.Array, d: int) -> jax.Array:
        if d == 0:
            # layer-granular split: broadcast the owner shard's slice
            per = num_layers // data_size
            me = lax.axis_index(DATA_AXIS)
            owner = k // per
            mine = lax.dynamic_index_in_dim(
                local, jnp.clip(k - owner * per, 0, per - 1), 0,
                keepdims=False)
            return lax.psum(
                jnp.where(owner == me, mine, jnp.zeros_like(mine)),
                DATA_AXIS)
        sliced = lax.dynamic_index_in_dim(local, k, 0, keepdims=False)
        if d == UNSPLIT:
            return sliced
        # within-layer split: the classic FSDP all-gather of the chunk dim
        return lax.all_gather(sliced, DATA_AXIS, axis=d - 1, tiled=True)

    def _scatter_leaf(g: jax.Array, k: jax.Array, d: int) -> jax.Array:
        if d == 0:
            per = num_layers // data_size
            me = lax.axis_index(DATA_AXIS)
            owner = k // per
            upd = jnp.where(owner == me, g, jnp.zeros_like(g))
            zeros = jnp.zeros((per,) + g.shape, g.dtype)
            return lax.dynamic_update_index_in_dim(
                zeros, upd, jnp.clip(k - owner * per, 0, per - 1), 0)
        if d == UNSPLIT:
            zeros = jnp.zeros((num_layers,) + g.shape, g.dtype)
            return lax.dynamic_update_index_in_dim(zeros, g, k, 0)
        chunk = g.shape[d - 1] // data_size
        me = lax.axis_index(DATA_AXIS)
        mine = lax.dynamic_slice_in_dim(g, me * chunk, chunk, axis=d - 1)
        local = jnp.zeros((num_layers,) + mine.shape, mine.dtype)
        return lax.dynamic_update_index_in_dim(local, mine, k, 0)

    def _fwd_local(tree: Any, k: jax.Array) -> Any:
        return jax.tree.map(lambda x, d: _gather_leaf(x, k, d), tree, dims)

    def _bwd_local(g: Any, k: jax.Array) -> Any:
        return jax.tree.map(lambda x, d: _scatter_leaf(x, k, d), g, dims)

    gather = shard_map(_fwd_local, mesh=mesh,
                       in_specs=(in_specs, P()), out_specs=rep_specs,
                       check_vma=False)
    scatter = shard_map(_bwd_local, mesh=mesh,
                        in_specs=(rep_specs, P()), out_specs=in_specs,
                        check_vma=False)
    return gather, scatter


def _zero_cotangent(tree: Any) -> Any:
    """Symbolic-zero cotangents: float0 for int/bool leaves (indices,
    masks, rng keys), real zeros for any inexact leaf."""
    def z(v):
        if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
            return jnp.zeros_like(v)
        return np.zeros(np.shape(v), jax.dtypes.float0)
    return jax.tree.map(z, tree)


def overlap_scan(apply_fn: Callable[[Any, jax.Array, jax.Array, Any],
                                    jax.Array],
                 stacked: Any, x: jax.Array, extras: Any,
                 mesh: Mesh, tp_specs: Any | None = None) -> jax.Array:
    """Run ``apply_fn(layer_params, x, k, extras)`` over the stacked
    layers with a one-layer-ahead gather pipeline and a hand-written
    (custom-vjp) backward.

    Since round 11 this is a thin wrapper assembling the fsdp
    contribution (:class:`parallel.schedule.FsdpSchedule`: fwd carry
    holds the NEXT layer's gathered weights, bwd carry the PREVIOUS
    layer's, per-iteration grad scatters into the sharded stacked
    layout) onto the ONE shared custom-vjp skeleton
    (``parallel.schedule.decomposed_scan`` — carry next-layer state,
    recompute blocks from saved boundary activations, drain grads per
    iteration). Same signature, same numerics as the r8 original.

    ``tp_specs`` (fsdp×tp composition) carries the Megatron model-axis
    placement of the stacked leaves through the gather/scatter region
    specs: the data-axis collectives then leave the model sharding
    intact while the block's ring ppermutes (over ``model``) pipeline
    independently of them.
    """
    from .schedule import (
        FsdpSchedule, decomposed_scan, num_stacked_layers,
    )

    num_layers = num_stacked_layers(stacked, "overlap_scan")
    schedule = FsdpSchedule(mesh, stacked, num_layers, tp_specs=tp_specs)
    return decomposed_scan(schedule, apply_fn, stacked, x, extras)


# -- HLO schedule evidence -------------------------------------------------


def hlo_overlap_evidence(hlo_text: str,
                         collectives: tuple[str, ...] | None = None,
                         ) -> dict[str, Any]:
    """Analyse compiled HLO for the decomposed schedule's signature.

    Since r12 this is a thin delegate: the operand-chain walker moved to
    ``obs/hlo_report.collective_evidence`` so the production
    ``--hlo_report`` tripwire and the bench legs share ONE analysis (this
    spelling and its semantics are unchanged — headline booleans
    ``prefetch_gather_independent`` / ``bwd_regather_independent``, and
    the ``collectives=`` override ``parallel/compress.py`` uses)."""
    from ..obs.hlo_report import collective_evidence

    return collective_evidence(hlo_text, collectives=collectives)
