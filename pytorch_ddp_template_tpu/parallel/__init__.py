"""Parallelism: sharding rules (dp/tp/sp over the mesh) + context engines.

The reference's parallel surface is NCCL data parallelism only
(SURVEY.md §2b); here data parallelism is the ``data`` mesh axis, tensor
parallelism the ``model`` axis (``sharding.py``), and sequence/context
parallelism the ``seq`` axis with two interchangeable engines: ring
attention (``ring.py``, n ppermute hops) and Ulysses all-to-all
(``ulysses.py``, 2 collectives + dense local attention). Pipeline
parallelism gets a minimal GPipe mechanism over the ``pipe`` axis
(``pipeline.py``); expert parallelism a minimal all_to_all MoE dispatch
over the ``expert`` axis (``expert.py``).
"""

from .compress import (
    compressed_allreduce,
    ddp_overlap_scan,
    hlo_comms_evidence,
    validate_ddp_mesh,
    wire_bytes_per_step,
)
from .expert import expert_apply, stack_expert_params
from .overlap import hlo_overlap_evidence, overlap_scan, validate_overlap_mesh
from .pipeline import pipeline_apply, stack_stage_params
from .ring import ring_attention, ring_attention_local
from .schedule import (
    DdpSchedule,
    FsdpSchedule,
    PlainSchedule,
    decomposed_scan,
    hlo_composed_evidence,
    stacked_tp_specs,
    validate_schedule_mesh,
)
from .sharding import (
    DEFAULT_RULES,
    active_rules,
    describe,
    fsdp_reshard,
    fsdp_split_dim,
    logical_shardings,
    shard_tree,
    zero1_reshard,
)
from .ulysses import ulysses_attention

__all__ = [
    "DEFAULT_RULES",
    "DdpSchedule",
    "FsdpSchedule",
    "PlainSchedule",
    "active_rules",
    "compressed_allreduce",
    "ddp_overlap_scan",
    "decomposed_scan",
    "describe",
    "hlo_composed_evidence",
    "stacked_tp_specs",
    "validate_schedule_mesh",
    "expert_apply",
    "hlo_comms_evidence",
    "validate_ddp_mesh",
    "wire_bytes_per_step",
    "fsdp_reshard",
    "fsdp_split_dim",
    "hlo_overlap_evidence",
    "logical_shardings",
    "overlap_scan",
    "stack_expert_params",
    "pipeline_apply",
    "ring_attention",
    "ring_attention_local",
    "stack_stage_params",
    "shard_tree",
    "ulysses_attention",
    "validate_overlap_mesh",
    "zero1_reshard",
]
