"""Parallelism: sharding rules (dp/tp/sp over the mesh) + ring attention.

The reference's parallel surface is NCCL data parallelism only
(SURVEY.md §2b); here data parallelism is the ``data`` mesh axis, tensor
parallelism the ``model`` axis (``sharding.py``), and sequence/context
parallelism the ``seq`` axis with ring attention (``ring.py``).
"""

from .ring import ring_attention, ring_attention_local
from .sharding import (
    DEFAULT_RULES,
    active_rules,
    describe,
    logical_shardings,
    shard_tree,
)

__all__ = [
    "DEFAULT_RULES",
    "active_rules",
    "describe",
    "logical_shardings",
    "ring_attention",
    "ring_attention_local",
    "shard_tree",
]
