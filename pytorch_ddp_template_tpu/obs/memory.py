"""Memory X-ray: HBM accounting, live-buffer forensics, capacity tripwires.

The fourth observability layer (r15). r12 answers "is the step healthy?",
r13 "where does the time go?", r14 "which host is sick?" — this module
answers "**where do the bytes go, and how close is the run to the HBM
cliff?**". The question decides what is *runnable* long before FLOPs do
(the remat lineage: Chen et al. 2016, "Training Deep Nets with Sublinear
Memory Cost" — the compile-time memory plan, not the compute, picks the
feasible configurations), and every open ROADMAP item is memory-gated:
a paged KV cache is sized against real headroom, reshard-on-restore must
pick a mesh that *fits*, and the int8-KV claim is a memory number the
production loop previously could not measure at all (the only in-tree
memory evidence was bench-only ``memory_analysis`` live-range checks,
r8/r10).

Three coordinated pieces:

- **Compile-time memory report** (:func:`static_memory_model`, riding the
  existing ``_startup_reports`` AOT compile under ``--mem_report`` /
  ``--perf_report`` / ``--hlo_report``): ``compiled.memory_analysis()``
  split into argument / output / temp / generated-code / aliased bytes
  plus the projected per-device peak, cross-referenced with a **donation
  audit** (:func:`donation_audit`) that walks the jitted step's
  ``lowered.args_info`` and names every train-state leaf that is NOT
  donated — an undonated state is a silently *doubled* resident state
  footprint (old + new buffers live across the step). The audit also
  cross-checks XLA's realised aliasing (``alias_size_in_bytes``) against
  the donated bytes: donation *requested* but not *honoured* (layout
  mismatch) is the same doubling wearing a quieter hat.
- **Runtime HBM watermark** (:class:`MemoryMonitor`): polls
  ``device.memory_stats()`` on the telemetry **drain thread** (the r6/r14
  contract — nothing on the hot loop) at the perf/logging cadence,
  emitting ``kind="mem"`` records with per-device bytes-in-use / peak /
  limit, a rolling high watermark, and a **per-phase peak attribution**
  sampled against the r13 named loop phases
  (``utils/profiler.current_phase``). Backends without ``memory_stats``
  (CPU) degrade to the static compile-time model — reported as the
  *projection* it is, never dressed up as a measurement.
- **Capacity tripwires + forensics**: projected peak above
  ``--mem_budget_frac`` (default 0.9) of the device limit logs a named
  warning at startup; a *measured* watermark above the same budget feeds
  the r12 sentry as an ``external_trigger(kind="mem_pressure")`` (one
  verdict per pressure episode, re-armed on recovery — the r14 straggler
  convention), so the standard triage bundle lands with the numbers in
  ``trigger.json``. An allocation-failure/OOM exception in the loop dumps
  a **memory forensics bundle** through the existing flight-recorder
  machinery: a live-buffer census (:func:`live_buffer_census` over
  ``jax.live_arrays()``, bucketed by shape × dtype × sharding), the
  compile-time split, and the last K ``mem`` records.

Honesty discipline (the r13 convention): every figure is labelled with
its provenance (``mem_measured`` 1.0 = ``memory_stats``, 0.0 = the static
model), missing backend support yields *no* figure rather than an
invented one, and the census reports logical (global) bytes per array —
the per-device share is the sharding's business, recorded next to it.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable

from ..utils.logging import get_logger

log = get_logger(__name__)

#: ``kind="mem"`` records kept for the forensics bundle (the flight-
#: recorder ring convention: the last K, not a session)
MEM_RING = 64

#: census buckets reported (largest-bytes first); the tail is summed,
#: never silently dropped
CENSUS_TOP = 64

#: message fragments that mark an exception as an allocation failure —
#: the forensics-bundle trigger (PJRT spells OOM several ways). The
#: bare "OOM" acronym is matched on word boundaries only (below): a
#: crash merely *mentioning* BLOOM or ZOOM must not get memory triage
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
                "out of memory", "Failed to allocate",
                "Allocation failure", "exceeds the memory capacity")

_OOM_WORD = None  # compiled lazily; regex import kept off the hot path


def looks_like_oom(exc: BaseException) -> bool:
    """True when ``exc`` smells like an allocation failure (a
    ``MemoryError``, or a runtime error carrying one of the PJRT/XLA
    OOM spellings) — the gate for dumping memory forensics into a crash
    bundle even when no :class:`MemoryMonitor` is configured."""
    if isinstance(exc, MemoryError):
        return True
    try:
        msg = f"{type(exc).__name__}: {exc}"
    except Exception:  # noqa: BLE001 - a broken __str__ on the crashing
        #               exception must not mask the crash (this helper
        #               runs inside the engine's crash handler, BEFORE
        #               its best-effort dump guard)
        return False
    if any(m in msg for m in _OOM_MARKERS):
        return True
    global _OOM_WORD
    if _OOM_WORD is None:
        import re

        _OOM_WORD = re.compile(r"\bOOM\b")
    return _OOM_WORD.search(msg) is not None


# -- compile-time accounting ------------------------------------------------

def compile_memory_split(compiled) -> dict[str, Any] | None:
    """The executable's own memory plan, split the way XLA accounts it:
    ``compiled.memory_analysis()`` → argument / output / temp /
    generated-code / aliased bytes plus the projected resident peak
    (arguments + outputs − aliased + temps + code: aliased output bytes
    reuse their argument's buffer, so they count once). Per-device
    figures — the executable is the per-device program.

    Returns None when the backend exposes no analysis (best-effort by
    the same rule as :func:`obs.attribution.cost_of`): **no figure is
    ever invented**.
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - not all PJRT backends implement it
        return None
    if ma is None:
        return None
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "generated_code_bytes": "generated_code_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
    }
    out: dict[str, Any] = {}
    for key, attr in fields.items():
        v = getattr(ma, attr, None)
        if v is None:
            return None  # a partial analysis is not an analysis
        out[key] = int(v)
    out["projected_peak_bytes"] = (
        out["argument_bytes"] + out["output_bytes"] - out["alias_bytes"]
        + out["temp_bytes"] + out["generated_code_bytes"])
    return out


def _leaf_bytes(info: Any) -> int:
    """Byte size of one ``ArgInfo`` leaf (0 when the aval is opaque)."""
    import numpy as np

    aval = getattr(info, "aval", None) or getattr(info, "_aval", None)
    try:
        return int(aval.size) * int(np.dtype(aval.dtype).itemsize)
    except Exception:  # noqa: BLE001
        return 0


def donation_audit(args_info, donate_argnums: tuple[int, ...] = (0,),
                   max_paths: int = 16) -> dict[str, Any]:
    """Walk the jitted step's ``lowered.args_info`` and account buffer
    donation over the arguments in ``donate_argnums`` (the train state is
    argument 0 by the ``make_train_step`` contract).

    A train-state leaf that is **not** donated keeps its input buffer
    alive across the step while the output allocates a fresh one — the
    state footprint silently doubles. The audit names such leaves
    (bounded by ``max_paths``) so the engine can WARN with the paths, not
    just a count. ``args_info`` may be None (older jax, wrapped steps):
    the audit then reports itself unavailable instead of guessing.
    """
    if args_info is None:
        return {"available": False}
    import jax.tree_util as jtu

    try:
        donated = undonated = 0
        donated_bytes = undonated_bytes = 0
        paths: list[str] = []
        for argnum in donate_argnums:
            subtree = args_info[0][argnum]
            for path, info in jtu.tree_leaves_with_path(subtree):
                nbytes = _leaf_bytes(info)
                if getattr(info, "donated", False):
                    donated += 1
                    donated_bytes += nbytes
                else:
                    undonated += 1
                    undonated_bytes += nbytes
                    if len(paths) < max_paths:
                        paths.append(jtu.keystr(path))
        return {
            "available": True,
            "donated_leaves": donated,
            "donated_bytes": donated_bytes,
            "undonated_leaves": undonated,
            "undonated_bytes": undonated_bytes,
            "undonated_paths": paths,
        }
    except Exception:  # noqa: BLE001 - an audit must never cost the run
        log.exception("donation audit failed")
        return {"available": False}


def static_memory_model(compiled, args_info=None,
                        donate_argnums: tuple[int, ...] = (0,)
                        ) -> dict[str, Any]:
    """The compile-time memory report: the :func:`compile_memory_split`
    plus the :func:`donation_audit`, cross-referenced — ``donation_honoured``
    is False when donation was *requested* for more bytes than XLA
    actually aliased (``alias_bytes`` well short of ``donated_bytes``
    means a layout/sharding mismatch quietly kept both buffers live).
    JSON-ready; never raises."""
    split = compile_memory_split(compiled)
    audit = donation_audit(args_info, donate_argnums)
    model: dict[str, Any] = {
        "available": split is not None,
        "split": split,
        "donation": audit,
    }
    if split is not None and audit.get("available"):
        requested = audit["donated_bytes"]
        # tolerance: padding/layout can legally shave a few percent
        model["donation_honoured"] = bool(
            requested == 0 or split["alias_bytes"] >= 0.5 * requested)
    return model


def donation_warnings(model: dict[str, Any]) -> list[str]:
    """Human warning strings for a :func:`static_memory_model` whose
    donation story doubles the state footprint (empty = clean)."""
    warnings: list[str] = []
    audit = model.get("donation") or {}
    if audit.get("available") and audit.get("undonated_leaves", 0) > 0:
        warnings.append(
            f"donation audit: {audit['undonated_leaves']} train-state "
            f"leaves ({audit['undonated_bytes'] / 1e6:.1f} MB) are NOT "
            "donated — the old and new state buffers both stay resident "
            "across the step (a silently doubled state footprint); "
            "first paths: " + ", ".join(audit.get("undonated_paths", [])))
    if model.get("donation_honoured") is False:
        split = model.get("split") or {}
        warnings.append(
            "donation audit: donation was requested for "
            f"{(audit.get('donated_bytes') or 0) / 1e6:.1f} MB but XLA "
            f"aliased only {split.get('alias_bytes', 0) / 1e6:.1f} MB — "
            "unhonoured donation (layout/sharding mismatch?) keeps both "
            "buffers live, same doubled footprint")
    return warnings


# -- live-buffer forensics --------------------------------------------------

def live_buffer_census(arrays=None, top: int = CENSUS_TOP) -> dict[str, Any]:
    """Bucket the process's live jax arrays by (shape, dtype, sharding):
    the "where did the bytes go" answer an OOM post-mortem starts from.

    ``bytes`` per bucket is the *logical* (global) array size — under a
    sharded runtime each device holds its shard; the sharding string
    next to it says how to divide. Buckets beyond ``top`` are summed
    into ``truncated`` (bounded output, nothing silently dropped).
    Never raises; arrays deleted mid-walk are skipped.
    """
    if arrays is None:
        import jax

        try:
            arrays = jax.live_arrays()
        except Exception:  # noqa: BLE001
            return {"available": False, "n_arrays": 0, "total_bytes": 0,
                    "buckets": []}
    buckets: dict[tuple, dict[str, Any]] = {}
    n = 0
    total = 0
    for a in arrays:
        try:
            if getattr(a, "is_deleted", lambda: False)():
                continue
            sharding = getattr(a, "sharding", None)
            spec = getattr(sharding, "spec", None)
            sh = (str(spec) if spec is not None
                  else type(sharding).__name__ if sharding is not None
                  else "unknown")
            key = (str(tuple(a.shape)), str(a.dtype), sh)
            nbytes = int(a.nbytes)
        except Exception:  # noqa: BLE001 - a half-dead array is not news
            continue
        n += 1
        total += nbytes
        b = buckets.setdefault(key, {
            "shape": key[0], "dtype": key[1], "sharding": key[2],
            "count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += nbytes
    ordered = sorted(buckets.values(), key=lambda b: -b["bytes"])
    head, tail = ordered[:top], ordered[top:]
    return {
        "available": True,
        "n_arrays": n,
        "total_bytes": total,
        "buckets": head,
        "truncated": {
            "buckets": len(tail),
            "bytes": sum(b["bytes"] for b in tail),
        } if tail else None,
    }


# -- runtime watermark ------------------------------------------------------

def device_memory_rows(devices) -> list[dict[str, Any]] | None:
    """Per-device HBM stats via ``device.memory_stats()`` — one row per
    device that reports them, None when **no** device does (the CPU
    backend): the caller degrades to the static model rather than
    publishing zeros as a measurement."""
    rows: list[dict[str, Any]] = []
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - per-device, not per-backend
            stats = None
        if not stats:
            continue
        rows.append({
            "device": i,
            "kind": getattr(d, "device_kind", "unknown"),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get(
                "peak_bytes_in_use", stats.get("bytes_in_use", 0))),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return rows or None


class MemoryMonitor:
    """Runtime HBM watermark + capacity tripwire + forensics source.

    Threading contract (the r12/r14 pattern): :meth:`observe` runs on
    the telemetry drain thread (``kind="mem"`` records route here — the
    poll is host-side PJRT bookkeeping, not a device computation, but it
    still does not belong on the hot loop); ``state()``/``forensics()``
    read under the same lock from any thread. ``poll`` is injectable
    (tests and the bench's injected-pressure leg fake a device's
    ``memory_stats``); the default reads this process's local devices.
    ``on_pressure(step, verdict)`` fires ONCE per pressure episode on
    the drain thread — the engine points it at the sentry's
    ``external_trigger(kind="mem_pressure")``.
    """

    def __init__(self, devices=(), *, budget_frac: float = 0.9,
                 on_pressure: Callable[[int, dict[str, Any]], None]
                 | None = None,
                 poll: Callable[[], list[dict[str, Any]] | None]
                 | None = None,
                 ring: int = MEM_RING):
        if not (0.0 < budget_frac <= 1.0):
            raise ValueError(f"mem budget_frac must be in (0, 1], got "
                             f"{budget_frac}")
        self.devices = list(devices)
        self.budget_frac = float(budget_frac)
        self.on_pressure = on_pressure
        self._poll = poll or (lambda: device_memory_rows(self.devices))
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(int(ring), 8))
        #: the compile-time model (set by the engine's _startup_reports
        #: when the AOT compile ran; None = runtime-only monitoring)
        self.static_model: dict[str, Any] | None = None
        self.watermark_bytes = 0.0   # max bytes_in_use observed
        self.peak_bytes = 0.0        # max backend-reported peak
        self.limit_bytes: float | None = None
        self.phase_peaks: dict[str, float] = {}
        self.polls = 0
        self._pressure_active = False
        self._static_logged = False
        self._last_rows: list[dict[str, Any]] | None = None

    def set_static_model(self, model: dict[str, Any] | None) -> None:
        with self._lock:
            self.static_model = model

    # -- drain-thread side -------------------------------------------------
    def observe(self, step: int, scalars: dict[str, Any] | None = None
                ) -> dict[str, Any] | None:
        """One watermark sample; returns the flat ``mem`` record for the
        metrics writer (None when there is nothing honest to report).
        Never raises."""
        del scalars  # the loop's emit carries no payload; the poll is here
        try:
            return self._observe(int(step))
        except Exception:  # noqa: BLE001 - the watchtower must never
            #               kill the telemetry drain
            log.exception("mem record dropped")
            return None

    def _observe(self, step: int) -> dict[str, Any] | None:
        from ..utils.profiler import current_phase

        phase = current_phase()
        rows = self._poll()
        rec: dict[str, Any] = {}
        verdict: dict[str, Any] | None = None
        with self._lock:
            self.polls += 1
            if rows:
                self._last_rows = rows
                in_use = max(r["bytes_in_use"] for r in rows)
                peak = max(r["peak_bytes_in_use"] for r in rows)
                limits = [r["bytes_limit"] for r in rows
                          if r["bytes_limit"] > 0]
                limit = min(limits) if limits else None
                self.watermark_bytes = max(self.watermark_bytes,
                                           float(in_use))
                self.peak_bytes = max(self.peak_bytes, float(peak))
                if limit is not None:
                    self.limit_bytes = float(limit)
                self.phase_peaks[phase] = max(
                    self.phase_peaks.get(phase, 0.0), float(in_use))
                import numpy as np

                rec = {
                    "mem_measured": 1.0,
                    "mem_bytes_in_use": float(in_use),
                    "mem_peak_bytes": float(peak),
                    "mem_watermark_bytes": self.watermark_bytes,
                    # per-device vector: as an ndarray it rides the
                    # JSONL-only vector channel (the per_layer_grad_norm
                    # convention — a Python list would be MEANED by the
                    # sink's loss-window rule)
                    "mem_bytes_in_use_per_device": np.asarray(
                        [float(r["bytes_in_use"]) for r in rows]),
                }
                if limit is not None:
                    frac = in_use / limit
                    rec["mem_limit_bytes"] = float(limit)
                    rec["mem_frac_of_limit"] = round(frac, 4)
                    bar = self.budget_frac
                    if frac > bar and not self._pressure_active:
                        # one verdict per pressure episode; re-armed on
                        # recovery below the bar (the r14 straggler
                        # convention — an hour of pressure is one
                        # bundle, not one per cadence tick)
                        self._pressure_active = True
                        worst = max(rows,
                                    key=lambda r: r["bytes_in_use"])
                        verdict = {
                            "bytes_in_use": int(in_use),
                            "bytes_limit": int(limit),
                            "frac_of_limit": round(frac, 4),
                            "budget_frac": bar,
                            "device": int(worst["device"]),
                            "watermark_bytes": int(self.watermark_bytes),
                            "phase": phase,
                        }
                    elif frac <= bar:
                        self._pressure_active = False
            else:
                # degrade to the compile-time model: report the
                # PROJECTION as a projection (mem_measured 0.0), or
                # nothing at all when no model exists — never a fake 0B
                # watermark
                split = (self.static_model or {}).get("split")
                if not split:
                    return None
                if not self._static_logged:
                    self._static_logged = True
                    log.info(
                        "device memory_stats unavailable on this backend; "
                        "mem records carry the static compile-time model "
                        "only (logged once)")
                rec = {
                    "mem_measured": 0.0,
                    "mem_projected_peak_bytes":
                        float(split["projected_peak_bytes"]),
                    "mem_temp_bytes": float(split["temp_bytes"]),
                    "mem_argument_bytes": float(split["argument_bytes"]),
                }
            self._ring.append({"step": step, "phase": phase, **rec})
        if verdict is not None and self.on_pressure is not None:
            self.on_pressure(step, verdict)
        return rec

    # -- tripwires ---------------------------------------------------------
    def startup_warnings(self) -> list[str]:
        """The compile-time capacity tripwire: projected peak (static
        model, plus any already-measured baseline in-use) against the
        device limit. Empty when no limit is known (CPU) or the budget
        holds — a missing limit is never treated as a pass *or* a fail,
        it is simply unmeasurable."""
        with self._lock:
            split = (self.static_model or {}).get("split")
            limit = self.limit_bytes
            baseline = self.watermark_bytes
        if not split:
            return []
        if limit is None:
            rows = self._poll()
            if rows:
                limits = [r["bytes_limit"] for r in rows
                          if r["bytes_limit"] > 0]
                limit = min(limits) if limits else None
                baseline = max((r["bytes_in_use"] for r in rows),
                               default=0.0)
        if not limit:
            return []
        projected = split["projected_peak_bytes"] + max(
            baseline - split["argument_bytes"], 0.0)
        frac = projected / limit
        if frac <= self.budget_frac:
            return []
        return [
            f"memory budget tripwire: projected peak "
            f"{projected / 1e9:.2f} GB is {100 * frac:.1f}% of the "
            f"{limit / 1e9:.2f} GB device limit (budget "
            f"--mem_budget_frac={self.budget_frac:g}) — args "
            f"{split['argument_bytes'] / 1e9:.2f} GB + temps "
            f"{split['temp_bytes'] / 1e9:.2f} GB + outputs/code; an "
            "allocation failure mid-run is likely (shrink the batch, "
            "enable --remat, or shard further)"]

    # -- consumers ---------------------------------------------------------
    def peak_hbm_bytes(self) -> float | None:
        """The figure stamped into ``perf_baseline.json``: the measured
        watermark when one exists, else the static projection, else
        None (never invented)."""
        with self._lock:
            if self.peak_bytes > 0:
                return float(self.peak_bytes)
            if self.watermark_bytes > 0:
                return float(self.watermark_bytes)
            split = (self.static_model or {}).get("split")
            if split:
                return float(split["projected_peak_bytes"])
        return None

    def wire_signals(self) -> dict[str, float]:
        """This host's memory columns for the fleet wire vector (zeros
        when unmeasured — the documented zero-fill tolerance; a host
        leaking memory is a straggler-to-be, so the fleet table wants
        these next to the step walls)."""
        with self._lock:
            last = self._ring[-1] if self._ring else {}
            return {
                "mem_bytes_in_use": float(
                    last.get("mem_bytes_in_use", 0.0)),
                "mem_frac_of_limit": float(
                    last.get("mem_frac_of_limit", 0.0)),
            }

    def records(self) -> list[dict[str, Any]]:
        """Ring snapshot, oldest first (the forensics bundle's last-K)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def state(self) -> dict[str, Any]:
        """JSON-ready snapshot for ``/status`` and ``/metrics``."""
        with self._lock:
            return {
                "budget_frac": self.budget_frac,
                "watermark_bytes": self.watermark_bytes,
                "peak_bytes": self.peak_bytes,
                "limit_bytes": self.limit_bytes,
                "pressure_active": self._pressure_active,
                "polls": self.polls,
                "phase_peaks": dict(self.phase_peaks),
                "devices": ([dict(r) for r in self._last_rows]
                            if self._last_rows else None),
                "static": self.static_model,
                "ring_len": len(self._ring),
            }

    def forensics(self) -> dict[str, Any]:
        """The memory forensics payload (``memory.json`` in a triage
        bundle): live-buffer census + compile-time split + the last K
        mem records + watermarks."""
        return forensics_payload(self)


def forensics_payload(monitor: MemoryMonitor | None = None
                      ) -> dict[str, Any]:
    """Build the ``memory.json`` bundle artifact. Works without a
    monitor (an OOM crash on a run without ``--mem_report`` still gets
    the census — the live arrays exist regardless)."""
    payload: dict[str, Any] = {"census": live_buffer_census()}
    if monitor is not None:
        with monitor._lock:
            payload.update({
                "static_model": monitor.static_model,
                "watermark_bytes": monitor.watermark_bytes,
                "peak_bytes": monitor.peak_bytes,
                "limit_bytes": monitor.limit_bytes,
                "phase_peaks": dict(monitor.phase_peaks),
                "records": [dict(r) for r in monitor._ring],
            })
    else:
        payload.update({"static_model": None, "records": []})
    return payload
