"""In-step health pack: device-side training-health scalars, zero extra
host syncs.

The jitted train step already produces ``loss``/``grad_norm``/``lr``; this
module adds the rest of the per-iteration health bundle the large-scale
stacks log every step (Megatron-LM's grad-norm/num-zeros discipline,
PyTorch DDP's detect-anomaly lineage):

- ``param_norm`` — global L2 of the weights the step consumed;
- ``update_ratio`` — ``‖Δw‖ / ‖w‖`` of the applied optimizer update (the
  classic learning-dynamics dial: healthy runs sit around 1e-3-ish;
  collapse and divergence both show here before the loss moves);
- ``nonfinite_loss`` / ``nonfinite_grads`` — element counts of NaN/Inf in
  the loss and the gradient tree (the sentry's hard trigger);
- ``per_layer_grad_norm`` — an ``(L,)`` vector of per-layer grad norms.
  Cheap ONLY under ``--scan_layers``: the stacked ``(L, ...)`` grad
  leaves reduce over their trailing dims in one fused kernel. Unrolled
  models skip it (L separate reductions per leaf family would be real
  work for a per-step metric);
- ``ef_residual_norm`` — global L2 of the error-feedback residual when
  ``--grad_error_feedback`` carries one (a growing residual means the
  compression is no longer telescoping).

Everything is a device array computed inside the jitted step — a handful
of fused reductions next to a backward pass, invisible in step time
(measured: ``BENCH_MODE=obs``) — and rides the r6 ``AsyncTelemetry``
device-array channel to the host, so ``host_overhead_pct`` stays at the
r6 level. Keys are stable: the sentry, the metrics writer and the bench
leg all consume :data:`HEALTH_KEYS`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

#: every key the pack may add to the step metrics (per_layer_grad_norm and
#: ef_residual_norm appear only when their structure exists)
HEALTH_KEYS = (
    "param_norm",
    "update_ratio",
    "nonfinite_loss",
    "nonfinite_grads",
    "per_layer_grad_norm",
    "ef_residual_norm",
)


def _stacked_leaves(tree: Any) -> list[jax.Array]:
    """Leaves living under a scan-over-layers ``"layers"`` dict key —
    the stacked ``(num_layers, ...)`` weight/grad leaves
    (``parallel/stacking.LAYER_AXIS`` naming, established r7)."""
    from ..parallel.stacking import LAYER_AXIS

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        in_stack = any(
            getattr(p, "key", getattr(p, "name", None)) == LAYER_AXIS
            for p in path
        )
        if in_stack and isinstance(leaf, jax.Array) and leaf.ndim >= 1:
            out.append(leaf)
    return out


def _nonfinite_count(tree: Any) -> jax.Array:
    """Total count of non-finite elements across the tree's float leaves
    (int leaves cannot be non-finite; skipping them avoids isfinite on
    integer dtypes)."""
    total = jnp.zeros((), jnp.int32)
    for leaf in jax.tree.leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            total = total + jnp.sum(
                ~jnp.isfinite(leaf), dtype=jnp.int32)
    return total


def health_metrics(*, loss: jax.Array, grads: Any, params: Any,
                   updates: Any, residual: Any = None) -> dict[str, jax.Array]:
    """The device-side health bundle (see module docstring). Call inside
    the jitted step, after the optimizer update is computed; every value
    is a device scalar except ``per_layer_grad_norm`` (an ``(L,)``
    vector, present only when the grad tree carries a scanned layer
    stack — a trace-time structural property, so jit specialises it
    away for unrolled models)."""
    out: dict[str, jax.Array] = {}
    param_norm = optax.global_norm(params)
    out["param_norm"] = param_norm
    out["update_ratio"] = optax.global_norm(updates) / (param_norm + 1e-20)
    out["nonfinite_loss"] = jnp.sum(
        ~jnp.isfinite(loss), dtype=jnp.int32)
    out["nonfinite_grads"] = _nonfinite_count(grads)
    stacked = _stacked_leaves(grads)
    if stacked:
        # each (L, ...) leaf reduces over its trailing dims; summing the
        # per-leaf squares gives the (L,) per-layer global norms in one
        # fused pass over memory the backward just touched
        sq = None
        for g in stacked:
            part = jnp.sum(
                jnp.square(g.astype(jnp.float32)),
                axis=tuple(range(1, g.ndim)))
            sq = part if sq is None else sq + part
        out["per_layer_grad_norm"] = jnp.sqrt(sq)
    if residual is not None:
        out["ef_residual_norm"] = optax.global_norm(residual)
    return out
