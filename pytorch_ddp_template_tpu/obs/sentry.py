"""Anomaly sentry + flight recorder: the host side of the health pack.

The sentry consumes the per-step health records the telemetry drain
thread converts (``kind="health"`` — device arrays in, host floats out;
see ``train/metrics.py``) and keeps the last ``window`` of them in a ring
buffer. Two trigger classes:

- **non-finite**: any of ``nonfinite_loss``/``nonfinite_grads`` > 0, or a
  drained ``loss``/``grad_norm`` that is itself NaN/Inf — fires
  immediately, no history needed (the r9 lineage: a NaN'd replica must
  not keep training);
- **spike**: rolling median/MAD on ``loss`` and ``grad_norm`` over the
  ring — robust statistics, so the detector survives the heavy-tailed
  step-to-step noise a mean/std z-score false-positives on. Fires when
  ``|x - median| > threshold * scale`` with
  ``scale = max(1.4826·MAD, 5%·|median|, 1e-6)`` (the MAD floor keeps a
  flat-lined loss from alarming on micro-wiggle), after ``min_history``
  finite samples exist.

Threading contract: ``observe`` runs on the telemetry drain thread;
``poll_trigger`` on the train loop. The handoff is one attribute write
guarded by a lock; the loop polls once per iteration (an attribute read —
nothing on the hot path).

The :class:`FlightRecorder` writes the triage bundle — the data you wish
you had AFTER a run died — into ``<output_dir>/flight_records/``:
ring-buffer JSONL (the last K steps of health scalars), the sharding/
schedule ``describe()`` snapshot, the full config, the replicated-state
divergence fingerprint, and the trigger record itself. The engine then
arms a ``TraceWindow`` over the next few steps into the same directory,
so the profile of the sick step pattern rides along. All JSON goes
through ``utils.serialization.json_sanitize`` — the bundle's whole point
is non-finite values, and it must stay parseable anyway.
"""

from __future__ import annotations

import json
import math
import statistics
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any

from ..utils.logging import get_logger
from ..utils.serialization import json_sanitize

log = get_logger(__name__)

#: steps of jax-profiler trace the engine captures after a trigger (into
#: the bundle directory) — small by design: the pattern, not a session
FLIGHT_TRACE_STEPS = 4

#: every file a complete bundle contains (the bench obs leg and the tests
#: assert against this list — keep it in sync with FlightRecorder.dump)
BUNDLE_FILES = ("trigger.json", "ring.jsonl", "config.json",
                "describe.json", "fingerprint.json")

#: keys the spike detector watches (must be in the per-step health feed)
SPIKE_KEYS = ("loss", "grad_norm")


class AnomalySentry:
    """Rolling-statistics anomaly detector over drained health records."""

    def __init__(self, mode: str = "warn", *, window: int = 128,
                 threshold: float = 10.0, min_history: int = 16):
        if mode not in ("warn", "halt"):
            raise ValueError(f"unknown anomaly mode {mode!r}; "
                             "expected warn | halt")
        self.mode = mode
        self.threshold = float(threshold)
        self.min_history = int(min_history)
        self._ring: deque[tuple[int, dict[str, Any]]] = deque(
            maxlen=max(int(window), 8))
        self._lock = threading.Lock()
        self._trigger: dict[str, Any] | None = None
        self._delivered = False

    # -- drain-thread side -------------------------------------------------
    def observe(self, step: int, scalars: dict[str, Any]) -> None:
        """Feed one step's host-converted health record; runs on the
        telemetry drain thread. Never raises (a broken record must not
        kill telemetry — it IS the failure path)."""
        try:
            reasons = self._detect(scalars)
        except Exception:  # noqa: BLE001
            log.exception("anomaly detection failed on a record")
            reasons = []
        first = False
        with self._lock:
            self._ring.append((int(step), dict(scalars)))
            if reasons and self._trigger is None:
                first = True
                self._trigger = {
                    "step": int(step),
                    "reasons": reasons,
                    "kind": "anomaly",
                    "scalars": dict(scalars),
                    "mode": self.mode,
                    "time": time.time(),
                }
        if first:
            # visible immediately, even before the loop polls — but only
            # for the FIRST trigger: a permanently-NaN'd run keeps
            # producing reasons every step, and one error line per step
            # for the rest of a long warn-mode run is log flooding, not
            # observability (the ring buffer still records every step)
            log.error("anomaly sentry triggered",
                      {"step": int(step), "reasons": reasons})

    def external_trigger(self, step: int, reasons: list[str], *,
                         kind: str = "external",
                         scalars: dict[str, Any] | None = None) -> None:
        """Inject a trigger from OUTSIDE the health feed — the r14 fleet
        watchtower's straggler verdict (``kind="straggler"``) rides this
        into the standard triage path: the loop's next poll dumps the
        bundle with this kind and these reasons in ``trigger.json``.
        Same first-trigger-wins contract as :meth:`observe`; safe from
        any thread; never raises."""
        first = False
        with self._lock:
            if self._trigger is None:
                first = True
                self._trigger = {
                    "step": int(step),
                    "reasons": list(reasons),
                    "kind": kind,
                    "scalars": dict(scalars or {}),
                    "mode": self.mode,
                    "time": time.time(),
                }
        if first:
            log.error(f"{kind} sentry trigger",
                      {"step": int(step), "reasons": list(reasons)})
        else:
            # first-trigger-wins gets the bundle, but a second verdict
            # (two hosts confirming in one window) must not vanish —
            # the log is its record
            log.warning(
                f"additional {kind} trigger suppressed (a triage "
                "bundle is already owed to the first trigger)",
                {"step": int(step), "reasons": list(reasons)})

    def _detect(self, scalars: dict[str, Any]) -> list[str]:
        reasons: list[str] = []
        for key in ("nonfinite_loss", "nonfinite_grads"):
            v = scalars.get(key)
            if v is not None and math.isfinite(v) and v > 0:
                reasons.append(f"{key}={int(v)}")
        for key in SPIKE_KEYS:
            x = scalars.get(key)
            if x is None:
                continue
            x = float(x)
            if not math.isfinite(x):
                reasons.append(f"{key} non-finite ({x!r})")
                continue
            hist = [float(r[1][key]) for r in self._ring
                    if key in r[1] and isinstance(r[1][key], (int, float))
                    and math.isfinite(float(r[1][key]))]
            if len(hist) < self.min_history:
                continue
            med = statistics.median(hist)
            mad = statistics.median(abs(h - med) for h in hist)
            scale = max(1.4826 * mad, 0.05 * abs(med), 1e-6)
            if abs(x - med) > self.threshold * scale:
                reasons.append(
                    f"{key} spike: {x:.6g} vs rolling median {med:.6g} "
                    f"(mad {mad:.3g}, threshold {self.threshold:g}x)")
        return reasons

    # -- train-loop side ---------------------------------------------------
    def poll_trigger(self) -> dict[str, Any] | None:
        """The trigger record, exactly once (later polls return None);
        an attribute read + lock — safe to call every iteration."""
        if self._trigger is None or self._delivered:
            return None
        with self._lock:
            if self._trigger is None or self._delivered:
                return None
            self._delivered = True
            return dict(self._trigger)

    @property
    def triggered(self) -> bool:
        return self._trigger is not None

    def records(self) -> list[dict[str, Any]]:
        """Ring-buffer snapshot, oldest first, one dict per step."""
        with self._lock:
            return [{"step": s, **r} for s, r in self._ring]

    def state(self) -> dict[str, Any]:
        """JSON-ready snapshot for the ``/status`` endpoint (the
        trigger dict itself, not just the flag — an operator hitting
        the endpoint after a trigger wants the reasons)."""
        with self._lock:
            return {
                "mode": self.mode,
                "triggered": self._trigger is not None,
                "trigger": (dict(self._trigger)
                            if self._trigger is not None else None),
                "ring_len": len(self._ring),
            }


class FlightRecorder:
    """Writes triage bundles under ``<output_dir>/flight_records/``."""

    def __init__(self, output_dir: str | Path):
        self.base = Path(output_dir) / "flight_records"

    def dump(self, *, step: int, trigger: dict[str, Any],
             ring: list[dict[str, Any]],
             config: Any = None,
             describe_snapshot: dict[str, Any] | None = None,
             fingerprint: list[float] | None = None,
             extra: dict[str, Any] | None = None) -> Path:
        """Write one complete bundle; returns its directory. Each file is
        written best-effort and independently — a failure in one artifact
        (e.g. a describe() that raises on poisoned params) must not cost
        the others. ``extra`` maps additional artifact filenames to
        JSON-ready payloads (the r15 memory forensics rides here as
        ``memory.json``); :data:`BUNDLE_FILES` stays the minimum set."""
        # atomic claim, not check-then-act: a fleet-replicated trigger
        # (the r14 straggler verdict, a replicated-NaN anomaly) dumps
        # from EVERY host at once, and on a shared output_dir a bare
        # exists()/mkdir pair would FileExistsError the race losers and
        # cost their bundles — mkdir itself is the test-and-set
        d = self.base / f"step_{step:08d}"
        suffix = 0
        while True:
            try:
                d.mkdir(parents=True)
                break
            except FileExistsError:  # taken (re-trigger or peer host):
                suffix += 1          # claim the next suffix, clobber
                d = self.base / f"step_{step:08d}.{suffix}"  # nothing

        def _write(name: str, payload: Any) -> None:
            try:
                if name.endswith(".jsonl"):
                    text = "\n".join(
                        json.dumps(json_sanitize(r), allow_nan=False)
                        for r in payload) + "\n"
                else:
                    body = (json_sanitize(payload)
                            if isinstance(payload, dict) else payload)
                    text = json.dumps(body, indent=2, default=str,
                                      allow_nan=False)
                (d / name).write_text(text)
            except Exception:  # noqa: BLE001 - partial bundle > no bundle
                log.exception(f"flight record artifact {name} failed")

        _write("trigger.json", trigger)
        _write("ring.jsonl", ring)
        if config is not None and hasattr(config, "to_json"):
            try:
                (d / "config.json").write_text(config.to_json())
            except Exception:  # noqa: BLE001
                log.exception("flight record artifact config.json failed")
        else:
            _write("config.json", config)
        _write("describe.json", describe_snapshot)
        _write("fingerprint.json",
               {"fingerprint": fingerprint,
                "note": "per-leaf (sum, l2) digest of the replicated "
                        "params (utils/divergence.fingerprint); null when "
                        "the state was not safely readable at dump time"})
        for name, payload in (extra or {}).items():
            _write(name, payload)
        log.warning("flight record dumped", {"dir": str(d)})
        return d
