"""Goodput ledger: every second of the run, bucketed and durable.

Throughput metrics describe the *steps that ran*; on a preemptible fleet
the number that decides the bill is what fraction of wall-clock was
productive training at all — the rest went to compiles, checkpoint
saves, restores, input stalls, or simply being dead between preemption
and reschedule. The big production stacks account this as *ML goodput*;
this module is that ledger, sized for this engine:

- :data:`BUCKETS` — ``productive_step`` (loop iterations doing training
  work), ``compile`` (the startup trace+compile and any mid-run
  re-trace), ``checkpoint_save`` (synchronous save scheduling + the
  final durability wait), ``restore`` (checkpoint restore + state
  init), ``input_stall`` (the loop blocked on the loader),
  ``eval`` (in-loop evaluation), ``halted`` (wall-clock lost BETWEEN
  attempts: preemption to reschedule, measured as the gap from the
  previous attempt's last heartbeat to this attempt's start), and
  ``other`` (side work that fits nowhere else, e.g. the divergence
  allgather).
- **Restart accumulation** — the ledger persists to
  ``<output_dir>/goodput.json`` and every new attempt LOADS the previous
  totals first, so an elastic run that was preempted five times reports
  its true end-to-end goodput, not the last attempt's. The per-attempt
  split is kept alongside the cumulative totals.

Accounting is wall-clock honest at the second level, not trace-exact:
each loop iteration's interval is split input-first (measured), then
explicit side-work durations (measured), remainder productive. Overlap
(an async checkpoint draining under compute) therefore lands in
``productive_step`` — correctly: the run WAS training during it.

Host-0 writes the file; every process keeps the in-memory ledger (the
engine logs the summary everywhere, rank-tagged).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

from ..utils import get_logger, is_main_process
from ..utils.serialization import json_sanitize

log = get_logger(__name__)

#: every bucket the ledger tracks; ``goodput`` = productive_step over the
#: sum of them all. r18 splits two elastic buckets out of their old
#: homes: ``hot_checkpoint_save`` (the --hot_save_steps local-disk tier,
#: previously indistinguishable inside ``checkpoint_save``) and
#: ``evict_resume`` (downtime the SUPERVISOR chose — checkpoint → evict
#: → resume — previously booked as generic ``halted`` preemption), so
#: the supervisor's cost/benefit is readable straight off goodput.json.
#: r19 adds the serving buckets: ``serve_prefill`` (admission forwards —
#: the TTFT cost) and ``serve_decode`` (per-token steps) — an engine
#: hosting a serving loop meters it with the same ledger the train loop
#: uses, so train-vs-serve wall split reads straight off goodput.json.
#: r20 splits ``serve_draft`` out of decode: the speculative draft
#: model's wall (prefill + proposal loop, ``serve/spec.py``) — the
#: wager's cost side, so draft-spend vs verify-win reads off the ledger
BUCKETS = ("productive_step", "compile", "checkpoint_save",
           "hot_checkpoint_save", "restore", "input_stall", "eval",
           "halted", "evict_resume", "serve_prefill", "serve_decode",
           "serve_draft", "other")

FILENAME = "goodput.json"


class GoodputLedger:
    """Accumulate per-bucket wall-clock; persist + merge across restarts."""

    def __init__(self, output_dir: str | Path, *, now: float | None = None):
        self.path = Path(output_dir) / FILENAME
        self._t_start = time.time() if now is None else float(now)
        self._current: dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._prior: dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._prior_attempts: list[dict[str, Any]] = []
        self.attempt = 1
        #: the engine sets this True when the run reached its step budget
        #: (NOT on a SIGTERM/anomaly stop): the flag persists, and the
        #: NEXT attempt then skips the downtime gap — resuming a
        #: finished run with a larger --max_steps days later is a
        #: workflow, not a preemption
        self.completed = False
        #: set True by the supervisor when IT stopped the run
        #: (checkpoint → evict → resume): the next attempt then books
        #: the restart gap as ``evict_resume`` — a cost the supervisor
        #: chose and must answer for — instead of generic ``halted``
        #: preemption downtime
        self.evicted = False
        prior = self._load_prior()
        if prior is not None:
            for b in BUCKETS:
                self._prior[b] = float(prior.get("buckets", {}).get(b, 0.0))
            self._prior_attempts = list(prior.get("attempts_log", []))[-32:]
            self.attempt = int(prior.get("attempt", 0)) + 1
            # downtime between attempts: the previous attempt's last
            # heartbeat to now — the bucket preemption actually costs a
            # fleet. Skipped when the prior attempt finished cleanly (a
            # fresh attempt with no prior file has no downtime either)
            last = prior.get("last_updated")
            if (not prior.get("completed")
                    and isinstance(last, (int, float)) and last > 0):
                gap = self._t_start - float(last)
                if gap < 0:
                    # wall clocks are not monotonic across hosts or
                    # reboots: a restart on a clock-skewed host can see
                    # the prior heartbeat in the FUTURE. Booking that
                    # negative gap would corrupt the halted bucket (and
                    # every ratio derived from the bucket sum) — clamp
                    # to 0 and say so once
                    log.warning(
                        "goodput: prior attempt's last heartbeat is "
                        f"{-gap:.1f}s in the future (clock skew between "
                        "hosts/reboots?); booking 0s of halted downtime "
                        "for this restart instead of a negative gap")
                    gap = 0.0
                # a supervisor-chosen stop books its reschedule gap to
                # its own bucket; organic preemption stays `halted`
                bucket = ("evict_resume" if prior.get("evicted")
                          else "halted")
                self._prior[bucket] += gap

    def _load_prior(self) -> dict[str, Any] | None:
        try:
            if self.path.is_file():
                return json.loads(self.path.read_text())
        except Exception:  # noqa: BLE001 - a corrupt ledger must not kill
            log.exception("goodput.json unreadable; starting a fresh ledger")
        return None

    # -- accounting --------------------------------------------------------
    def add(self, bucket: str, seconds: float) -> None:
        """Add ``seconds`` of wall-clock to ``bucket`` (unknown bucket
        names land in ``other`` rather than raising — the ledger must
        never cost the run it measures)."""
        if seconds <= 0:
            return
        if bucket not in self._current:
            bucket = "other"
        self._current[bucket] += float(seconds)

    def split_iteration(self, dt: float, *, input_s: float = 0.0,
                        compile_s: float = 0.0, save_s: float = 0.0,
                        hot_save_s: float = 0.0, eval_s: float = 0.0,
                        other_s: float = 0.0) -> None:
        """Split one loop-iteration interval ``dt`` across buckets:
        measured components first (clamped so the sum never exceeds
        ``dt``), remainder productive."""
        if dt <= 0:
            return
        remaining = dt
        for bucket, s in (("input_stall", input_s), ("compile", compile_s),
                          ("checkpoint_save", save_s),
                          ("hot_checkpoint_save", hot_save_s),
                          ("eval", eval_s), ("other", other_s)):
            take = min(max(s, 0.0), remaining)
            if take > 0:
                self._current[bucket] += take
                remaining -= take
        if remaining > 0:
            self._current["productive_step"] += remaining

    # -- reporting ---------------------------------------------------------
    def totals(self) -> dict[str, float]:
        """Cumulative buckets: every prior attempt plus this one."""
        return {b: self._prior[b] + self._current[b] for b in BUCKETS}

    def summary(self) -> dict[str, Any]:
        tot = self.totals()
        wall = sum(tot.values())
        return {
            "goodput": round(tot["productive_step"] / wall, 4) if wall else None,
            "wall_s": round(wall, 1),
            "attempt": self.attempt,
            "buckets_s": {b: round(v, 1) for b, v in tot.items()},
        }

    def flush(self, *, min_interval_s: float = 0.0) -> None:
        """Write ``goodput.json`` (host 0 only; best-effort — telemetry
        must never kill training). Called at the perf/logging cadence and
        from the engine's shutdown path.

        ``min_interval_s`` rate-limits mid-run heartbeats: the file's
        ``last_updated`` only needs enough resolution to bound the next
        attempt's downtime gap, and an unconditional write per logging
        interval would dominate sub-ms toy steps (measured in
        BENCH_MODE=perf). Shutdown paths pass the default 0 = always."""
        if not is_main_process():
            return
        now = time.time()
        if min_interval_s > 0 and now - getattr(self, "_last_flush", 0.0) \
                < min_interval_s:
            return
        self._last_flush = now
        tot = self.totals()
        wall = sum(tot.values())
        payload = {
            "schema": "goodput/v1",
            "attempt": self.attempt,
            "completed": bool(self.completed),
            "evicted": bool(self.evicted),
            "goodput": (tot["productive_step"] / wall) if wall else None,
            "wall_s": wall,
            "buckets": tot,
            "current_attempt_buckets": dict(self._current),
            "attempts_log": self._prior_attempts + [{
                "attempt": self.attempt,
                "started": self._t_start,
                "wall_s": sum(self._current.values()),
            }],
            "last_updated": time.time(),
            "note": "buckets accumulate across restarts; 'halted' is the "
                    "wall-clock between one attempt's last heartbeat and "
                    "the next attempt's start (preemption downtime)",
        }
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(json_sanitize(payload), indent=2,
                                      allow_nan=False))
            tmp.replace(self.path)  # atomic: a kill mid-write never leaves
            #                         a truncated ledger for the next attempt
        except Exception:  # noqa: BLE001
            log.exception("goodput.json write failed (ledger kept in memory)")
