"""Observability subsystem (rounds 12-15): the training loop watching
itself.

Nine coordinated pieces:

- :mod:`.health` — in-step device-side health scalars (param/update
  norms, non-finite counts, per-layer grad norms, EF-residual norm)
  riding the r6 async-telemetry channel with zero extra host syncs;
- :mod:`.sentry` — host-side ring buffer + median/MAD anomaly detection
  (``--anomaly {off,warn,halt}``) and the flight-recorder triage bundle
  under ``<output_dir>/flight_records/``;
- :mod:`.hlo_report` — the r8-r11 HLO overlap-evidence walkers factored
  out of bench-only code, plus the ``--hlo_report`` startup schedule
  report and its overlap-regression tripwire;
- :mod:`.attribution` — the r13 step-time X-ray: static cost model
  (FLOPs + wire bytes per step, per mesh axis) from the startup compile
  and the runtime MFU / compute-comm-host-input attribution
  (``--perf_report``);
- :mod:`.goodput` — the wall-clock ledger bucketing every second of the
  run (productive / compile / checkpoint / restore / input-stall /
  halted), persisted to ``goodput.json`` and accumulated across
  restarts;
- :mod:`.memory` — the r15 memory X-ray: compile-time memory split +
  donation audit off the startup AOT compile, the runtime HBM watermark
  poller (``kind="mem"`` records on the drain thread, per-phase peak
  attribution), the ``--mem_budget_frac`` capacity tripwire feeding the
  sentry as ``mem_pressure``, and the live-buffer-census forensics
  attached to flight bundles on OOM;
- :mod:`.fleet` — the r14 fleet watchtower: periodic cross-host
  exchange of host-side signals at the logging cadence (on the
  telemetry drain thread), min/median/max fleet tables, and the
  straggler verdict that feeds the sentry as a ``straggler`` trigger;
- :mod:`.server` — the opt-in ``--status_port`` HTTP endpoint:
  ``/status`` (JSON), ``/metrics`` (Prometheus text format),
  ``/healthz``;
- :mod:`.regression` — the per-attempt steady-state perf fingerprint
  (``perf_baseline.json``) compared on restore, WARNing when a
  restarted/resharded run comes back out of band.

Import discipline: :mod:`.hlo_report` is pure stdlib and must STAY
reachable without jax installed/imported (the ``parallel/`` delegates and
any text-only consumer pull it), so this ``__init__`` is lazy (PEP 562):
importing ``pytorch_ddp_template_tpu.obs.hlo_report`` executes only this
docstring, never :mod:`.health`'s jax/optax imports. :mod:`.health`
imports ``parallel.stacking`` lazily inside the function for the same
no-cycle reason.
"""

from typing import Any

_EXPORTS = {
    "attribution": (
        "HBM_BYTES_PER_SEC",
        "ICI_BYTES_PER_SEC",
        "PEAK_FLOPS",
        "PerfAttribution",
        "cost_of",
        "peak_flops_for",
        "static_cost_model",
    ),
    "fleet": (
        "FLEET_WIRE_KEYS",
        "FleetMonitor",
        "decode_rows",
        "encode_window",
    ),
    "goodput": ("BUCKETS", "GoodputLedger"),
    "health": ("HEALTH_KEYS", "health_metrics"),
    "memory": (
        "MEM_RING",
        "MemoryMonitor",
        "compile_memory_split",
        "device_memory_rows",
        "donation_audit",
        "donation_warnings",
        "forensics_payload",
        "live_buffer_census",
        "looks_like_oom",
        "static_memory_model",
    ),
    "regression": (
        "PerfBaseline",
        "compare_fingerprints",
        "config_signature",
        "make_fingerprint",
    ),
    "server": (
        "PROM_PREFIX",
        "StatusServer",
        "prom_escape",
        "prom_name",
        "prometheus_lines",
    ),
    "hlo_report": (
        "GATHER_FAMILY",
        "RING_FAMILY",
        "check_overlap_expectations",
        "collective_evidence",
        "composed_evidence",
        "op_census",
        "ring_evidence",
        "schedule_report",
    ),
    "sentry": (
        "BUNDLE_FILES",
        "FLIGHT_TRACE_STEPS",
        "SPIKE_KEYS",
        "AnomalySentry",
        "FlightRecorder",
    ),
}

__all__ = [name for names in _EXPORTS.values() for name in names]


def __getattr__(name: str) -> Any:  # PEP 562 lazy re-export
    for module, names in _EXPORTS.items():
        if name in names:
            from importlib import import_module

            return getattr(import_module(f".{module}", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
