"""HLO schedule analysis: the overlap-evidence walkers, factored out of
bench-only code into a production subsystem.

History: the operand-chain walker was born as
``parallel/overlap.hlo_overlap_evidence`` (r8, BENCH_MODE=overlap), grew a
ring-narrowed variant ``parallel/collective_matmul.hlo_tp_evidence`` (r10)
and a composed two-family variant ``parallel/schedule.
hlo_composed_evidence`` (r11) — but all three only ever ran inside bench
legs, so a production run whose overlap schedule silently degraded to
serial collectives (a spec change, an XLA upgrade, a flag interaction)
had no tripwire. This module is the shared home: the ``parallel/``
spellings remain as thin delegates (their callers and committed-record
semantics are unchanged), and :func:`schedule_report` +
:func:`check_overlap_expectations` put the same analysis behind
``--hlo_report`` at engine startup.

Everything here is pure text analysis over ``compiled.as_text()`` — no
jax imports, safe to call from any thread or process.

What the walker proves (and what it cannot): a *compute-independent*
collective inside a dot-carrying loop body is the schedulability witness —
the latency-hiding scheduler MAY start it at the top of the iteration and
run the matmuls under it. Whether overlap then *happens* is a
scheduler/hardware property, measured on TPU by the tools/ followup
scripts; this analysis proves what instruction text can: the dataflow
freedom exists (or, for the tripwire, that it does NOT).
"""

from __future__ import annotations

import re
from typing import Any

#: the data-axis collective family: what FSDP weight gathers, DDP grad
#: reduces (incl. the compressed all-to-all phase) and ZeRO scatters
#: lower to
GATHER_FAMILY = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all")
#: the model-axis family: the ring kernels' single-hop rotations are the
#: only collective the decomposed TP hot path issues
RING_FAMILY = ("collective-permute",)

#: itemsize of the HLO shape prefix dtypes seen on this harness (wire-byte
#: estimates; unknown dtypes fall back to 4)
_ITEMSIZE = {
    "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([0-9,]*)\]")
_TOKEN_RE = re.compile(r"%[\w.\-]+")


def parse_computations(hlo_text: str) -> list[tuple[str, list[str]]]:
    """Split an HLO module dump into ``(computation_name, instructions)``
    pairs (instruction lines only, braces stripped)."""
    bodies: list[tuple[str, list[str]]] = []
    cur: list[str] | None = None
    name = ""
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and ("(" in stripped and "->" in stripped):
            cur = []
            name = stripped.split(" ", 1)[0]
            continue
        if stripped == "}" or stripped.startswith("}"):
            if cur:
                bodies.append((name, cur))
            cur = None
            continue
        if cur is not None and "=" in stripped:
            cur.append(stripped)
    return bodies


def collective_evidence(hlo_text: str,
                        collectives: tuple[str, ...] | None = None,
                        ) -> dict[str, Any]:
    """Analyse compiled HLO for the decomposed schedule's signature.

    For every non-entry computation that contains both matmuls and a
    cross-replica collective (on this harness those are exactly the
    layer-scan loop bodies, forward and backward), walk each collective's
    operand chain and classify it as *compute-independent* (its inputs
    reach only loop-carried state — the stacked params and the induction
    variable, never a same-body dot) or *compute-dependent* (it consumes
    this iteration's dots, e.g. the per-layer gradient reduction).

    A compute-independent collective inside a dot-carrying loop body is
    the schedulability witness: the latency-hiding scheduler may start it
    at the top of the iteration and run the matmuls under it — the
    layer-(k+1) weight gather issued before layer k's compute retires.
    Dependent collectives (the backward grad drain) can only overlap
    ACROSS iterations (start in iteration k, complete during k-1), which
    instruction-level text cannot prove; their presence and count are
    reported as-is.

    Headline booleans: ``prefetch_gather_independent`` (≥1 loop body has
    a compute-independent collective — the forward prefetch) and
    ``bwd_regather_independent`` (≥2 such bodies — the backward re-gather
    pipeline too).

    ``collectives`` overrides the default op set — ``parallel/compress.py``
    adds ``all-to-all`` (its reduce-scatter phase) when analysing the
    compressed-DDP schedule.
    """
    if collectives is None:
        collectives = ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute")

    def is_dot(s: str) -> bool:
        return " dot(" in s or " convolution(" in s

    def is_collective(s: str) -> bool:
        return any(f" {c}(" in s or f" {c}-start(" in s
                   for c in collectives)

    rows = []
    for body_name, instrs in parse_computations(hlo_text):
        if body_name.upper().startswith("ENTRY"):
            # entry holds the pre-loop warm gather and the optimizer
            # tail — not a layer-schedule witness either way
            continue
        defs: dict[str, tuple[list[str], str]] = {}
        for s in instrs:
            lhs, _, rhs = s.partition("=")
            names = _TOKEN_RE.findall(lhs)
            if not names:
                continue
            # operands: %refs on the RHS; refs to other computations
            # (calls=, to_apply=) simply miss the defs map and end the walk
            defs[names[0]] = (_TOKEN_RE.findall(rhs), s)
        dot_names = {n for n, (_, s) in defs.items() if is_dot(s)}
        coll_names = [n for n, (_, s) in defs.items() if is_collective(s)]
        if not dot_names or not coll_names:
            continue

        dep_cache: dict[str, bool] = {}

        def depends_on_dot(n: str) -> bool:
            if n in dep_cache:
                return dep_cache[n]
            dep_cache[n] = False  # cycles impossible in HLO; guards re-entry
            if n in dot_names:
                dep_cache[n] = True
                return True
            ops = defs.get(n, ([], ""))[0]
            dep_cache[n] = any(depends_on_dot(o) for o in ops)
            return dep_cache[n]

        independent = [n for n in coll_names
                       if not any(depends_on_dot(o)
                                  for o in defs[n][0])]
        rows.append({
            "computation": body_name,
            "dots": len(dot_names),
            "collectives": len(coll_names),
            "compute_independent_collectives": len(independent),
            "compute_dependent_collectives":
                len(coll_names) - len(independent),
        })
    with_indep = [r for r in rows
                  if r["compute_independent_collectives"] > 0]
    return {
        "bodies": rows,
        "prefetch_gather_independent": len(with_indep) >= 1,
        "bwd_regather_independent": len(with_indep) >= 2,
    }


def ring_evidence(hlo_text: str) -> dict[str, Any]:
    """Ring-schedule witness for a compiled ``--tp_overlap`` program.

    :func:`collective_evidence` with the collective set narrowed to
    ``collective-permute`` (the only collective the ring kernels issue on
    the hot path): a dot-carrying loop body whose ppermute operands reach
    only loop-carried state is a ring step the latency-hiding scheduler
    may run under the dots. Headline counts: ``ring_bodies`` (dot-carrying
    bodies with any ppermute) and ``independent_ring_bodies`` (all of
    whose ppermutes are compute-independent). Callers compare a
    forward-only lowering against the full train step to attribute bodies
    to fwd vs bwd (instruction text alone cannot).
    """
    ev = collective_evidence(hlo_text, collectives=RING_FAMILY)
    bodies = ev["bodies"]
    independent = [r for r in bodies
                   if r["compute_independent_collectives"] > 0
                   and r["compute_dependent_collectives"] == 0]
    return {
        "bodies": bodies,
        "ring_bodies": len(bodies),
        "independent_ring_bodies": len(independent),
    }


def composed_evidence(hlo_text: str) -> dict[str, Any]:
    """Witness that a composed (fsdp×tp) lowering carries BOTH axes'
    collectives compute-independent in ONE scanned body.

    Two operand walks over the same HLO: the *gather family*
    (:data:`GATHER_FAMILY` — the data-axis fsdp/ddp collectives) and the
    *ring family* (:data:`RING_FAMILY` — the model-axis TP hops). The TP
    rings lower to nested loop computations called FROM the layer-scan
    body, so "one scanned body" means: a dot-carrying loop body whose
    gather collectives are compute-independent AND that either contains
    independent ppermutes directly or calls a nested ring body all of
    whose ppermutes are independent. ``composed_overlap_independent`` is
    the headline boolean.
    """
    gather_ev = collective_evidence(hlo_text, collectives=GATHER_FAMILY)
    ring_ev = collective_evidence(hlo_text, collectives=RING_FAMILY)

    def norm(name: str) -> str:
        return name.lstrip("%")

    gather_ind = {norm(r["computation"]) for r in gather_ev["bodies"]
                  if r["compute_independent_collectives"] > 0}
    ring_ind = {norm(r["computation"]) for r in ring_ev["bodies"]
                if r["compute_independent_collectives"] > 0
                and r["compute_dependent_collectives"] == 0}

    # map each computation to the computations it references (while
    # bodies, calls, fusions) so a gather body "contains" the ring
    # bodies its nested loops execute
    refs = _computation_refs(hlo_text)

    def reaches_ring(name: str, seen: set[str]) -> bool:
        if name in ring_ind:
            return True
        if name in seen:
            return False
        seen.add(name)
        return any(reaches_ring(r, seen) for r in refs.get(name, ()))

    both = sorted(
        b for b in gather_ind
        if b in ring_ind or reaches_ring(b, set())
    )
    return {
        "gather_bodies": gather_ev["bodies"],
        "ring_bodies": ring_ev["bodies"],
        "independent_gather_bodies": len(gather_ind),
        "independent_ring_bodies": len(ring_ind),
        "bodies_with_both_independent": both,
        "composed_overlap_independent": len(both) >= 1,
    }


def _computation_refs(hlo_text: str) -> dict[str, set[str]]:
    """computation -> computations it references (while bodies, calls,
    fusions, conditional branches) — the nested-reachability map the
    composed and pipe walkers share.

    Two passes: collect every computation name first, then count any
    ``%name`` token matching one as a reference — a keyed regex alone
    misses all-but-the-first entry of
    ``branch_computations={%a, %b, ...}`` lists (the slot-loop switch
    lowers to exactly that shape)."""
    names: set[str] = set()
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and "(" in stripped and "->" in stripped:
            names.add(stripped.split(" ", 1)[0].lstrip("%"))
    refs: dict[str, set[str]] = {}
    cur: str | None = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and "(" in stripped and "->" in stripped:
            cur = stripped.split(" ", 1)[0].lstrip("%")
            refs[cur] = set()
            continue
        if stripped.startswith("}"):
            cur = None
            continue
        if cur is not None:
            for tok in _TOKEN_RE.findall(stripped):
                name = tok.lstrip("%")
                if name != cur and name in names:
                    refs[cur].add(name)
    return refs


def pipe_evidence(hlo_text: str) -> dict[str, Any]:
    """Schedulability witness for the pipeline slot loop (r16).

    The fused 1F1B/ZB driver issues its two boundary ppermutes at the
    top of every slot, consuming only loop-carried send buffers — so in
    the lowered slot-loop body every ``collective-permute``'s operand
    chain must reach only loop state, never this slot's compute. The
    slot WORK lives inside ``conditional`` branches (the work switch),
    so a body counts as a *slot body* when it carries ppermutes and
    reaches dot ops through its referenced computations (nested
    conditionals/fusions), not necessarily directly.

    Returns: ``slot_bodies`` (ppermute-carrying, dot-reaching loop
    bodies), ``independent_send_bodies`` (all of whose ppermutes are
    compute-independent), the headline ``pipe_sends_independent``,
    ``conditional_count`` (the work-switch witness) and
    ``dw_ops_present`` — whether the zb deferred-dw computations are in
    the program (via the ``pipe_stage_dw``/``pipe_dw_wave`` named
    scopes the driver stamps; scope metadata survives into the compiled
    dump on this toolchain — absent metadata degrades this to False,
    never a crash).

    r22 (the compose invariant): ``branch_collectives`` counts
    collective ops (all-reduce / all-gather / reduce-scatter /
    all-to-all / collective-permute, plus their async ``-start``
    twins) reachable from any ``conditional``'s branch computations —
    transitively, through nested calls/fusions/whiles. The
    boundary-hoisting contract says every compose-wave collective
    sits at the slot-body top level, uniform across stages; a
    collective inside a branch executes under a divergent stage
    predicate and deadlocks on real hardware, so
    ``branch_collectives_free`` (== 0) is the tripwire the pipe×
    {tp,ddp,fsdp} tests pin.
    """
    # dots per computation (direct) + the nested-reachability map
    refs = _computation_refs(hlo_text)
    comps = parse_computations(hlo_text)
    direct_dots: dict[str, bool] = {}
    for name, instrs in comps:
        direct_dots[name.lstrip("%")] = any(
            " dot(" in s or " convolution(" in s for s in instrs)

    def reaches_dots(name: str, seen: set[str]) -> bool:
        if direct_dots.get(name):
            return True
        if name in seen:
            return False
        seen.add(name)
        return any(reaches_dots(r, seen) for r in refs.get(name, ()))

    rows = []
    for name, instrs in comps:
        cname = name.lstrip("%")
        if cname.upper().startswith("ENTRY"):
            # entry holds the region-edge output permute (the dx slice
            # leaving the shard_map), not a slot-schedule witness
            continue
        defs: dict[str, tuple[list[str], str]] = {}
        for s in instrs:
            lhs, _, rhs = s.partition("=")
            names_ = _TOKEN_RE.findall(lhs)
            if names_:
                defs[names_[0]] = (_TOKEN_RE.findall(rhs), s)

        def is_work(instr: str) -> bool:
            # "compute" the sends must not depend on: a same-body dot,
            # OR any instruction executing a dot-reaching nested
            # computation (the slot switch's conditional, fusions) —
            # without the nested case the fused loops, whose dots live
            # entirely inside the switch branches, could never trip
            # the send-independence check
            if " dot(" in instr or " convolution(" in instr:
                return True
            return any(tok.lstrip("%") in direct_dots
                       and reaches_dots(tok.lstrip("%"), set())
                       for tok in _TOKEN_RE.findall(
                           instr.partition("=")[2])
                       if tok.lstrip("%") in refs)
        work_names = {n for n, (_, s) in defs.items() if is_work(s)}
        pp_names = [n for n, (_, s) in defs.items()
                    if " collective-permute(" in s
                    or " collective-permute-start(" in s]
        if not pp_names or not reaches_dots(cname, set()):
            continue

        dep_cache: dict[str, bool] = {}

        def depends_on_work(n: str) -> bool:
            if n in dep_cache:
                return dep_cache[n]
            dep_cache[n] = False
            if n in work_names:
                dep_cache[n] = True
                return True
            ops = defs.get(n, ([], ""))[0]
            dep_cache[n] = any(depends_on_work(o) for o in ops)
            return dep_cache[n]

        independent = all(
            not any(depends_on_work(o) for o in defs[n][0])
            for n in pp_names)
        rows.append({"computation": cname, "ppermutes": len(pp_names),
                     "sends_independent": independent})
    independent_bodies = [r for r in rows if r["sends_independent"]]
    conditional_count = sum(
        1 for _, instrs in comps
        for s in instrs if " conditional(" in s)

    # r22 compose invariant: no collective may execute under a branch
    # predicate. Collect every computation named by a conditional's
    # branch list, close over nested references, and count collective
    # ops inside the closure.
    _COLL = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
             "collective-permute")

    def _is_collective(instr: str) -> bool:
        return any(f" {op}(" in instr or f" {op}-start(" in instr
                   for op in _COLL)

    branch_roots: set[str] = set()
    for _, instrs in comps:
        for s in instrs:
            if " conditional(" not in s:
                continue
            m = re.search(r"branch_computations=\{([^}]*)\}", s)
            if m:
                for tok in m.group(1).split(","):
                    branch_roots.add(tok.strip().lstrip("%"))
            for m in re.finditer(r"(?:true|false)_computation="
                                 r"(%?[\w.\-]+)", s):
                branch_roots.add(m.group(1).lstrip("%"))
    reach = set(branch_roots)
    frontier = list(branch_roots)
    while frontier:
        nxt = frontier.pop()
        for r in refs.get(nxt, ()):
            if r not in reach:
                reach.add(r)
                frontier.append(r)
    instrs_by_name = {name.lstrip("%"): instrs for name, instrs in comps}
    branch_collectives = sum(
        1 for cname in reach
        for s in instrs_by_name.get(cname, ())
        if _is_collective(s))

    return {
        "bodies": rows,
        "slot_bodies": len(rows),
        "independent_send_bodies": len(independent_bodies),
        "pipe_sends_independent": bool(rows) and (
            len(independent_bodies) == len(rows)),
        "conditional_count": conditional_count,
        "branch_computation_count": len(branch_roots),
        "branch_collectives": branch_collectives,
        "branch_collectives_free": branch_collectives == 0,
        "dw_ops_present": ("pipe_stage_dw" in hlo_text
                           or "pipe_dw_wave" in hlo_text),
    }


#: narrow-dtype HLO spellings the quant walker recognises (int8 + the
#: two fp8 formats; ``f8e4m3`` covers toolchains that drop the ``fn``)
NARROW_DTYPES = ("s8", "f8e4m3fn", "f8e4m3", "f8e5m2")


def _mentions_narrow(text: str) -> bool:
    return any(f"{d}[" in text for d in NARROW_DTYPES)


def _converts_to_narrow(text: str) -> bool:
    """Whether any instruction in ``text`` is a convert whose RESULT is
    narrow (the result shape sits between '=' and the opcode)."""
    for line in text.splitlines():
        rhs = line.partition("=")[2]
        cidx = rhs.find(" convert(")
        if cidx >= 0:
            m = _SHAPE_RE.search(rhs[:cidx])
            if m and m.group(1) in NARROW_DTYPES:
                return True
    return False


def quant_evidence(hlo_text: str) -> dict[str, Any]:
    """Low-precision compute witness (r17, ``--quant_compute``).

    Three properties of the compiled step, all pure text analysis:

    - ``narrow_dots`` — dot instructions fed by narrow operands: either
      a narrow dtype inline in the operand list (a real narrow-MXU dot)
      or an operand defined by a ``convert`` FROM a narrow value
      (backends without a narrow MXU — this CPU host — upcast the
      operands but the program still carries the narrow tensors, which
      is what the HBM/wire savings ride on). ``quant_dots_present`` is
      the headline boolean.
    - ``narrow_ppermutes`` — collective-permutes whose payload is
      narrow: the quantized ring wire (``--quant_compute`` ×
      ``--tp_overlap``).
    - the hoisting witness: a loop body whose narrow ppermute payloads
      are NOT produced by an in-body convert-to-narrow (nor by a fusion
      whose computation converts to narrow) quantized its payload ONCE
      outside the loop (``hoisted_quant_ring_bodies`` — the
      "scales not re-materialised per hop" tripwire); a body whose wire
      tensor comes off such a convert re-quantizes per hop
      (``requant_ring_bodies`` — the accumulator streams requant per
      hop BY DESIGN, so only converts feeding the ppermute count).
    """
    comps = parse_computations(hlo_text)
    # computation name -> its full instruction text, for resolving dot
    # operands that are fusions wrapping the dequantizing converts (the
    # CPU lowering fuses convert(s8→s32) into %convert_convert_fusion)
    comp_text = {name.lstrip("%"): "\n".join(instrs)
                 for name, instrs in comps}

    def _operand_reaches_narrow(def_instr: str) -> bool:
        rhs = def_instr.partition("=")[2]
        if _mentions_narrow(rhs):
            return True
        for tok in _TOKEN_RE.findall(rhs):
            text = comp_text.get(tok.lstrip("%"))
            if text is not None and _mentions_narrow(text):
                return True
        return False

    rows = []
    narrow_dots = 0
    narrow_pp = 0
    for body_name, instrs in comps:
        defs: dict[str, str] = {}
        for s in instrs:
            lhs, _, _rhs = s.partition("=")
            names = _TOKEN_RE.findall(lhs)
            if names:
                defs[names[0]] = s
        body_narrow_dots = 0
        body_pp = 0
        pp_payload_tokens: set[str] = set()
        for s in instrs:
            rhs = s.partition("=")[2]
            if " dot(" in s or " convolution(" in s:
                if _mentions_narrow(rhs):
                    body_narrow_dots += 1
                else:
                    # narrow-MXU-less lowering: operands arrive through
                    # converts/fusions FROM the narrow tensors
                    for tok in _TOKEN_RE.findall(rhs):
                        d = defs.get(tok, "")
                        if d and _operand_reaches_narrow(d):
                            body_narrow_dots += 1
                            break
            if (" collective-permute(" in s
                    or " collective-permute-start(" in s) \
                    and _mentions_narrow(s):
                body_pp += 1
                op = rhs.find("collective-permute")
                pp_payload_tokens.update(_TOKEN_RE.findall(rhs[op:]))
        # per-hop payload requant witness: the wire tensor is produced
        # INSIDE the body by a convert whose RESULT is narrow (result
        # shape sits between '=' and the opcode), or by a fusion whose
        # computation carries such a convert (this CPU lowering fuses
        # the requant). Converts-to-narrow NOT feeding a ppermute are
        # the accumulator streams — by design, never counted.
        converts_to_narrow = 0
        for tok in sorted(pp_payload_tokens):
            d = defs.get(tok)
            if not d:
                continue
            drhs = d.partition("=")[2]
            cidx = drhs.find(" convert(")
            fidx = drhs.find(" fusion(")
            opidx = cidx if cidx >= 0 else fidx
            if opidx < 0:
                continue
            m = _SHAPE_RE.search(drhs[:opidx])
            if not (m and m.group(1) in NARROW_DTYPES):
                continue
            if cidx >= 0:
                converts_to_narrow += 1
            else:
                for ftok in _TOKEN_RE.findall(drhs[opidx:]):
                    text = comp_text.get(ftok.lstrip("%"))
                    if text is not None and _converts_to_narrow(text):
                        converts_to_narrow += 1
                        break
        narrow_dots += body_narrow_dots
        narrow_pp += body_pp
        if body_narrow_dots or body_pp:
            rows.append({
                "computation": body_name.lstrip("%"),
                "narrow_dots": body_narrow_dots,
                "narrow_ppermutes": body_pp,
                "converts_to_narrow": converts_to_narrow,
            })
    pp_bodies = [r for r in rows if r["narrow_ppermutes"] > 0]
    hoisted = [r for r in pp_bodies if r["converts_to_narrow"] == 0]
    return {
        "bodies": rows,
        "narrow_dots": narrow_dots,
        "narrow_ppermutes": narrow_pp,
        "narrow_ring_bodies": len(pp_bodies),
        "hoisted_quant_ring_bodies": len(hoisted),
        "requant_ring_bodies": len(pp_bodies) - len(hoisted),
        "quant_dots_present": narrow_dots >= 1,
    }


def _shape_bytes(instr: str, op: str) -> int:
    """Estimated result bytes of a collective instruction: the last
    ``dtype[dims]`` group BEFORE the opcode token (for the plain
    ``%x = f32[4,8]{1,0} all-gather(...)`` form that is the result shape;
    for ``-start`` tuple forms it is the output element of the buffer
    pair). An estimate, not an accounting — good enough to rank what
    dominates the wire."""
    idx = instr.find(f" {op}")
    head = instr[:idx] if idx >= 0 else instr
    last = None
    for m in _SHAPE_RE.finditer(head):
        last = m
    if last is None:
        return 0
    dtype, dims_s = last.group(1), last.group(2)
    n = 1
    for d in dims_s.split(","):
        if d:
            n *= int(d)
    return n * _ITEMSIZE.get(dtype, 4)


def op_census(hlo_text: str) -> dict[str, dict[str, int]]:
    """Count every collective instruction in the module (all
    computations, entry included) with estimated wire bytes per op kind.
    ``-start`` and plain spellings count as one op each (``-done`` is the
    completion marker of its ``-start``, not a second collective)."""
    census: dict[str, dict[str, int]] = {}
    ops = GATHER_FAMILY + RING_FAMILY
    for _, instrs in parse_computations(hlo_text):
        for s in instrs:
            for op in ops:
                if f" {op}(" in s or f" {op}-start(" in s:
                    row = census.setdefault(op, {"count": 0, "wire_bytes": 0})
                    row["count"] += 1
                    row["wire_bytes"] += _shape_bytes(s, op)
                    break
    return census


def schedule_report(hlo_text: str) -> dict[str, Any]:
    """The always-on production report over one compiled train step.

    One dict, JSON-ready, combining the three walkers the bench legs run
    separately plus a module-wide collective census:

    - ``ops``: per-opcode count + estimated wire bytes (module-wide);
    - ``gather``: the data-axis family's dot-carrying-body evidence
      (bodies, independent/dependent counts — the fsdp/ddp witness);
    - ``ring``: the model-axis ppermute evidence (the tp witness);
    - ``composed``: the r11 both-axes-in-one-body evidence, with the
      SAME ``independent_gather_bodies``/``independent_ring_bodies``
      counts the ``BENCH_MODE=overlap3d`` committed record carries.

    Axis attribution is by family: under the decomposed schedules the
    gather family rides the ``data`` axis and collective-permute the
    ``model`` axis (GSPMD-default programs may blur this; the census
    keeps the raw per-opcode truth either way).
    """
    # ONE composed walk supplies all three sections: its gather_bodies/
    # ring_bodies ARE the per-family walks' row lists (re-running
    # collective_evidence/ring_evidence here would parse a multi-MB HLO
    # dump three times for identical rows)
    composed = composed_evidence(hlo_text)
    census = op_census(hlo_text)
    gather_bodies = composed["gather_bodies"]
    ring_rows = composed["ring_bodies"]
    clean_ring = [r for r in ring_rows
                  if r["compute_independent_collectives"] > 0
                  and r["compute_dependent_collectives"] == 0]
    return {
        "ops": census,
        "wire_mb_estimate": round(
            sum(r["wire_bytes"] for r in census.values()) / 1e6, 3),
        "gather": {
            "bodies": gather_bodies,
            "dot_carrying_bodies": len(gather_bodies),
            "independent_bodies": sum(
                1 for r in gather_bodies
                if r["compute_independent_collectives"] > 0),
            "independent_collectives": sum(
                r["compute_independent_collectives"] for r in gather_bodies),
            "dependent_collectives": sum(
                r["compute_dependent_collectives"] for r in gather_bodies),
        },
        "ring": {
            "bodies": ring_rows,
            "ring_bodies": len(ring_rows),
            "independent_ring_bodies": len(clean_ring),
        },
        "composed": {
            "independent_gather_bodies":
                composed["independent_gather_bodies"],
            "independent_ring_bodies": composed["independent_ring_bodies"],
            "bodies_with_both_independent":
                composed["bodies_with_both_independent"],
            "composed_overlap_independent":
                composed["composed_overlap_independent"],
        },
        "pipe": pipe_evidence(hlo_text),
        "quant": quant_evidence(hlo_text),
    }


def check_overlap_expectations(report: dict[str, Any], config: Any,
                               axis_sizes: dict[str, int]) -> list[str]:
    """The schedule-regression tripwire: WARN strings for every overlap
    flag whose compiled program does NOT show its schedulability witness.

    Each check gates on its axis actually being parallel (``axis_sizes``
    from the live mesh): a single-replica run compiles no collectives at
    all, which is degenerate, not degraded. The returned strings are
    ready for ``log.warning`` — empty list means every active overlap
    flag's collectives are compute-independent where they must be.
    """
    warns: list[str] = []
    data = axis_sizes.get("data", 1)
    model = axis_sizes.get("model", 1)
    gather = report["gather"]
    ring = report["ring"]
    # on the pipelined entries the overlap flags select the slot-boundary
    # compose waves (parallel/pipeline.py), not the scanned-stack
    # machinery these witnesses describe — their evidence is the r22
    # branch-collective invariant below, so the scan-shaped checks are
    # skipped rather than allowed to fire vacuous warnings
    pipe_model = str(getattr(config, "model", "")).startswith("gpt-pipe")
    if (getattr(config, "fsdp_overlap", False) and data > 1
            and not pipe_model):
        if gather["independent_bodies"] < 1:
            warns.append(
                "--fsdp_overlap is on but NO dot-carrying loop body has a "
                "compute-independent gather-family collective: the weight "
                "gathers cannot start under compute — the schedule has "
                "degraded to serial gather-then-compute "
                f"(bodies={gather['dot_carrying_bodies']}, "
                f"dependent={gather['dependent_collectives']})"
            )
    if (getattr(config, "ddp_overlap", False) and data > 1
            and not pipe_model):
        per_layer = sum(r["collectives"] for r in gather["bodies"])
        if per_layer < 1:
            warns.append(
                "--ddp_overlap is on but no gather-family collective lives "
                "inside any dot-carrying loop body: the per-layer grad "
                "reduce has left the backward scan — gradients are "
                "draining as one post-backward wall again"
            )
    if (getattr(config, "tp_overlap", False) and model > 1
            and not pipe_model):
        if ring["independent_ring_bodies"] < 1:
            warns.append(
                "--tp_overlap is on but no dot-carrying loop body carries "
                "only compute-independent collective-permutes: the ring "
                "rotations cannot hide under the partial dots — the "
                "collective matmuls have degraded to blocking rotations "
                f"(ring_bodies={ring['ring_bodies']})"
            )
    if (getattr(config, "tp_overlap", False)
            and (getattr(config, "fsdp_overlap", False)
                 or getattr(config, "ddp_overlap", False))
            and data > 1 and model > 1):
        if not report["composed"]["composed_overlap_independent"]:
            warns.append(
                "composed schedule: no scanned body carries BOTH "
                "compute-independent gather-family collectives and "
                "independent ring ppermutes — the two axes' overlap "
                "pipelines are no longer composed in one body"
            )
    # r16 pipe check: a pipelined entry's stage-boundary hops must be
    # compute-independent in the loop body (issued before the consuming
    # compute), and under zb the deferred-dw computations must actually
    # be in the program (their absence means the split backward has
    # silently degraded to the fused one)
    pipe_axis = axis_sizes.get("pipe", 1)
    if pipe_model and pipe_axis > 1:
        pe = report.get("pipe", {})
        sched = getattr(config, "pipe_schedule", "gpipe")
        if not pe.get("pipe_sends_independent", False):
            warns.append(
                f"pipe schedule {sched!r} is active but the slot loop's "
                "stage-boundary collective-permutes are not compute-"
                "independent (or no slot body was found): the p2p hops "
                "cannot start under the adjacent microbatch's work — "
                "the pipeline schedule has degraded to "
                "send-then-compute "
                f"(slot_bodies={pe.get('slot_bodies', 0)}, "
                f"independent={pe.get('independent_send_bodies', 0)})"
            )
        if sched == "zb" and not pe.get("dw_ops_present", False):
            warns.append(
                "pipe_schedule=zb is active but no deferred-dw "
                "computation (pipe_stage_dx / pipe_dw_wave named scope) "
                "appears in the compiled program: the dx/dw split has "
                "not survived compilation — the deferred dw wave that "
                "fills the drain region is missing"
            )
        # r22 compose invariant: the boundary-hoisting contract admits
        # NO collective under a branch predicate — one there executes
        # only on the stages whose switch arm selects it, and a
        # divergent collective deadlocks on real hardware. Checked
        # whenever the slot loop compiles conditionals (compose flag or
        # not: plain pipe must hold the invariant too).
        if not pe.get("branch_collectives_free", True):
            warns.append(
                f"pipe schedule {sched!r}: "
                f"{pe.get('branch_collectives', '?')} collective op(s) "
                "are reachable from a conditional's branch_computations "
                "— a collective under a divergent stage predicate is a "
                "deadlock on real hardware; every compose-wave "
                "collective must sit at the slot-body top level "
                "(parallel/pipeline.py boundary-hoisting contract)"
            )
    # r17 quant tripwire: a --quant_compute run must actually carry
    # narrow-dtype dots (compute quantized), and composed with the TP
    # rings the ppermute payloads must be narrow with the quantization
    # hoisted out of at least one ring loop (quantize once per chunk —
    # per-hop re-quantization of every stream means the narrow wire is
    # paying a full requant tax it was designed to avoid)
    quant_mode = getattr(config, "quant_compute", "off")
    if quant_mode != "off":
        qe = report.get("quant", {})
        if not qe.get("quant_dots_present", False):
            warns.append(
                f"--quant_compute {quant_mode} is on but the compiled "
                "step carries NO narrow-dtype dots: the low-precision "
                "path has not survived compilation — every matmul is "
                "running wide again"
            )
        if getattr(config, "tp_overlap", False) and model > 1:
            if qe.get("narrow_ppermutes", 0) < 1:
                warns.append(
                    f"--quant_compute {quant_mode} × --tp_overlap is on "
                    "but no collective-permute carries a narrow payload: "
                    "the ring wire is wide — the quantized ring kernels "
                    "are not in the compiled program"
                )
            elif qe.get("hoisted_quant_ring_bodies", 0) < 1:
                warns.append(
                    f"--quant_compute {quant_mode} × --tp_overlap: every "
                    "narrow-ppermute ring body re-quantizes inside the "
                    "loop — the once-per-chunk quantization hoisting has "
                    "not survived compilation "
                    f"(requant_bodies={qe.get('requant_ring_bodies', 0)})"
                )
    return warns
