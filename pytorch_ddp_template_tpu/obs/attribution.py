"""Performance attribution: where the step time and the wire budget go.

The complementary question to the r12 flight recorder's "is this run
healthy?" is "is this run *fast*, and if not, what is it spending its
time on?" — the question the MFU convention (PaLM, Chowdhery et al.
2022: model FLOPs per step over step wall-time over peak matmul
throughput, *all* overheads included in the denominator) and
Megatron-LM-style efficiency reporting answer continuously in the large
production stacks. Before this module the pieces existed but never met:
``compiled.cost_analysis()`` ran in exactly one bench.py leg, MFU only
in the standalone ``tools/mfu_probe.py``, wire-byte estimates only in
the r12 ``op_census``, and the loader's stall counters only as a raw
``input_wait_ms``.

Two halves:

- :func:`static_cost_model` — derived ONCE at startup from the
  AOT-compiled train step (riding the same compile ``--hlo_report``
  pays): model FLOPs/step and HBM bytes/step from XLA's own cost
  analysis, plus expected collective wire bytes/step from the
  :func:`obs.hlo_report.op_census` shape walk, split per collective
  family and attributed per mesh axis (gather family → ``data``: the
  fsdp/ddp/zero collectives; ring family → ``model``: the decomposed-TP
  ppermutes). This is the engine's *a-priori* budget for the active
  overlap schedule.
- :class:`PerfAttribution` — combines that budget with what the loop
  actually measures per logging interval (wall time, step count, the
  loader's ``consumer_wait_s``/``producer_idle_s``, the dispatch-depth
  barrier's device-wait time) into rolling MFU, achieved HBM/wire
  bytes-per-second estimates, and a compute/comm/host/input fractional
  breakdown that sums to exactly 1.0.

Attribution semantics (honest about what host-side wall-clock can and
cannot prove): ``input`` is measured directly (the loop blocked on the
loader), ``host`` is measured directly (iteration wall minus input minus
the device-wait fence read), and the *device* remainder is split into
``compute`` vs ``comm`` by the static model's estimated time ratio
(FLOPs/peak vs wire-bytes/interconnect-bandwidth). Where no peak or
bandwidth figure exists for the device (CPU hosts; ``--peak_tflops``
overrides), the whole device share is reported as compute and MFU is
omitted rather than invented. Achieved overlap shows up exactly as you
want it to: hidden communication inflates no bucket, because the split
only distributes time the loop *observably spent* waiting on the device.

Import discipline: top-level imports are stdlib-only (like
:mod:`obs.hlo_report`) so bench.py can pull :data:`PEAK_FLOPS` and
:func:`cost_of` before any backend initialises.
"""

from __future__ import annotations

from typing import Any

from .hlo_report import GATHER_FAMILY, RING_FAMILY, op_census

#: Peak dense-matmul throughput per chip (bf16), for MFU. Sources: public
#: TPU spec sheets; matched by substring against ``device.device_kind``.
#: Moved here from bench.py (r13) — bench and tools/mfu_probe.py import
#: this copy. No CPU entry on purpose: a made-up CPU "peak" would turn
#: MFU into fiction; CPU runs pass ``--peak_tflops`` (the bench perf leg
#: calibrates one) or simply report no MFU.
PEAK_FLOPS = {
    "TPU v6e": 918e12,  # Trillium
    "TPU v6 lite": 918e12,
    "TPU v5p": 459e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 45e12,
}

#: Per-dtype peak rows (r17, ``--quant_compute``): the narrow-format
#: matmul peaks the low-precision compute path can reach, from the same
#: public spec sheets. int8 is 2x bf16 on every generation that exposes
#: it; generations without a narrow MXU path are deliberately ABSENT —
#: the headroom is then reported as none rather than invented (v2/v3
#: have no int8 MXU mode; fp8 arrives with Trillium). The attribution
#: reports the *headroom* (narrow peak / bf16 peak) so the r13 MFU
#: convention keeps its bf16 denominator and stays comparable across
#: rounds.
PEAK_FLOPS_BY_DTYPE = {
    "bf16": PEAK_FLOPS,
    "int8": {
        "TPU v6e": 1836e12,
        "TPU v6 lite": 1836e12,
        "TPU v5p": 918e12,
        "TPU v5e": 394e12,
        "TPU v5 lite": 394e12,
        "TPU v4": 275e12,  # v4 int8 runs at the bf16 rate (no 2x path)
    },
    "fp8": {
        "TPU v6e": 1836e12,
        "TPU v6 lite": 1836e12,
    },
}

#: Per-chip interconnect bandwidth (bytes/s, one direction, order-of-
#: magnitude spec figures) for the comm-time estimate that splits the
#: device share into compute vs comm. Coarse by design: the split is an
#: attribution heuristic, not a measurement — the followup trace legs
#: measure real overlap.
ICI_BYTES_PER_SEC = {
    "TPU v6e": 3584e9 / 2,
    "TPU v6 lite": 3584e9 / 2,
    "TPU v5p": 4800e9 / 2,
    "TPU v5e": 1600e9 / 2,
    "TPU v5 lite": 1600e9 / 2,
    "TPU v4": 2400e9 / 2,
    "TPU v3": 700e9 / 2,
    "TPU v2": 500e9 / 2,
}

#: HBM bandwidth per chip (bytes/s), for the achieved-fraction context
#: next to the absolute GB/s estimate (same sources as PEAK_FLOPS).
HBM_BYTES_PER_SEC = {
    "TPU v6e": 1640e9,
    "TPU v6 lite": 1640e9,
    "TPU v5p": 2765e9,
    "TPU v5e": 819e9,
    "TPU v5 lite": 819e9,
    "TPU v4": 1228e9,
    "TPU v3": 900e9,
    "TPU v2": 700e9,
}


def _lookup(table: dict[str, float], device_kind: str) -> float | None:
    return next((v for k, v in table.items() if k in device_kind), None)


def peak_flops_for(device_kind: str, override_tflops: float = 0.0,
                   dtype: str = "bf16") -> float | None:
    """Peak FLOPs/s for MFU: the ``--peak_tflops`` override when given
    (custom hardware, CPU calibration runs), else the per-dtype spec
    table (``dtype`` = ``bf16`` | ``int8`` | ``fp8``; the r17 quant
    rows), else None (MFU/headroom is then omitted, never invented)."""
    if override_tflops and override_tflops > 0:
        return float(override_tflops) * 1e12
    table = PEAK_FLOPS_BY_DTYPE.get(dtype)
    if table is None:
        raise ValueError(
            f"peak_flops_for: unknown dtype {dtype!r}; expected one of "
            f"{sorted(PEAK_FLOPS_BY_DTYPE)}")
    return _lookup(table, device_kind)


def cost_of(compiled) -> dict:
    """FLOPs + bytes of one executable from XLA's own cost analysis
    (zeros when the backend exposes none — cost analysis is best-effort).
    Shared home (r13): bench.py and tools/mfu_probe.py import this."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return {
            "flops": float(cost.get("flops", 0.0)),
            "bytes": float(cost.get("bytes accessed", 0.0)),
        }
    except Exception:  # noqa: BLE001
        return {"flops": 0.0, "bytes": 0.0}


def static_cost_model(compiled, axis_sizes: dict[str, int] | None = None,
                      hlo_text: str | None = None,
                      pipe_bubble_frac: float = 0.0,
                      model_wire_bytes_per_step: float = 0.0
                      ) -> dict[str, Any]:
    """The a-priori per-step budget of one compiled train step.

    ``compiled`` is the AOT executable (``jit(...).lower(...).compile()``)
    the engine builds at startup under ``--perf_report``/``--hlo_report``;
    ``hlo_text`` lets a caller that already holds ``compiled.as_text()``
    (the shared startup compile) avoid dumping the multi-MB module twice.

    Returns a JSON-ready dict:

    - ``flops_per_step`` / ``hbm_bytes_per_step`` — XLA cost analysis
      (model FLOPs in the MFU sense: whatever the compiled program does,
      including remat recompute — the honest denominator input);
    - ``wire_bytes_data`` / ``wire_bytes_model`` / ``wire_bytes_total``
      — estimated collective bytes per step from the op census, family-
      attributed to mesh axes (gather family → ``data``, ring family →
      ``model``; the r11 convention). Axes of size <= 1 contribute zero
      regardless of census text (a single-replica program may still
      contain degenerate collectives);
    - ``collective_ops`` — the raw per-opcode census (count + bytes);
    - ``pipe_bubble_frac`` — the pipeline schedule's static bubble
      fraction (``parallel/pipeline.schedule_bubble_fraction`` at the
      run's (schedule, M, P); the engine passes it for the pipelined
      entries). Zeroed when the mesh has no live ``pipe`` axis — the
      r16 convention mirroring the wire-byte axis gating.

    r22 pipe-mesh attribution: on a live ``pipe`` axis the
    collective-permutes ARE the stage-boundary hops, so their bytes go
    to a ``wire_bytes_pipe`` bucket instead of ``model``. With a model
    axis ALSO live (pipe×tp), the model-axis psums share the
    all-reduce spelling with the data-axis grad reduce, and the census
    alone cannot split the opcode between axes — the caller passes the
    STATIC model ring-wire figure (``model_wire_bytes_per_step``, e.g.
    ``PipelineSchedule``'s per-step TP wave estimate) and that many
    gather-family bytes are re-attributed from ``data`` to ``model``
    (clamped to what the census actually carries — the figure is an
    estimate, never invented traffic). Off pipe meshes the parameter
    is ignored and the r11 family convention stands unchanged.
    """
    axis_sizes = dict(axis_sizes or {})
    c = cost_of(compiled)
    if hlo_text is None:
        try:
            hlo_text = compiled.as_text()
        except Exception:  # noqa: BLE001
            hlo_text = ""
    census = op_census(hlo_text)
    data_live = axis_sizes.get("data", 1) > 1
    model_live = axis_sizes.get("model", 1) > 1
    pipe_live = axis_sizes.get("pipe", 1) > 1
    gather_bytes = sum(v["wire_bytes"] for k, v in census.items()
                       if k in GATHER_FAMILY)
    ring_bytes = sum(v["wire_bytes"] for k, v in census.items()
                     if k in RING_FAMILY)
    wire_pipe = 0
    if pipe_live:
        wire_pipe = ring_bytes
        wire_model = 0
        if model_live:
            wire_model = min(int(model_wire_bytes_per_step),
                             gather_bytes)
        wire_data = (gather_bytes - wire_model) if data_live else 0
    else:
        wire_data = gather_bytes if data_live else 0
        wire_model = ring_bytes if model_live else 0
    return {
        "flops_per_step": c["flops"],
        "hbm_bytes_per_step": c["bytes"],
        "wire_bytes_data": int(wire_data),
        "wire_bytes_model": int(wire_model),
        "wire_bytes_pipe": int(wire_pipe),
        "wire_bytes_total": int(wire_data + wire_model + wire_pipe),
        "collective_ops": census,
        "pipe_bubble_frac": (float(pipe_bubble_frac) if pipe_live
                             else 0.0),
    }


class PerfAttribution:
    """Rolling runtime attribution over the static budget.

    Built once at engine startup; the loop feeds cumulative counters and
    calls :meth:`interval` at the perf cadence. All methods are cheap
    host float math — nothing here touches a device.

    ``n_devices`` scales the per-chip peak/bandwidth figures to the whole
    program (cost analysis reports whole-program FLOPs).
    """

    def __init__(self, cost_model: dict[str, Any] | None, *,
                 device_kind: str = "", n_devices: int = 1,
                 peak_tflops_override: float = 0.0,
                 compute_dtype: str = "bf16"):
        self.cost_model = cost_model or {}
        self.n_devices = max(int(n_devices), 1)
        peak1 = peak_flops_for(device_kind, peak_tflops_override)
        self.peak_flops = peak1 * self.n_devices if peak1 else None
        # r17 low-precision headroom: under --quant_compute the narrow
        # peak (per-dtype table row) rides alongside — MFU keeps the
        # bf16 denominator (r13 convention, cross-round comparable) and
        # the narrow figure is reported next to it, or omitted when the
        # hardware has no narrow path (never invented)
        self.compute_dtype = compute_dtype
        self.quant_peak_flops = None
        if compute_dtype not in ("bf16", "off"):
            narrow1 = peak_flops_for(device_kind, 0.0, dtype=compute_dtype)
            self.quant_peak_flops = (narrow1 * self.n_devices
                                     if narrow1 else None)
        ici1 = _lookup(ICI_BYTES_PER_SEC, device_kind)
        self.ici_bytes_per_sec = ici1 * self.n_devices if ici1 else None
        hbm1 = _lookup(HBM_BYTES_PER_SEC, device_kind)
        self.hbm_bytes_per_sec = hbm1 * self.n_devices if hbm1 else None

    def describe(self) -> dict[str, Any]:
        """Startup-log summary of the static budget + the rate ceilings
        the runtime fractions will be computed against."""
        cm = self.cost_model
        out = {
            "model_gflops_per_step": round(
                cm.get("flops_per_step", 0.0) / 1e9, 3),
            "hbm_gb_per_step": round(
                cm.get("hbm_bytes_per_step", 0.0) / 1e9, 4),
            "wire_mb_per_step_data": round(
                cm.get("wire_bytes_data", 0) / 1e6, 3),
            "wire_mb_per_step_model": round(
                cm.get("wire_bytes_model", 0) / 1e6, 3),
        }
        if cm.get("wire_bytes_pipe"):
            out["wire_mb_per_step_pipe"] = round(
                cm["wire_bytes_pipe"] / 1e6, 3)
        if self.peak_flops:
            out["peak_tflops"] = round(self.peak_flops / 1e12, 2)
        if self.compute_dtype not in ("bf16", "off"):
            out["quant_compute"] = self.compute_dtype
            if self.quant_peak_flops:
                out[f"peak_tflops_{self.compute_dtype}"] = round(
                    self.quant_peak_flops / 1e12, 2)
                if self.peak_flops:
                    # the low-precision FLOPs headroom: how much faster
                    # the narrow MXU path is than the bf16 ceiling the
                    # MFU denominator uses
                    out["quant_peak_headroom"] = round(
                        self.quant_peak_flops / self.peak_flops, 2)
        if self.ici_bytes_per_sec:
            out["ici_gbps"] = round(self.ici_bytes_per_sec / 1e9, 1)
        if cm.get("pipe_bubble_frac"):
            out["pipe_bubble_frac_static"] = round(
                cm["pipe_bubble_frac"], 4)
        return out

    def interval(self, *, wall_s: float, steps: int,
                 input_wait_s: float = 0.0, device_wait_s: float = 0.0,
                 producer_idle_s: float = 0.0) -> dict[str, float]:
        """Attribute one interval of ``steps`` steps over ``wall_s``
        seconds of loop wall-clock.

        ``input_wait_s``: time the loop blocked on the loader (the
        consumer_wait delta). ``device_wait_s``: time the loop blocked in
        the dispatch-depth barrier's fence read — in a device-bound
        steady state this IS the device time the host observed.
        ``producer_idle_s``: the prefetch thread's full-queue idle time
        (slack indicator — reported, never a fraction: it overlaps
        compute by construction).

        Returns the ``perf_*`` fields for the progress record. The four
        fractions sum to exactly 1.0: input and host are measured, and
        the observed device share splits compute:comm by the static
        model's estimated times (everything compute when no comm budget
        or bandwidth figure exists). MFU follows the PaLM convention —
        model FLOPs over TOTAL wall (all overheads in the denominator).
        """
        wall_s = max(float(wall_s), 1e-9)
        steps = max(int(steps), 0)
        out: dict[str, float] = {}
        frac_input = min(max(input_wait_s, 0.0) / wall_s, 1.0)
        frac_device = min(max(device_wait_s, 0.0) / wall_s,
                          1.0 - frac_input)
        frac_host = max(0.0, 1.0 - frac_input - frac_device)

        flops = self.cost_model.get("flops_per_step", 0.0) * steps
        wire = self.cost_model.get("wire_bytes_total", 0) * steps
        hbm = self.cost_model.get("hbm_bytes_per_step", 0.0) * steps

        # split the OBSERVED device share by the static model's estimated
        # compute vs comm times; with no wire budget / no bandwidth
        # figure the device share is all compute (single-axis runs, CPU)
        comm_est_s = (wire / self.ici_bytes_per_sec
                      if wire and self.ici_bytes_per_sec else 0.0)
        compute_est_s = (flops / self.peak_flops
                         if flops and self.peak_flops else 0.0)
        total_est = comm_est_s + compute_est_s
        comm_share = comm_est_s / total_est if total_est > 0 else 0.0
        out["perf_frac_input"] = round(frac_input, 4)
        out["perf_frac_host"] = round(frac_host, 4)
        out["perf_frac_comm"] = round(frac_device * comm_share, 4)
        out["perf_frac_compute"] = round(
            frac_device - frac_device * comm_share, 4)
        # pipeline bubble: the static schedule model applied to the
        # MEASURED device share — an overlay on the compute fraction
        # (bubble slots are device-occupied-but-idle), never a fifth
        # term of the sum-to-1.0 quartet. Zero when no pipe axis.
        bubble = self.cost_model.get("pipe_bubble_frac", 0.0)
        out["perf_bubble_frac"] = round(frac_device * bubble, 4)

        if steps:
            out["perf_step_ms"] = round(1e3 * wall_s / steps, 3)
        if flops and self.peak_flops:
            out["perf_mfu"] = round(flops / wall_s / self.peak_flops, 4)
            out["perf_tflops_per_sec"] = round(flops / wall_s / 1e12, 3)
        if flops and self.quant_peak_flops:
            # utilisation against the NARROW peak (always <= perf_mfu):
            # the gap between the two is the unclaimed low-precision
            # headroom the r17 quant path exists to spend
            out["perf_mfu_vs_quant_peak"] = round(
                flops / wall_s / self.quant_peak_flops, 4)
        if hbm:
            out["perf_hbm_gbps"] = round(hbm / wall_s / 1e9, 2)
            if self.hbm_bytes_per_sec:
                out["perf_hbm_frac_of_peak"] = round(
                    hbm / wall_s / self.hbm_bytes_per_sec, 4)
        if wire:
            out["perf_wire_gbps"] = round(wire / wall_s / 1e9, 3)
        if producer_idle_s:
            # input-path slack, not a wall-clock fraction: the producer
            # idles concurrently with compute (large values + ~zero
            # frac_input = the input pipeline has headroom)
            out["perf_producer_idle_ms_per_step"] = round(
                1e3 * producer_idle_s / max(steps, 1), 3)
        return out
