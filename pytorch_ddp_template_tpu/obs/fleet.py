"""Fleet watchtower: cross-host straggler attribution over host-side signals.

The r12 flight recorder and r13 step-time X-ray made a *single host*
self-diagnosing, but every signal they produce is host-local: on a
multi-host pod the operational questions are "which host is slow right
now?" and "is one host quietly degrading?". Production LLM-training
experience (MegaScale, NSDI'24) puts stragglers and silent per-host
degradation at the top of the lost-goodput table, and the fix is always
the same shape: exchange each host's cheap host-side health numbers at
a low cadence, aggregate them rank-aware, and name the outlier.

This module is that exchange, sized for this engine:

- **Window** — once per perf/logging interval the engine packs its
  *host-side* signals (step wall, input/device-wait/host wall fractions,
  producer idle, goodput bucket deltas, anomaly state) into a flat float
  record keyed by :data:`FLEET_WIRE_KEYS`. Everything is host float math
  the loop already computed — nothing touches a device on the hot path.
- **Exchange** — :meth:`FleetMonitor.observe` runs on the r6
  ``AsyncTelemetry`` drain thread (``kind="fleet"`` records route here,
  never to the JSONL writer), encodes the window as a fixed-size vector
  and all-gathers it across processes
  (``jax.experimental.multihost_utils.process_allgather``; a
  single-process run skips the collective entirely, so the degenerate
  case costs a dict copy). Every process emits at the same cadence —
  the loop's logging boundary — so the collective is symmetric by
  construction. A transport failure retries with bounded backoff (the
  step-keyed round protocol makes retries idempotent), then degrades to
  the local row for THAT window only and re-probes on the next — a
  transient coordinator blip must not blind the watchtower, and the
  watchtower must never cost the run it watches.
- **Aggregation** — the fleet table: per-signal min/median/max plus the
  per-host rows, kept as :attr:`FleetMonitor.latest_table` (served by
  ``obs/server.py`` under ``/status`` and ``/metrics``) and logged on
  rank 0 at a gentle cadence.
- **Straggler verdict** — a host whose ``step_wall_ms`` exceeds the
  fleet median by more than ``threshold`` (relative) for ``windows``
  consecutive exchanges is named a straggler. The verdict feeds the r12
  sentry as a new ``kind="straggler"`` trigger
  (:meth:`obs.sentry.AnomalySentry.external_trigger`), so the standard
  triage bundle lands in ``flight_records/`` with the offending host in
  ``trigger.json``. A flagged host re-arms only after it returns under
  the threshold (one verdict per degradation episode, not one per
  window).

Threading contract: ``observe`` runs on the telemetry drain thread; the
table handoff is a single attribute rebind (read by the status server
and the engine without a lock — dict replacement is atomic in CPython).
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Callable

import numpy as np

from ..utils import get_logger
from ..utils.dist import process_count, process_index

log = get_logger(__name__)

#: the per-window host signals on the wire, in vector order (the
#: allgather ships one float32 per key; keep appends at the END so a
#: mixed-version fleet degrades to garbage-in-new-keys, not misaligned
#: old ones)
FLEET_WIRE_KEYS = (
    "step",               # global step of the window boundary
    "step_wall_ms",       # interval wall / steps — THE straggler signal
    "frac_input",         # fraction of wall blocked on the loader
    "frac_device",        # fraction of wall in the dispatch-depth fence
    "frac_host",          # remainder: host-side Python between dispatches
    "input_wait_ms",      # per-step loader block
    "producer_idle_ms",   # per-step prefetch slack
    "gp_productive_s",    # goodput ledger delta: productive seconds
    "gp_wall_s",          # goodput ledger delta: total seconds
    "anomaly",            # 1.0 when this host's sentry has triggered
    # -- r15 memory columns (appended at the END per the mixed-version
    #    tolerance above: an old peer's shorter row zero-fills these) --
    "mem_bytes_in_use",   # latest HBM bytes in use (max over local
    #                       devices; 0.0 when the backend reports none —
    #                       a host leaking memory is a straggler-to-be)
    "mem_frac_of_limit",  # that figure over the device limit (0.0
    #                       when unmeasured)
    # -- r16 pipeline column (appended at the END, same tolerance) --
    "bubble_frac",        # pipeline-bubble share of this host's wall
    #                       (the r16 perf_bubble_frac overlay: static
    #                       schedule model x measured device share;
    #                       0.0 when no pipe axis or no --perf_report)
)

#: signals the fleet table summarises with min/median/max (step is an
#: identity column; anomaly is summarised as a count)
SUMMARY_KEYS = tuple(k for k in FLEET_WIRE_KEYS
                     if k not in ("step", "anomaly"))


def encode_window(window: dict[str, Any]) -> np.ndarray:
    """Pack one host window into the fixed-order float32 wire vector
    (missing keys ship as 0.0 — a host that has no perf data yet must
    not stall the fleet's collective)."""
    return np.asarray([float(window.get(k, 0.0) or 0.0)
                       for k in FLEET_WIRE_KEYS], dtype=np.float32)


def decode_rows(rows: np.ndarray) -> list[dict[str, float]]:
    """Unpack the allgathered ``(n_hosts, len(FLEET_WIRE_KEYS))`` matrix
    back into per-host records (extra columns from a newer peer are
    ignored; short rows zero-fill)."""
    out: list[dict[str, float]] = []
    arr = np.atleast_2d(np.asarray(rows, dtype=np.float64))
    for host, row in enumerate(arr):
        rec: dict[str, float] = {"host": float(host)}
        for i, k in enumerate(FLEET_WIRE_KEYS):
            rec[k] = float(row[i]) if i < row.shape[0] else 0.0
        out.append(rec)
    return out


#: wall-clock bound on waiting for one peer's window in the exchange —
#: a wedged peer degrades THIS host to a partial table (its own row
#: substituted), it must never wedge the drain thread with it
KV_TIMEOUT_MS = 10_000

#: bounded retry-with-backoff before one window degrades to local-only
#: (r18 satellite): a transient coordinator blip must not blind the
#: watchtower for even one window when a 50ms retry would have worked
EXCHANGE_RETRIES = 2
EXCHANGE_BACKOFF_S = 0.05

#: rounds already exchanged, for best-effort store cleanup (the round
#: NUMBER itself is the window's global step since r18 — identical on
#: every host by SPMD construction, and stable across retries, so a
#: retried set/gather is idempotent instead of desynchronising the
#: fleet's round counters the way a per-call counter would)
_done_rounds: list[int] = []


def _default_exchange(vec: np.ndarray) -> np.ndarray:
    """Share this host's wire vector across processes via the
    ``jax.distributed`` coordination-service KV store — deliberately
    NOT a device collective: this runs on the telemetry drain thread,
    and issuing an XLA collective there would interleave with the
    train loop's own collectives in a thread-scheduling-dependent
    order across hosts (XLA:TPU requires every host to enqueue
    cross-host computations identically — a mismatched order deadlocks
    the very run the watchtower exists to watch). The KV store is the
    same gRPC side channel orbax and the distributed init use; it
    never touches a device. Single-process fleets are just this
    host's row (no jax.distributed involved at all).

    Exchange protocol: round-numbered keys — the round number is the
    window's global STEP (identical on every host: fleet windows are
    emitted at the same loop boundary), so a retried call re-sets the
    same key idempotently instead of advancing a per-call counter out
    of sync with the fleet. Set-then-gather with a bounded per-peer
    wait — a missing/laggard peer's row degrades to this host's own
    values rather than stalling; rounds older than the previous one
    are deleted best-effort so the store stays bounded."""
    if process_count() == 1:
        return vec[None, :]
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError("jax.distributed client not initialised")
    me = process_index()
    n = process_count()
    rnd = int(vec[0])  # the window's step: fleet-agreed, retry-stable
    payload = ",".join(repr(float(x)) for x in vec)
    client.key_value_set(f"obs_fleet/{rnd}/{me}", payload)
    rows = []
    for peer in range(n):
        if peer == me:
            rows.append(vec)
            continue
        try:
            raw = client.blocking_key_value_get(
                f"obs_fleet/{rnd}/{peer}", KV_TIMEOUT_MS)
            vals = [float(x) for x in raw.split(",")]
            # normalise to THIS version's width before stacking: a
            # mixed-version fleet (rolling upgrade appended keys) must
            # degrade to zero-filled/ignored columns, not a ValueError
            # from np.stack that permanently benches the exchange
            row = np.zeros(vec.shape[0], dtype=np.float32)
            k = min(len(vals), vec.shape[0])
            row[:k] = vals[:k]
            rows.append(row)
        except Exception:  # noqa: BLE001 - a laggard peer degrades to
            #               this host's row, never a stalled drain
            rows.append(vec)
    _done_rounds.append(rnd)
    if len(_done_rounds) > 2:  # bounded store: drop the round before last
        try:
            client.key_value_delete(f"obs_fleet/{_done_rounds.pop(0)}/")
        except Exception:  # noqa: BLE001 - cleanup is best-effort
            pass
    return np.stack(rows)


class FleetMonitor:
    """Aggregate per-host windows into a fleet table + straggler verdict.

    ``exchange`` is injectable (tests and the bench's injected-straggler
    leg fake a multi-host feed by returning extra rows); the default is
    the real cross-process allgather. ``on_straggler(step, verdict)``
    fires ONCE per degradation episode, on the drain thread — the engine
    points it at the sentry's external trigger.
    """

    def __init__(self, *, threshold: float = 0.25, windows: int = 3,
                 exchange: Callable[[np.ndarray], np.ndarray] | None = None,
                 on_straggler: Callable[[int, dict[str, Any]], None] | None
                 = None):
        if threshold <= 0:
            raise ValueError(f"straggler threshold must be > 0, got "
                             f"{threshold}")
        if windows < 1:
            raise ValueError(f"straggler windows must be >= 1, got "
                             f"{windows}")
        self.threshold = float(threshold)
        self.windows = int(windows)
        self._exchange = exchange or _default_exchange
        self.on_straggler = on_straggler
        #: most recent aggregated table (drain thread writes, status
        #: server / engine read — whole-dict rebind, no partial state)
        self.latest_table: dict[str, Any] | None = None
        self._suspect: dict[int, int] = {}   # host -> consecutive windows
        self._flagged: set[int] = set()      # named stragglers, re-armed
        #                                      when they recover
        self._exchange_failed = False
        self.exchanges = 0

    # -- drain-thread side -------------------------------------------------
    def observe(self, step: int, window: dict[str, Any]) -> None:
        """Feed this host's window (telemetry ``kind="fleet"`` route);
        exchanges, aggregates, detects. Never raises.

        Transport discipline (r18 satellite): a failed exchange retries
        ``EXCHANGE_RETRIES`` times with exponential backoff INSIDE this
        window (the step-keyed round protocol makes retries idempotent)
        before degrading to the local row; the degradation lasts this
        window only — the next window re-probes, and a recovery clears
        the degraded flag and says so, so a transient coordinator blip
        never permanently blinds the watchtower."""
        try:
            vec = encode_window(window)
            rows = None
            delay = EXCHANGE_BACKOFF_S
            for attempt in range(EXCHANGE_RETRIES + 1):
                try:
                    rows = self._exchange(vec)
                    break
                except Exception:  # noqa: BLE001 - transport down ≠ run down
                    if attempt < EXCHANGE_RETRIES:
                        time.sleep(delay)
                        delay *= 2
                    elif not self._exchange_failed:
                        self._exchange_failed = True
                        log.exception(
                            "fleet exchange failed after "
                            f"{EXCHANGE_RETRIES + 1} attempts; watching "
                            "this host only for this window (re-probing "
                            "next window; logged once per episode)")
            if rows is None:
                rows = vec[None, :]
            elif self._exchange_failed:
                self._exchange_failed = False
                log.info("fleet exchange recovered; cross-host "
                         "aggregation resumed")
            hosts = decode_rows(rows)
            table = self.aggregate(hosts, step=int(step))
            self.exchanges += 1
            verdicts = self._detect(table)
            # the table's headline carries the slowest CURRENTLY-flagged
            # host (not only newly-confirmed verdicts: an hour-long
            # episode must read as a straggler on every scrape, not just
            # the confirmation window), with this window's numbers
            table["straggler"] = self._headline(table)
            self.latest_table = table
            if self.on_straggler is not None:
                # every newly confirmed host gets its own verdict (two
                # hosts behind one sick switch both deserve naming)
                for verdict in verdicts:
                    self.on_straggler(int(step), verdict)
        except Exception:  # noqa: BLE001 - the watchtower must never
            #               kill the telemetry drain
            log.exception("fleet window dropped")

    # -- pure aggregation (unit-testable without any transport) ------------
    def aggregate(self, hosts: list[dict[str, float]], *,
                  step: int = 0) -> dict[str, Any]:
        """The fleet table over per-host rows: min/median/max per signal
        plus the rows themselves and the anomaly count."""
        table: dict[str, Any] = {
            "step": int(step),
            "time": time.time(),
            "n_hosts": len(hosts),
            "this_host": process_index(),
            "hosts": [dict(h) for h in hosts],
            "signals": {},
            "anomaly_hosts": [int(h["host"]) for h in hosts
                              if h.get("anomaly", 0.0) > 0],
            "straggler": None,
        }
        for key in SUMMARY_KEYS:
            vals = [float(h.get(key, 0.0)) for h in hosts]
            table["signals"][key] = {
                "min": min(vals),
                "median": statistics.median(vals),
                "max": max(vals),
            }
        return table

    def _detect(self, table: dict[str, Any]) -> list[dict[str, Any]]:
        """Straggler rule: ``step_wall_ms > median * (1 + threshold)``
        for ``windows`` consecutive exchanges — one verdict PER newly
        confirmed host (a degraded switch can make two hosts sick at
        once; naming only the slowest would silently suppress the
        other for its whole episode). Needs >= 3 hosts for a
        meaningful median (with 2, the median straddles both and a
        slow pair blames an innocent); a smaller fleet never fires.
        Returns [] when nothing newly confirmed."""
        hosts = table["hosts"]
        if len(hosts) < 3:
            return []
        med = table["signals"]["step_wall_ms"]["median"]
        if med <= 0:
            return []
        bar = med * (1.0 + self.threshold)
        verdicts: list[dict[str, Any]] = []
        for h in hosts:
            hid = int(h["host"])
            if h.get("step_wall_ms", 0.0) > bar:
                self._suspect[hid] = self._suspect.get(hid, 0) + 1
                if (self._suspect[hid] >= self.windows
                        and hid not in self._flagged):
                    self._flagged.add(hid)
                    verdicts.append({
                        "host": hid,
                        "step_wall_ms": round(h["step_wall_ms"], 3),
                        "fleet_median_ms": round(med, 3),
                        "excess_pct": round(
                            100.0 * (h["step_wall_ms"] / med - 1.0), 1),
                        "threshold_pct": round(100.0 * self.threshold, 1),
                        "consecutive_windows": self._suspect[hid],
                    })
            else:
                # back under the bar: reset the streak AND re-arm the
                # flag — the next sustained episode is a new verdict
                self._suspect[hid] = 0
                self._flagged.discard(hid)
        return verdicts

    def _headline(self, table: dict[str, Any]) -> dict[str, Any] | None:
        """The table's ``straggler`` slot: the slowest currently-flagged
        host with THIS window's numbers — stays set for the whole
        degradation episode (scrapers alert on it), None when no host
        is flagged."""
        flagged = [h for h in table["hosts"]
                   if int(h["host"]) in self._flagged]
        if not flagged:
            return None
        med = table["signals"]["step_wall_ms"]["median"]
        worst = max(flagged, key=lambda h: h.get("step_wall_ms", 0.0))
        hid = int(worst["host"])
        return {
            "host": hid,
            "step_wall_ms": round(worst.get("step_wall_ms", 0.0), 3),
            "fleet_median_ms": round(med, 3),
            "excess_pct": round(
                100.0 * (worst.get("step_wall_ms", 0.0) / med - 1.0), 1)
            if med > 0 else 0.0,
            "threshold_pct": round(100.0 * self.threshold, 1),
            "consecutive_windows": self._suspect.get(hid, 0),
        }

    # -- status-server side ------------------------------------------------
    def state(self) -> dict[str, Any]:
        """JSON-ready snapshot for ``/status``."""
        return {
            "exchanges": self.exchanges,
            "threshold": self.threshold,
            "windows": self.windows,
            "degraded_to_local": self._exchange_failed,
            "table": self.latest_table,
        }
