"""Perf-regression tripwires: a per-attempt steady-state fingerprint.

Goodput (r13) answers "how much wall-clock trained?"; this answers
"did the run come back *slower* than it used to be?" — the question a
restart (new jax wheel, reshard, different host pool) silently changes
the answer to. Two pieces:

- :class:`PerfBaseline` — at the end of every attempt the engine writes
  ``<output_dir>/perf_baseline.json`` next to ``goodput.json``: the
  steady-state step-wall percentiles (from the honest ``StepTimer`` —
  side-work intervals already discarded), rolling MFU and wire budget
  when ``--perf_report`` produced them, the host fraction, and a config
  signature (mesh/model/overlap flags/batch). On restore the NEXT
  attempt loads the prior fingerprint and, once its own timer has
  enough steady samples, compares: a step wall slower (or MFU lower)
  than the prior attempt by more than ``--regression_pct`` logs one
  WARNING per regressed signal with the delta — and names a config
  change when the signature differs (a resharded run that got slower is
  information, not noise).
- ``tools/bench_diff.py`` (the CLI sibling) applies the same
  out-of-band rule to ``bench_records/*.jsonl`` files, turning the
  committed records into executable tripwires.

Comparisons are direction-aware (:data:`DIRECTIONS`): step walls
regress upward, MFU/goodput regress downward. Signals missing on
either side are skipped — a baseline written without ``--perf_report``
still guards the step wall.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

from ..utils import get_logger, is_main_process
from ..utils.serialization import json_sanitize

log = get_logger(__name__)

FILENAME = "perf_baseline.json"

#: compared fingerprint signals -> which direction is a regression
DIRECTIONS = {
    "step_time_p50_ms": "higher_is_worse",
    "step_time_p90_ms": "higher_is_worse",
    "step_time_mean_ms": "higher_is_worse",
    "mfu": "lower_is_worse",
    # r15: peak HBM (measured watermark when the backend reports one,
    # else the static compile-time projection) — a restore whose memory
    # footprint grew out of band is marching toward the OOM cliff even
    # when its step walls look fine
    "peak_hbm_bytes": "higher_is_worse",
}

#: config facts that change what a fair step-wall comparison means —
#: recorded so a regression WARN can say "...and the config changed"
SIGNATURE_FIELDS = ("model", "mesh", "scan_layers", "fsdp", "fsdp_overlap",
                    "ddp_overlap", "tp_overlap", "grad_comm", "bf16",
                    "per_device_train_batch_size",
                    "gradient_accumulation_steps", "remat")


def config_signature(config: Any, *, n_devices: int | None = None
                     ) -> dict[str, Any]:
    """The comparable-run signature of a config (plus device count —
    the reshard case this tripwire exists for)."""
    sig = {f: getattr(config, f, None) for f in SIGNATURE_FIELDS}
    if n_devices is not None:
        sig["n_devices"] = int(n_devices)
    return sig


def make_fingerprint(*, timer_summary: dict[str, float],
                     mfu: float | None = None,
                     wire_bytes_total: float | None = None,
                     frac_host: float | None = None,
                     steps: int | None = None,
                     attempt: int = 1,
                     config_sig: dict[str, Any] | None = None,
                     peak_hbm_bytes: float | None = None
                     ) -> dict[str, Any]:
    """One attempt's steady-state perf fingerprint (JSON-ready)."""
    fp: dict[str, Any] = {
        "schema_version": 1,
        "attempt": int(attempt),
        "time": time.time(),
    }
    for k in ("step_time_p50_ms", "step_time_p90_ms", "step_time_p99_ms",
              "step_time_mean_ms"):
        if timer_summary.get(k) is not None:
            fp[k] = round(float(timer_summary[k]), 4)
    if mfu is not None:
        fp["mfu"] = float(mfu)
    if wire_bytes_total is not None:
        fp["wire_bytes_total"] = int(wire_bytes_total)
    if peak_hbm_bytes is not None:
        fp["peak_hbm_bytes"] = float(peak_hbm_bytes)
    if frac_host is not None:
        fp["frac_host"] = float(frac_host)
    if steps is not None:
        fp["steps"] = int(steps)
    if config_sig is not None:
        fp["config_sig"] = dict(config_sig)
    return fp


def compare_fingerprints(prior: dict[str, Any], current: dict[str, Any],
                         *, threshold_pct: float = 20.0) -> list[str]:
    """Direction-aware comparison; returns one human warning string per
    out-of-band signal (empty = within band). Signals absent or
    non-positive on either side are skipped."""
    warnings: list[str] = []
    config_note = ""
    ps, cs = prior.get("config_sig"), current.get("config_sig")
    if ps and cs and ps != cs:
        changed = sorted(k for k in set(ps) | set(cs)
                         if ps.get(k) != cs.get(k))
        config_note = (" (config changed since the baseline: "
                       + ", ".join(
                           f"{k} {ps.get(k)!r}->{cs.get(k)!r}"
                           for k in changed) + ")")
    tol = float(threshold_pct) / 100.0
    for key, direction in DIRECTIONS.items():
        p, c = prior.get(key), current.get(key)
        if not isinstance(p, (int, float)) or not isinstance(c, (int, float)):
            continue
        if p <= 0 or c <= 0:
            continue
        delta_pct = 100.0 * (c / p - 1.0)
        worse = (delta_pct > 100.0 * tol
                 if direction == "higher_is_worse"
                 else delta_pct < -100.0 * tol)
        if worse:
            warnings.append(
                f"{key} {p:.4g} -> {c:.4g} "
                f"({delta_pct:+.1f}% vs prior attempt "
                f"{prior.get('attempt', '?')}, band ±{threshold_pct:g}%)"
                + config_note)
    return warnings


class PerfBaseline:
    """Load/compare/persist the per-output-dir perf fingerprint."""

    def __init__(self, output_dir: str | Path):
        self.path = Path(output_dir) / FILENAME
        self._doc = self._load()
        #: the previous attempt's fingerprint (None on a fresh dir)
        self.prior: dict[str, Any] | None = (
            self._doc.get("fingerprint") if self._doc else None)
        # history as of THIS attempt's start: the prior doc's history
        # plus its fingerprint. Snapshotted once so repeated write()
        # calls within one attempt (r18: the fingerprint persists at
        # the perf cadence so a CRASHED attempt still leaves a
        # yardstick) stay idempotent instead of stuffing the bounded
        # history with same-attempt snapshots
        self._init_history: list[dict[str, Any]] = list(
            (self._doc or {}).get("history", []))
        if self.prior:
            self._init_history.append(self.prior)

    def _load(self) -> dict[str, Any] | None:
        try:
            if self.path.is_file():
                return json.loads(self.path.read_text())
        except Exception:  # noqa: BLE001 - a corrupt baseline must not
            #               kill the run; it just stops guarding
            log.exception("perf_baseline.json unreadable; starting fresh")
        return None

    def compare(self, current: dict[str, Any], *,
                threshold_pct: float = 20.0) -> list[str]:
        """Warnings for ``current`` vs the prior attempt's fingerprint
        (empty when no prior exists or everything is in band)."""
        if not self.prior:
            return []
        return compare_fingerprints(self.prior, current,
                                    threshold_pct=threshold_pct)

    def write(self, fingerprint: dict[str, Any]) -> None:
        """Persist ``fingerprint`` as the new baseline (host 0, atomic,
        best-effort); prior attempts' fingerprints are kept in a
        bounded history so a slow drift across many attempts stays
        visible. Idempotent within an attempt: the engine calls this at
        the perf cadence once the timer is steady (so a hard-killed
        attempt still leaves a yardstick — the elastic restart case)
        and again at clean shutdown."""
        if not is_main_process():
            return
        history = list(self._init_history)
        payload = {
            "schema": "perf_baseline/v1",
            "fingerprint": fingerprint,
            "history": history[-16:],
            "note": "steady-state perf fingerprint per attempt; compared "
                    "on restore — a restarted run slower than this by "
                    "more than --regression_pct WARNs with the delta",
        }
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(json_sanitize(payload), indent=2,
                                      allow_nan=False))
            tmp.replace(self.path)
        except Exception:  # noqa: BLE001
            log.exception("perf_baseline.json write failed")
