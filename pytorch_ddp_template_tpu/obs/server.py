"""Live status endpoint: ``/status`` (JSON), ``/metrics`` (Prometheus
text format), ``/healthz`` — the watchtower's window into a running job.

Everything the obs stack produces today is post-hoc files in
``output_dir``; a production fleet needs the same signals *live*, from
every host, over the one transport every ops stack already speaks:
HTTP. ``--status_port N`` starts a background
``ThreadingHTTPServer`` on a daemon thread serving three routes:

- ``GET /status`` — one JSON document: the latest drained telemetry
  records by kind (progress/perf/eval/mem), the goodput summary, sentry
  state, the fleet table, the memory-monitor state (r15), and the
  startup ``describe.json`` snapshot.
  All state is already host-side (drained) floats; request handling
  never touches a device and never blocks the train loop.
- ``GET /metrics`` — the same numerics in Prometheus text exposition
  format (gauges, ``tpuddp_`` prefix), so a stock Prometheus/Grafana
  scrape works with zero glue. Label values are escaped per the
  exposition spec (backslash, quote, newline); metric names are
  sanitised to ``[a-zA-Z_:][a-zA-Z0-9_:]*``.
- ``GET /healthz`` — liveness: 200 with ``{"ok": true, step, age_s}``.

Data flow: the engine chains :meth:`StatusServer.note_record` onto the
telemetry ``on_write`` hook (drain thread) and registers lazy
``sources`` callables (goodput summary, sentry state, fleet table) that
are evaluated per request — the server holds no stale copies of state
that changes between scrapes. Updates are whole-value rebinds under one
lock; a request sees a consistent snapshot.

Lifecycle: started before the train loop, closed in the engine's
``finally`` (crash-safe: a dying run takes its endpoint down instead of
serving frozen numbers forever). Binding failures log and disable the
server — the endpoint must never cost the run it observes.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from ..utils import get_logger
from ..utils.dist import process_index
from ..utils.serialization import json_sanitize

log = get_logger(__name__)

#: every Prometheus metric this exporter emits is a gauge with this
#: prefix (one namespace, greppable, collision-free with node exporters)
PROM_PREFIX = "tpuddp_"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(key: str) -> str:
    """Sanitise a record key into a legal Prometheus metric name."""
    name = _NAME_OK.sub("_", str(key))
    if not name or name[0].isdigit():
        name = "_" + name
    return PROM_PREFIX + name


def prom_escape(value: str) -> str:
    """Escape a label value per the Prometheus text exposition format:
    backslash, double-quote and newline."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _gauge(lines: list[str], seen: set[str], name: str, value: Any,
           labels: dict[str, Any] | None = None, help_: str = "") -> None:
    """Append one gauge sample (TYPE/HELP emitted once per metric).
    Non-numeric and non-finite values are skipped — a scrape must stay
    parseable even while the job is mid-NaN (the JSON channel keeps the
    ``null``+``_repr`` spelling for those). A repeated (name, labels)
    sample is skipped too: duplicate samples make the whole exposition
    invalid to Prometheus, and ``perf_*`` fields legitimately appear in
    BOTH the progress record and an off-cadence ``perf`` record — first
    emitter (the fresher progress record) wins."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return
    if v != v or v in (float("inf"), float("-inf")):
        return
    label_s = ""
    if labels:
        inner = ",".join(f'{k}="{prom_escape(v2)}"'
                         for k, v2 in labels.items())
        label_s = "{" + inner + "}"
    if name + label_s in seen:
        return
    seen.add(name + label_s)
    if ("#type#" + name) not in seen:
        seen.add("#type#" + name)
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} gauge")
    lines.append(f"{name}{label_s} {v!r}")


#: HELP text for record-derived gauges whose meaning is not readable
#: from the name alone — the speculative-serving rates especially: an
#: alerting rule on acceptance collapse should not need the repo docs
_RECORD_HELP = {
    "serve_spec_accept_rate": "draft tokens accepted / drafted, lifetime "
                              "(speculative decoding)",
    "serve_spec_accept_rate_rolling": "EWMA acceptance over recent verify "
                                      "rounds (the adaptive-k signal)",
    "serve_spec_accepted_per_target_step": "tokens committed per (slot, "
                                           "verify round) — the >1 "
                                           "multiplier spec decoding buys",
    "serve_spec_draft_s_total": "draft-model wall (prefill + proposal "
                                "loop) — the wager's cost side",
    "serve_spec_verify_s_total": "target verify wall (one batched "
                                 "dispatch per round)",
    "serve_spec_k_mean": "mean adaptive draft window over running "
                         "requests",
    "serve_tp_degree": "model-axis shards the ring decode program "
                       "spans (1 = single-replica path)",
    "serve_tp_ring_wire_mb_per_step": "decode-step ring bytes actually "
                                      "on the wire (quantized when "
                                      "--quant_compute rides the ring)",
    "serve_tp_ring_wire_mb_per_step_wide": "decode-step ring bytes at "
                                           "full f32 chunk width",
    "serve_tp_ring_wire_mb_per_step_quant": "decode-step ring bytes at "
                                            "the r17 int8 wire width",
    "serve_tp_kv_pool_bytes_per_shard": "paged KV pool residency per "
                                        "model shard (heads split over "
                                        "the ring)",
}


def prometheus_lines(snapshot: dict[str, Any]) -> str:
    """Render a ``/status``-shaped snapshot as Prometheus text format.

    Flat numeric fields of the latest ``progress``/``perf`` records
    become gauges (vectors like ``per_layer_grad_norm`` are a
    JSONL-only channel and are skipped); goodput buckets carry a
    ``bucket`` label; fleet signals carry a ``host`` label per row.
    """
    lines: list[str] = []
    seen: set[str] = set()
    host = str(snapshot.get("host", 0))
    _gauge(lines, seen, prom_name("step"), snapshot.get("step", 0),
           {"host": host}, help_="latest drained global step")
    age = snapshot.get("age_s")
    if age is not None:
        _gauge(lines, seen, prom_name("last_update_age_seconds"), age,
               {"host": host})
    for kind in ("progress", "perf", "mem", "serve"):
        rec = snapshot.get("records", {}).get(kind) or {}
        for k, v in rec.items():
            if isinstance(v, (list, tuple)) or k.endswith("_repr"):
                continue  # vectors / repr strings: JSONL-only channels
            _gauge(lines, seen, prom_name(k), v, {"host": host},
                   help_=_RECORD_HELP.get(k))
    gp = snapshot.get("goodput") or {}
    if gp.get("goodput") is not None:
        _gauge(lines, seen, prom_name("goodput_ratio"), gp["goodput"],
               {"host": host},
               help_="productive_step over total wall, all attempts")
    for bucket, secs in (gp.get("buckets_s") or {}).items():
        _gauge(lines, seen, prom_name("goodput_seconds_total"), secs,
               {"host": host, "bucket": bucket})
    sentry = snapshot.get("sentry") or {}
    if sentry:
        _gauge(lines, seen, prom_name("anomaly_triggered"),
               1.0 if sentry.get("triggered") else 0.0, {"host": host})
    sup = snapshot.get("supervisor") or {}
    if sup:
        # the r18 supervisor: decision count + whether it has acted
        # (checkpoint -> evict -> stop), with the eviction target as a
        # label so an alert can name the drained host without parsing
        # supervisor.json
        _gauge(lines, seen, prom_name("supervisor_decisions_total"),
               len(sup.get("decisions") or []), {"host": host},
               help_="verdicts the supervisor evaluated (act or warn)")
        _gauge(lines, seen, prom_name("supervisor_acted"),
               1.0 if sup.get("acted") else 0.0, {"host": host})
        ev = next((d for d in reversed(sup.get("decisions") or [])
                   if d.get("action") == "evict" and d.get("acted")),
                  None)
        _gauge(lines, seen, prom_name("supervisor_eviction_active"),
               0.0 if ev is None else 1.0,
               {"host": host,
                "evicted_host": "" if ev is None else str(ev.get("host"))})
    fleet = (snapshot.get("fleet") or {}).get("table") or {}
    for row in fleet.get("hosts") or []:
        h = str(int(row.get("host", 0)))
        for k, v in row.items():
            if k == "host":
                continue
            _gauge(lines, seen, prom_name(f"fleet_{k}"), v, {"host": h})
    strag = fleet.get("straggler")
    if fleet:
        _gauge(lines, seen, prom_name("fleet_straggler"),
               0.0 if strag is None else 1.0,
               {"host": "" if strag is None else str(strag.get("host"))})
    mem = snapshot.get("memory") or {}
    if mem:
        # the r15 HBM watchtower: per-device gauges (device-labelled)
        # plus the host-level watermark/limit/pressure summary. Absent
        # entries (CPU backends report no memory_stats) simply emit no
        # sample — a scrape never shows an invented 0-byte HBM.
        # per-device family under its OWN metric names: the latest mem
        # RECORD also exports host-level mem_bytes_in_use/... gauges
        # (the records loop above), and one metric name carrying both a
        # host-level max and per-device samples would double-count in
        # any PromQL sum over the family
        for row in mem.get("devices") or []:
            labels = {"host": host, "device": str(int(row.get("device", 0)))}
            _gauge(lines, seen, prom_name("mem_device_bytes_in_use"),
                   row.get("bytes_in_use"), labels,
                   help_="HBM bytes in use per device (memory_stats)")
            _gauge(lines, seen, prom_name("mem_device_peak_bytes"),
                   row.get("peak_bytes_in_use"), labels)
            _gauge(lines, seen, prom_name("mem_device_limit_bytes"),
                   row.get("bytes_limit"), labels)
        if mem.get("watermark_bytes"):
            _gauge(lines, seen, prom_name("mem_watermark_bytes"),
                   mem["watermark_bytes"], {"host": host},
                   help_="high-watermark HBM bytes in use this attempt")
        if mem.get("limit_bytes") and mem.get("watermark_bytes"):
            _gauge(lines, seen, prom_name("mem_watermark_frac_of_limit"),
                   float(mem["watermark_bytes"]) / float(mem["limit_bytes"]),
                   {"host": host})
        if "pressure_active" in mem:
            _gauge(lines, seen, prom_name("mem_pressure_active"),
                   1.0 if mem.get("pressure_active") else 0.0,
                   {"host": host})
        split = (mem.get("static") or {}).get("split") or {}
        _gauge(lines, seen, prom_name("mem_projected_peak_bytes"),
               split.get("projected_peak_bytes"), {"host": host},
               help_="compile-time projected peak (memory_analysis)")
    return "\n".join(lines) + "\n"


class StatusServer:
    """Background HTTP endpoint for one training process.

    ``port=0`` binds an ephemeral port (tests); the engine passes the
    configured ``--status_port``. ``self.port`` holds the actual bound
    port after :meth:`start`.
    """

    def __init__(self, port: int = 0, *, host: str = "0.0.0.0"):
        self._bind = (host, int(port))
        self._lock = threading.Lock()
        self._records: dict[str, dict[str, Any]] = {}
        self._static: dict[str, Any] = {}
        #: lazy per-request state providers (goodput summary, sentry
        #: state, fleet table): evaluated at scrape time, best-effort
        self.sources: dict[str, Callable[[], Any]] = {}
        self._step = 0
        self._last_update: float | None = None
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.port = int(port)

    # -- producers (drain thread / engine) ---------------------------------
    def note_record(self, kind: str, step: int, host: dict[str, Any]) -> None:
        """Latest drained telemetry record by kind (chained onto the
        telemetry ``on_write`` hook)."""
        with self._lock:
            self._records[kind] = dict(host)
            self._step = max(self._step, int(step))
            self._last_update = time.time()

    def set_static(self, key: str, value: Any) -> None:
        """Startup facts that never change mid-run (the describe.json
        snapshot, config)."""
        with self._lock:
            self._static[key] = value

    # -- snapshot ----------------------------------------------------------
    def liveness(self) -> dict[str, Any]:
        """The ``/healthz`` payload: step + age only, no source
        evaluation — a liveness probe hitting this every few seconds
        must stay constant-time."""
        with self._lock:
            return {
                "ok": True,
                "step": self._step,
                "age_s": (round(time.time() - self._last_update, 3)
                          if self._last_update else None),
                "host": process_index(),
            }

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            snap: dict[str, Any] = {
                "host": process_index(),
                "time": time.time(),
                "step": self._step,
                "age_s": (round(time.time() - self._last_update, 3)
                          if self._last_update else None),
                "records": {k: dict(v) for k, v in self._records.items()},
                **{k: v for k, v in self._static.items()},
            }
        for key, fn in self.sources.items():
            try:
                snap[key] = fn()
            except Exception:  # noqa: BLE001 - one broken source must
                #               not take down the whole endpoint
                snap[key] = {"error": "source failed"}
        return snap

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: A003 - silence stdlib
                pass  # request logging would interleave the train log

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib casing
                try:
                    path = self.path.split("?", 1)[0].rstrip("/") or "/"
                    if path == "/healthz":
                        body = json.dumps(server.liveness()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/status":
                        body = json.dumps(
                            json_sanitize(server.snapshot()),
                            indent=2, default=str,
                            allow_nan=False).encode()
                        self._send(200, body, "application/json")
                    elif path == "/metrics":
                        body = prometheus_lines(
                            json_sanitize(server.snapshot())).encode()
                        self._send(200, body,
                                   "text/plain; version=0.0.4")
                    else:
                        self._send(404, b'{"error": "not found"}',
                                   "application/json")
                except Exception:  # noqa: BLE001 - a broken scrape must
                    #               never surface into the training run
                    try:
                        self._send(500, b'{"error": "internal"}',
                                   "application/json")
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer(self._bind, Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="status-server")
        self._thread.start()
        log.info("status server listening",
                 {"port": self.port,
                  "routes": ["/status", "/metrics", "/healthz"]})

    def close(self) -> None:
        """Stop serving (idempotent; called from the engine's finally —
        a dead run must not keep answering scrapes with frozen data)."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:  # noqa: BLE001
            log.exception("status server shutdown failed")
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
