"""Model zoo registry: name → (Task, Dataset) factories.

The reference's "zoo" is one hardcoded model (``ddp.py:311``); the
BASELINE.md config ladder defines the real surface (MLP → ResNet-18/50 →
BERT-base → ViT-B/16). Each entry builds the Flax task and its paired
synthetic dataset from the :class:`TrainingConfig`.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..config import TrainingConfig
from ..data.dataset import Dataset
from .task import Task

_REGISTRY: dict[str, Callable[[TrainingConfig], tuple[Task, Dataset]]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def available_models() -> list[str]:
    return sorted(_REGISTRY)


def build(name: str, config: TrainingConfig, mesh=None) -> tuple[Task, Dataset]:
    """Build (task, dataset). ``mesh`` is consumed by entries that embed
    mesh-dependent ops (ring attention); omitted, those entries construct
    one from ``config.mesh`` over all devices."""
    import inspect

    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {available_models()}"
        ) from None
    if "mesh" in inspect.signature(factory).parameters:
        task, ds = factory(config, mesh=mesh)
    else:
        task, ds = factory(config)
    if config.num_layers:
        # depth override (the --num_layers draft-training workflow):
        # clone BEFORE the other knobs so remat/scan see the final depth
        if not hasattr(task.model, "num_layers"):
            raise ValueError(
                f"--num_layers: model {name!r} "
                f"({type(task.model).__name__}) has no transformer "
                "layer-depth knob (transformer families only; the "
                "pipelined entries own their stage stacking)"
            )
        task.model = task.model.clone(num_layers=config.num_layers)
    if config.remat:
        if not hasattr(task.model, "remat"):
            raise ValueError(
                f"--remat: model {name!r} ({type(task.model).__name__}) has "
                "no remat knob"
            )
        kwargs = {"remat": True}
        if config.remat_policy == "save-convs":
            if not hasattr(task.model, "remat_save_convs"):
                raise ValueError(
                    f"--remat_policy save-convs: model {name!r} "
                    f"({type(task.model).__name__}) has no named conv "
                    "checkpoints (ResNet-family only)"
                )
            kwargs["remat_save_convs"] = True
        task.model = task.model.clone(**kwargs)
    if config.fused_head:
        if not hasattr(task.model, "fused_head"):
            raise ValueError(
                f"--fused_head: model {name!r} "
                f"({type(task.model).__name__}) has no LM head"
            )
        task.model = task.model.clone(fused_head=True)
    if name.startswith("gpt-pipe"):
        # the pipelined entries run OUTSIDE the flax-module knob surface
        # (task.model is None): their schedule composition is validated
        # here, with pipe-specific reasons, before any tracing. Since
        # r22 the 1f1b slot loop composes with ONE of tp/ddp/fsdp
        # (boundary-hoisted collective waves — parallel/pipeline.py);
        # what remains refused is genuinely impossible, reason named.
        compose_on = [f for f in ("tp_overlap", "ddp_overlap",
                                  "fsdp_overlap")
                      if getattr(config, f, False)]
        if config.fsdp and not config.fsdp_overlap:
            raise ValueError(
                f"--fsdp does not compose with the pipelined entries "
                f"({name!r}): GSPMD-managed data splits of the stage "
                "stack would be silently re-gathered by the slot "
                "region's specs every step; use --fsdp_overlap — the "
                "slot-boundary gather/scatter wave — instead"
            )
        if len(compose_on) > 1:
            raise ValueError(
                f"--{' --'.join(compose_on)}: the pipelined entries "
                f"({name!r}) compose pipe with exactly ONE of "
                "tp/ddp/fsdp per run (the slot boundary carries one "
                "uniform collective wave); drop all but one flag"
            )
        if compose_on and config.pipe_schedule != "1f1b":
            raise ValueError(
                f"--{compose_on[0]} rides the 1f1b slot loop only: "
                "gpipe differentiates through the masked fill/drain "
                "loop (no slot boundary to hoist collectives to) and "
                "zb's bit-exact tapped backward has no decomposed twin "
                "yet; pass --pipe_schedule 1f1b"
            )
        if config.ddp_overlap and config.grad_error_feedback:
            raise ValueError(
                "--grad_error_feedback does not compose with the "
                f"pipelined entries ({name!r}): the residual would have "
                "to telescope across the slot loop's per-microbatch "
                "partial reduces instead of whole-step gradients; drop "
                "the flag or use a non-pipe entry"
            )
        if compose_on:
            from ..parallel.schedule import validate_schedule_mesh
            from ..runtime import make_mesh

            import jax

            if mesh is None:
                mesh = make_mesh(config.mesh, jax.devices())
            # fail fast, before any tracing, with the pipe-aware
            # refusal matrix (pipe×data×model for tp, pipe×data for
            # ddp/fsdp)
            validate_schedule_mesh(
                mesh, pipe=True, tp=config.tp_overlap,
                ddp=config.ddp_overlap, fsdp=config.fsdp_overlap)
        if getattr(config, "quant_compute", "off") != "off":
            raise ValueError(
                f"--quant_compute does not compose with the pipelined "
                f"entries ({name!r}) yet: the zb schedule's tapped "
                "backward is a bit-exact twin of the block built from "
                "_plain_dense, and quantized dots inside the slot "
                "loop's switch branches would break that pin; drop the "
                "flag or use a non-pipe entry"
            )
    if config.scan_layers:
        if name.startswith("gpt-pipe"):
            # stage-local scan-over-layers: each stage drives ONE block
            # body over its (layers_per_stage, ...) stack inside the
            # slot schedule (models/gpt_pipe.py) — the checkpoint layout
            # (the (P, layers_per_stage, ...) stage stacking) is
            # identical either way, so no conversion is needed
            task.scan_layers = True
        else:
            if not hasattr(task.model, "scan_layers"):
                raise ValueError(
                    f"--scan_layers: model {name!r} "
                    f"({type(task.model).__name__}) has no transformer "
                    "layer stack to scan (transformer families only)"
                )
            task.model = task.model.clone(scan_layers=True)
    if config.fsdp_overlap and not name.startswith("gpt-pipe"):
        if not config.scan_layers:
            raise ValueError(
                "--fsdp_overlap needs --scan_layers: the stacked "
                "(num_layers, ...) weight layout IS the unit of the "
                "prefetch schedule (and keeps checkpoints in the scanned "
                "layout); pass both flags"
            )
        if not hasattr(task.model, "fsdp_overlap"):
            raise ValueError(
                f"--fsdp_overlap: model {name!r} "
                f"({type(task.model).__name__}) has no decomposed-FSDP "
                "execution path (transformer families only)"
            )
        if getattr(task.model, "moe_experts", 0):
            raise ValueError(
                "--fsdp_overlap does not compose with MoE entries yet "
                "(sown load-balance losses and expert dispatch need "
                "in-region handling); drop one of the two"
            )
        from ..parallel.overlap import validate_overlap_mesh
        from ..runtime import make_mesh

        import jax

        if mesh is None:
            mesh = make_mesh(config.mesh, jax.devices())
        # fail fast, before any tracing; tp=True (fsdp×tp composition)
        # admits the model axis the gather specs will carry
        validate_overlap_mesh(mesh, tp=config.tp_overlap)
        task.model = task.model.clone(fsdp_overlap=True, mesh=mesh)
    if config.ddp_overlap and not name.startswith("gpt-pipe"):
        if not config.scan_layers:
            raise ValueError(
                "--ddp_overlap needs --scan_layers: the stacked "
                "(num_layers, ...) weight layout IS the unit of the "
                "per-layer reduce schedule (and keeps checkpoints in the "
                "scanned layout); pass both flags"
            )
        if not hasattr(task.model, "ddp_overlap"):
            raise ValueError(
                f"--ddp_overlap: model {name!r} "
                f"({type(task.model).__name__}) has no compressed-DDP "
                "execution path (transformer families only)"
            )
        if getattr(task.model, "moe_experts", 0):
            raise ValueError(
                "--ddp_overlap does not compose with MoE entries yet "
                "(sown load-balance losses and expert dispatch need "
                "in-region handling); drop one of the two"
            )
        from ..parallel.compress import validate_ddp_mesh
        from ..runtime import make_mesh

        import jax

        if mesh is None:
            mesh = make_mesh(config.mesh, jax.devices())
        # fail fast, before any tracing; tp=True (ddp×tp composition)
        # moves the region onto data×model with the local ring kernels
        validate_ddp_mesh(mesh, tp=config.tp_overlap)
        task.model = task.model.clone(
            ddp_overlap=True, mesh=mesh, grad_comm=config.grad_comm,
            grad_error_feedback=config.grad_error_feedback)
    if config.tp_overlap and not name.startswith("gpt-pipe"):
        # --scan_layers is co-required by config.__post_init__; this path
        # also covers direct TrainingConfig construction with both set
        if not hasattr(task.model, "tp_overlap"):
            raise ValueError(
                f"--tp_overlap: model {name!r} "
                f"({type(task.model).__name__}) has no tensor-parallel "
                "transformer stack to decompose (transformer families "
                "only)"
            )
        if getattr(task.model, "moe_experts", 0):
            raise ValueError(
                "--tp_overlap does not compose with MoE entries yet (the "
                "expert dispatch needs in-region handling); drop one of "
                "the two"
            )
        from ..parallel.collective_matmul import validate_tp_mesh
        from ..runtime import make_mesh

        import jax

        if mesh is None:
            mesh = make_mesh(config.mesh, jax.devices())
        validate_tp_mesh(mesh)  # fail fast, before any tracing
        kwargs = {"tp_overlap": True, "mesh": mesh}
        if hasattr(task.model, "fused_head"):
            # the ring vocab head IS the LM head under --tp_overlap: the
            # (B,T,V) logits tensor must never materialise on any shard
            kwargs["fused_head"] = True
        task.model = task.model.clone(**kwargs)
    if config.quant_compute != "off":
        # low-precision compute (ops/quant.py): per-channel scaled
        # int8/fp8 dots in the block matmuls (and, composed with
        # --tp_overlap, inside the ring collective matmuls — the clone
        # above already carries tp_overlap, so the encoder routes the
        # quantized ring kernels)
        if not hasattr(task.model, "quant_compute"):
            raise ValueError(
                f"--quant_compute: model {name!r} "
                f"({type(task.model).__name__}) has no transformer block "
                "matmuls to quantize (transformer families only)"
            )
        if getattr(task.model, "moe_experts", 0):
            raise ValueError(
                "--quant_compute does not compose with MoE entries yet "
                "(the expert dispatch and per-expert FFNs have no "
                "quantized path); drop one of the two"
            )
        task.model = task.model.clone(quant_compute=config.quant_compute)
    if config.data_dir:
        from ..data.filestore import MemmapDataset

        if not isinstance(ds, MemmapDataset):
            # silently training on synthetic data while the user believes
            # their store is in use would be the worst kind of success
            raise ValueError(
                f"--data_dir is not supported by model {name!r} (it built a "
                f"{type(ds).__name__}); file-backed stores serve the image "
                "and token families"
            )
    return task, ds


def _dtype(config: TrainingConfig):
    return jnp.bfloat16 if config.bf16 else jnp.float32


@register("mlp")
def _mlp(config: TrainingConfig):
    from ..data.dataset import SyntheticRegressionDataset
    from .mlp import MLP
    from .task import RegressionTask

    task = RegressionTask(MLP(features=(10, 5), dtype=_dtype(config)))
    ds = SyntheticRegressionDataset(samples=config.dataset_size, seed=config.seed)
    return task, ds


@register("mlp-wide")
def _mlp_wide(config: TrainingConfig):
    """MXU-sized MLP: same path as the toy config but with 1024-wide
    matmuls so single-chip benchmarking measures compute, not dispatch."""
    from ..data.dataset import SyntheticRegressionDataset
    from .mlp import MLP
    from .task import RegressionTask

    task = RegressionTask(MLP(features=(1024, 1024, 5), dtype=_dtype(config)))
    ds = SyntheticRegressionDataset(samples=config.dataset_size, seed=config.seed)
    return task, ds


def _image_entry(config: TrainingConfig, model_factory, image_size: int,
                 num_classes: int):
    """Classification task + images; ``model_factory`` takes
    ``(num_classes, dtype)`` and returns the Flax module. Data comes from
    ``config.data_dir`` (memory-mapped store, the real-data rung) when set,
    else the synthetic source; augmentation runs on device either way."""
    from .task import ClassificationTask

    task = ClassificationTask(model_factory(num_classes, _dtype(config)),
                              augment=config.augment)
    if config.data_dir:
        from ..data.filestore import MemmapDataset

        ds = MemmapDataset(config.data_dir)
        missing = {"image", "label"} - set(ds.arrays)
        if missing:
            raise ValueError(
                f"store {config.data_dir} lacks keys {sorted(missing)} "
                f"(has {sorted(ds.arrays)})"
            )
        got = ds.arrays["image"].shape[1:3]
        if got != (image_size, image_size):
            raise ValueError(
                f"store images are {got}, model {config.model} expects "
                f"({image_size}, {image_size})"
            )
        dtype = ds.arrays["image"].dtype
        if dtype != np.uint8:
            # the on-device normalisation assumes [0, 255] bytes; a
            # pre-normalised float store would collapse to ~-1.0 silently
            raise ValueError(
                f"store images are {dtype}, expected uint8 (normalisation "
                "to [-1, 1] happens on device)"
            )
        max_label = int(ds.arrays["label"].max()) if len(ds) else 0
        if max_label >= num_classes:
            raise ValueError(
                f"store labels reach {max_label}, model {config.model} has "
                f"{num_classes} classes"
            )
        return task, ds
    from ..data.dataset import SyntheticImageDataset

    ds = SyntheticImageDataset(
        samples=config.dataset_size, image_size=image_size,
        num_classes=num_classes, seed=config.seed,
    )
    return task, ds


@register("resnet18")
def _resnet18(config: TrainingConfig):
    """ResNet-18 / CIFAR-10-shaped data (BASELINE.md ladder rung 2)."""
    from .resnet import ResNet18

    # norm_dtype follows the compute dtype: BN statistics stay f32 inside
    # flax regardless, and bf16 normalise/ReLU traffic between convs is
    # worth +27% step time on the HBM-bound resnet50 (tools/mfu_probe.py,
    # bench_records/mfu_probe_tpu_r4.jsonl)
    factory = lambda n, dt: ResNet18(num_classes=n, dtype=dt, stem="cifar",
                                     norm_dtype=dt)
    return _image_entry(config, factory, image_size=32, num_classes=10)


@register("resnet50")
def _resnet50(config: TrainingConfig):
    """ResNet-50 / ImageNet-shaped data — the BASELINE.json headline config."""
    from .resnet import ResNet50

    factory = lambda n, dt: ResNet50(num_classes=n, dtype=dt, stem="imagenet",
                                     norm_dtype=dt)
    return _image_entry(config, factory, image_size=224, num_classes=1000)


@register("bert-base")
def _bert_base(config: TrainingConfig):
    """BERT-base MLM on synthetic 512-token sequences (BASELINE.md rung 4)."""
    from .bert import MlmTask, bert_base

    seq_len, vocab = 512, 30_522
    task = MlmTask(bert_base(dtype=_dtype(config), seq_len=seq_len,
                             vocab_size=vocab))
    return _token_entry(config, task, seq_len, vocab)


@register("bert-tiny")
def _bert_tiny(config: TrainingConfig):
    """2-layer BERT on short synthetic sequences — the CPU-CI language config."""
    from .bert import MlmTask, bert_tiny

    seq_len, vocab = 128, 1024
    task = MlmTask(bert_tiny(dtype=_dtype(config), seq_len=seq_len,
                             vocab_size=vocab))
    return _token_entry(config, task, seq_len, vocab)


@register("vit-b16")
def _vit_b16(config: TrainingConfig):
    """ViT-B/16 / ImageNet-shaped data (BASELINE.md rung 5; bf16 + accum)."""
    from .vit import vit_b16

    factory = lambda n, dt: vit_b16(num_classes=n, dtype=dt)
    return _image_entry(config, factory, image_size=224, num_classes=1000)


@register("vit-tiny")
def _vit_tiny(config: TrainingConfig):
    """2-layer ViT on 32px images — the CPU-CI vision-transformer config."""
    from .vit import vit_tiny

    factory = lambda n, dt: vit_tiny(num_classes=n, dtype=dt)
    return _image_entry(config, factory, image_size=32, num_classes=10)


@register("bert-long")
def _bert_long(config: TrainingConfig, mesh=None):
    """Long-context BERT (4096 tokens): ring attention over the ``seq``
    mesh axis when the mesh has one — the context-parallel rung."""
    from ..runtime import make_mesh
    from .bert import MlmTask, bert_long

    import jax

    if mesh is None:
        mesh = make_mesh(config.mesh, jax.devices())
    seq_len, vocab = 4096, 30_522
    task = MlmTask(bert_long(seq_len=seq_len, dtype=_dtype(config), mesh=mesh,
                             vocab_size=vocab, cp_impl=config.cp_impl))
    # padded batches: the ring path consumes the key-padding mask natively
    return _token_entry(config, task, seq_len, vocab, padded=True)


@register("bert-long-tiny")
def _bert_long_tiny(config: TrainingConfig, mesh=None):
    """Test-sized long-context config: 2-layer BERT, 512 tokens, ring
    attention when the mesh has a ``seq`` axis (CPU-CI exercisable)."""
    from ..runtime import make_mesh
    from .bert import MlmTask, bert_long

    import jax

    if mesh is None:
        mesh = make_mesh(config.mesh, jax.devices())
    seq_len, vocab = 512, 1024
    task = MlmTask(bert_long(seq_len=seq_len, dtype=_dtype(config), mesh=mesh,
                             vocab_size=vocab, cp_impl=config.cp_impl,
                             num_layers=2, num_heads=4, head_dim=16,
                             mlp_dim=128))
    return _token_entry(config, task, seq_len, vocab, padded=True)


def _token_entry(config: TrainingConfig, task, seq_len: int, vocab: int,
                 *, padded: bool = False):
    """Token task + sequences: ``config.data_dir`` (memory-mapped token
    store with ``input_ids`` [+ ``attention_mask``]) when set, else the
    synthetic source — the same disk contract the image families have
    (reference map-style dataset: ``/root/reference/dataset.py:6-17``).
    Stores come from any tokeniser writing ``StoreWriter`` batches, or
    ``tools/make_file_dataset.py --model gpt-small`` for a fabricated one."""
    if config.data_dir:
        from ..data.filestore import MemmapDataset

        ds = MemmapDataset(config.data_dir)
        if "input_ids" not in ds.arrays:
            raise ValueError(
                f"store {config.data_dir} lacks key 'input_ids' "
                f"(has {sorted(ds.arrays)})"
            )
        ids = ds.arrays["input_ids"]
        if not np.issubdtype(ids.dtype, np.integer):
            raise ValueError(
                f"store input_ids are {ids.dtype}, expected an integer type"
            )
        if ids.shape[1:] != (seq_len,):
            raise ValueError(
                f"store sequences are {list(ids.shape[1:])}, model "
                f"{config.model} expects [{seq_len}]"
            )
        # bounded probe (first 1024 rows): a full memmap scan of an
        # ImageNet-scale store would stall startup; out-of-range ids later
        # fail loudly anyway (embedding gather is checked on CPU, and the
        # probe catches the systematic case of a vocab mismatch)
        probe = np.asarray(ids[: min(len(ds), 1024)])
        if probe.size and (int(probe.min()) < 0 or int(probe.max()) >= vocab):
            raise ValueError(
                f"store token ids span [{int(probe.min())}, "
                f"{int(probe.max())}], model {config.model} has vocab {vocab}"
            )
        if padded and "attention_mask" not in ds.arrays:
            raise ValueError(
                f"store {config.data_dir} lacks 'attention_mask' — the "
                f"long-context model {config.model} consumes key-padding "
                "masks (pad to full length with mask=1 rows if the corpus "
                "is unpadded)"
            )
        return task, ds
    from ..data.dataset import SyntheticTokenDataset

    ds = SyntheticTokenDataset(samples=config.dataset_size, seq_len=seq_len,
                               vocab=vocab, seed=config.seed, padded=padded)
    return task, ds


@register("gpt-small")
def _gpt_small(config: TrainingConfig):
    """GPT-2-small causal LM on synthetic 1024-token sequences."""
    from .gpt import CausalLmTask, gpt_small

    seq_len, vocab = 1024, 50_257
    task = CausalLmTask(gpt_small(dtype=_dtype(config), seq_len=seq_len,
                                  vocab_size=vocab))
    return _token_entry(config, task, seq_len, vocab)


@register("gpt-tiny")
def _gpt_tiny(config: TrainingConfig):
    """2-layer GPT on short sequences — the CPU-CI causal-LM config."""
    from .gpt import CausalLmTask, gpt_tiny

    seq_len, vocab = 128, 1024
    task = CausalLmTask(gpt_tiny(dtype=_dtype(config), seq_len=seq_len,
                                 vocab_size=vocab))
    return _token_entry(config, task, seq_len, vocab)


@register("gpt-moe-tiny")
def _gpt_moe_tiny(config: TrainingConfig, mesh=None):
    """Tiny MoE causal LM: top-1 expert FFNs, expert-parallel over the
    ``expert`` mesh axis when present (CPU-CI exercisable)."""
    from ..runtime import make_mesh
    from .gpt import CausalLmTask, gpt_moe_tiny

    import jax

    if mesh is None:
        mesh = make_mesh(config.mesh, jax.devices())
    seq_len, vocab = 128, 1024
    task = CausalLmTask(gpt_moe_tiny(dtype=_dtype(config), seq_len=seq_len,
                                     vocab_size=vocab, mesh=mesh))
    return _token_entry(config, task, seq_len, vocab)


@register("gpt-pipe-tiny")
def _gpt_pipe_tiny(config: TrainingConfig, mesh=None):
    """Pipeline-parallel causal LM: the block stack runs as a pipeline
    over the ``pipe`` mesh axis through the ordinary Trainer
    (models/gpt_pipe.py) under the ``--pipe_schedule`` of choice
    (gpipe | 1f1b | zb). Launch: ``--model gpt-pipe-tiny --mesh
    data:4,pipe:2`` (CPU-CI exercisable)."""
    from ..runtime import make_mesh
    from .gpt_pipe import PipelinedGptTask

    import jax

    if mesh is None:
        mesh = make_mesh(config.mesh, jax.devices())
    seq_len, vocab = 128, 1024
    task = PipelinedGptTask(mesh, vocab_size=vocab, seq_len=seq_len,
                            num_layers=4, num_heads=4, head_dim=16,
                            mlp_dim=128, dtype=_dtype(config),
                            n_micro=config.pipe_microbatches,
                            pipe_schedule=config.pipe_schedule,
                            tp_overlap=config.tp_overlap,
                            ddp_overlap=config.ddp_overlap,
                            fsdp_overlap=config.fsdp_overlap,
                            grad_comm=config.grad_comm)
    return _token_entry(config, task, seq_len, vocab)


@register("gpt-long")
def _gpt_long(config: TrainingConfig, mesh=None):
    """Long-context GPT (4096 tokens): causal ring attention over the
    ``seq`` mesh axis when present."""
    from ..runtime import make_mesh
    from .gpt import CausalLmTask, gpt_long

    import jax

    if mesh is None:
        mesh = make_mesh(config.mesh, jax.devices())
    seq_len, vocab = 4096, 50_257
    task = CausalLmTask(gpt_long(seq_len=seq_len, dtype=_dtype(config),
                                 mesh=mesh, vocab_size=vocab,
                                 cp_impl=config.cp_impl))
    return _token_entry(config, task, seq_len, vocab)


@register("gpt-long-tiny")
def _gpt_long_tiny(config: TrainingConfig, mesh=None):
    """Test-sized long-context causal config (CPU-CI exercisable)."""
    from ..runtime import make_mesh
    from .gpt import CausalLmTask, gpt_long

    import jax

    if mesh is None:
        mesh = make_mesh(config.mesh, jax.devices())
    seq_len, vocab = 512, 1024
    task = CausalLmTask(gpt_long(seq_len=seq_len, dtype=_dtype(config),
                                 mesh=mesh, vocab_size=vocab,
                                 cp_impl=config.cp_impl, num_layers=2,
                                 num_heads=4, head_dim=16, mlp_dim=128))
    return _token_entry(config, task, seq_len, vocab)
