"""Model zoo registry: name → (Task, Dataset) factories.

The reference's "zoo" is one hardcoded model (``ddp.py:311``); the
BASELINE.md config ladder defines the real surface (MLP → ResNet-18/50 →
BERT-base → ViT-B/16). Each entry builds the Flax task and its paired
synthetic dataset from the :class:`TrainingConfig`.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from ..config import TrainingConfig
from ..data.dataset import Dataset
from .task import Task

_REGISTRY: dict[str, Callable[[TrainingConfig], tuple[Task, Dataset]]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def available_models() -> list[str]:
    return sorted(_REGISTRY)


def build(name: str, config: TrainingConfig) -> tuple[Task, Dataset]:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {available_models()}"
        ) from None
    return factory(config)


def _dtype(config: TrainingConfig):
    return jnp.bfloat16 if config.bf16 else jnp.float32


@register("mlp")
def _mlp(config: TrainingConfig):
    from ..data.dataset import SyntheticRegressionDataset
    from .mlp import MLP
    from .task import RegressionTask

    task = RegressionTask(MLP(features=(10, 5), dtype=_dtype(config)))
    ds = SyntheticRegressionDataset(samples=config.dataset_size, seed=config.seed)
    return task, ds


@register("mlp-wide")
def _mlp_wide(config: TrainingConfig):
    """MXU-sized MLP: same path as the toy config but with 1024-wide
    matmuls so single-chip benchmarking measures compute, not dispatch."""
    from ..data.dataset import SyntheticRegressionDataset
    from .mlp import MLP
    from .task import RegressionTask

    task = RegressionTask(MLP(features=(1024, 1024, 5), dtype=_dtype(config)))
    ds = SyntheticRegressionDataset(samples=config.dataset_size, seed=config.seed)
    return task, ds


def _image_entry(config: TrainingConfig, model_cls, image_size: int,
                 num_classes: int, stem: str):
    from ..data.dataset import SyntheticImageDataset
    from .task import ClassificationTask

    task = ClassificationTask(
        model_cls(num_classes=num_classes, dtype=_dtype(config), stem=stem)
    )
    ds = SyntheticImageDataset(
        samples=config.dataset_size, image_size=image_size,
        num_classes=num_classes, seed=config.seed,
    )
    return task, ds


@register("resnet18")
def _resnet18(config: TrainingConfig):
    """ResNet-18 / CIFAR-10-shaped data (BASELINE.md ladder rung 2)."""
    from .resnet import ResNet18

    return _image_entry(config, ResNet18, image_size=32, num_classes=10,
                        stem="cifar")


@register("resnet50")
def _resnet50(config: TrainingConfig):
    """ResNet-50 / ImageNet-shaped data — the BASELINE.json headline config."""
    from .resnet import ResNet50

    return _image_entry(config, ResNet50, image_size=224, num_classes=1000,
                        stem="imagenet")
