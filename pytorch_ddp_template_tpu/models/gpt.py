"""GPT-family decoder-only causal LM.

No counterpart in the reference (zoo = one MLP,
``/root/reference/model.py:8-16``); this family completes the long-context
story for the autoregressive case: the causal paths of the Pallas flash
kernel (block-skipped lower triangle, ``ops/flash.py``) and of ring
attention (offset-correct distributed causal masking,
``parallel/ring.py``) run inside a real model here. TPU-first choices
match the rest of the zoo: pre-LN blocks, bf16 compute with f32 norms,
tied embedding/LM head (one MXU transpose matmul), remat for long
configs.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from .task import Task
from .transformer import TransformerEncoder, default_kernel_init


class GptDecoder(nn.Module):
    """Decoder-only transformer LM.

    Returns next-token logits ``(B, T, V)`` — or, with ``fused_head=True``,
    final hidden states ``(B, T, E)`` for the blockwise head the task
    applies (``ops/lm_head.py``)."""

    vocab_size: int = 50_257
    max_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    mlp_dim: int = 3072
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0
    attn_impl: str = "auto"  # Impl | "ring" (context parallelism)
    mesh: jax.sharding.Mesh | None = None
    remat: bool = False
    moe_experts: int = 0  # >0: MoE FFN (models/moe.py) in every block
    # one nn.scan-compiled block over (num_layers, ...)-stacked weights
    # instead of num_layers unrolled copies: O(1) compile time in depth,
    # remat-scan memory profile when composed with remat (--scan_layers)
    scan_layers: bool = False
    # decomposed FSDP (--fsdp_overlap, parallel/overlap.py): prefetched
    # per-layer weight gathers + overlapped grad drain; needs scan_layers
    fsdp_overlap: bool = False
    # compressed DDP (--ddp_overlap, parallel/compress.py): per-layer
    # grad reduce inside the backward scan, in grad_comm wire precision,
    # optional error-feedback residual; needs scan_layers
    ddp_overlap: bool = False
    grad_comm: str = "fp32"
    grad_error_feedback: bool = False
    # ring-decomposed TP collective matmuls (--tp_overlap,
    # parallel/collective_matmul.py): qkv/fc1 as all-gather-matmul rings,
    # out/fc2 as matmul-reduce-scatter rings over the `model` axis; the
    # tied LM head accumulates per-vocab-shard partial logits around the
    # same ring (ops/lm_head.tp_lm_head_loss). Needs scan_layers + a
    # data×model mesh; registry turns fused_head on alongside
    tp_overlap: bool = False
    # low-precision compute (--quant_compute, ops/quant.py): the block
    # matmuls run as per-channel-scaled int8/fp8 dots from the fp32
    # masters; fused into the TP rings when tp_overlap is on
    quant_compute: str = "off"
    # blockwise tied head (ops/lm_head.py): the model returns final hidden
    # states and the task computes cross-entropy vocab-block-wise — the
    # (B, T, V) logits tensor never exists. The memory enabler for the
    # long-context rung (1.6 GB of logits+softmax at seq 4096, GPT-2 vocab)
    fused_head: bool = False

    @nn.compact
    def __call__(self, input_ids, *, train: bool = True):
        embed_dim = self.num_heads * self.head_dim
        embed = nn.Embed(
            self.vocab_size,
            embed_dim,
            dtype=self.dtype,
            embedding_init=nn.with_logical_partitioning(
                default_kernel_init, ("vocab", "embed")
            ),
            name="wte",
        )
        pos = nn.Embed(self.max_len, embed_dim, dtype=self.dtype,
                       embedding_init=default_kernel_init, name="wpe")
        x = embed(input_ids) + pos(jnp.arange(input_ids.shape[1]))[None]
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = TransformerEncoder(
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            pre_norm=True,  # GPT-2 style
            attn_impl=self.attn_impl,
            mesh=self.mesh,
            causal=True,
            remat=self.remat,
            moe_experts=self.moe_experts,
            scan_layers=self.scan_layers,
            fsdp_overlap=self.fsdp_overlap,
            ddp_overlap=self.ddp_overlap,
            grad_comm=self.grad_comm,
            grad_error_feedback=self.grad_error_feedback,
            tp_overlap=self.tp_overlap,
            quant_compute=self.quant_compute,
            name="decoder",
        )(x, train=train)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x)
        if self.fused_head:
            return x.astype(self.dtype)  # head applied blockwise by the task
        logits = embed.attend(x.astype(self.dtype))  # tied head
        return logits.astype(jnp.float32)


class CausalLmTask(Task):
    """Next-token cross-entropy over ``batch = {"input_ids": (B, T)}``."""

    seq_dims = {"input_ids": 1}

    def model_inputs(self, batch):
        return (batch["input_ids"],)

    def loss(self, params, extra_vars, batch, rng, *, train=True):
        input_ids = batch["input_ids"]
        out, extra_vars, aux = self._apply_inputs(
            params, extra_vars, (input_ids,), rng, train
        )

        # predict token t+1 from prefix ..t; last position has no target
        targets = input_ids[:, 1:].astype(jnp.int32)
        if getattr(self.model, "fused_head", False):
            # ``out`` is final hidden states; head computed blockwise
            # against the tied table (ops/lm_head.py) — no (B,T,V) logits.
            # Under --tp_overlap the vocab shards stay put and the hidden
            # chunks ring past them (tp_lm_head_loss)
            token_logp, hits = self.blockwise_head(
                out[:, :-1], params["wte"]["embedding"], targets,
                mesh=self.model.mesh if getattr(
                    self.model, "tp_overlap", False) else None)
        else:
            logp = jax.nn.log_softmax(out[:, :-1], axis=-1)
            token_logp = jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            hits = (jnp.argmax(out[:, :-1], -1) == targets).astype(jnp.float32)
        # per-example weights (exactly-once eval) broadcast over target slots
        w = self.example_weights(batch, token_logp.shape[0])[:, None]
        metrics = self.weighted_metrics(
            w.sum() * token_logp.shape[1], train,  # weighted target tokens
            loss=-(token_logp * w).sum(),
            next_token_accuracy=(hits * w).sum(),
        )
        total, metrics = self._with_aux(metrics, aux)
        return total, extra_vars, metrics


def gpt_small(dtype=jnp.float32, attn_impl: str = "auto", remat: bool = False,
              seq_len: int = 1024, vocab_size: int = 50_257,
              mesh=None, fused_head: bool = False) -> GptDecoder:
    """GPT-2-small shape: 12 layers, 12 heads, 768 wide (~124M params)."""
    return GptDecoder(vocab_size=vocab_size, max_len=seq_len, dtype=dtype,
                      attn_impl=attn_impl, mesh=mesh, remat=remat,
                      fused_head=fused_head)


def gpt_long(seq_len: int = 4096, dtype=jnp.float32, mesh=None,
             vocab_size: int = 50_257, cp_impl: str = "ring",
             **size_overrides) -> GptDecoder:
    """Long-context GPT: causal context-parallel attention (``cp_impl`` =
    ``"ring"`` or ``"ulysses"``) over the ``seq`` mesh axis when present,
    blockwise attention otherwise; remat per block."""
    if cp_impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown cp_impl {cp_impl!r}")
    cp = bool(mesh) and mesh.shape.get("seq", 1) > 1
    return GptDecoder(vocab_size=vocab_size, max_len=seq_len, dtype=dtype,
                      attn_impl=cp_impl if cp else "blockwise",
                      mesh=mesh if cp else None, remat=True,
                      fused_head=True,  # logits never materialise (lm_head)
                      **size_overrides)


def gpt_tiny(dtype=jnp.float32, attn_impl: str = "auto", seq_len: int = 128,
             vocab_size: int = 1024) -> GptDecoder:
    """Test-sized GPT: 2 layers, 2 heads — CPU-CI fast."""
    return GptDecoder(vocab_size=vocab_size, max_len=seq_len, num_layers=2,
                      num_heads=2, head_dim=32, mlp_dim=128, dtype=dtype,
                      attn_impl=attn_impl)


def gpt_moe_tiny(dtype=jnp.float32, seq_len: int = 128,
                 vocab_size: int = 1024, mesh=None,
                 num_experts: int = 4) -> GptDecoder:
    """Test-sized MoE GPT: every block's FFN is a top-1 mixture of
    ``num_experts`` experts (models/moe.py); with an ``expert`` mesh axis
    the experts shard and tokens flow over all_to_all dispatch."""
    return GptDecoder(vocab_size=vocab_size, max_len=seq_len, num_layers=2,
                      num_heads=2, head_dim=32, mlp_dim=128, dtype=dtype,
                      mesh=mesh, moe_experts=num_experts)
