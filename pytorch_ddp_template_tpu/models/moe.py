"""Mixture-of-experts feed-forward block for the transformer zoo.

No counterpart in the reference (zoo = one MLP, ``/root/reference/
model.py:8-16``) — this integrates the expert-parallel mechanism
(``parallel/expert.py``) into a real model family: a top-1-routed FFN
drop-in for ``MlpBlock``, selected per block via
``TransformerEncoder(moe_experts=E)``.

Two execution paths, numerically identical (tests/test_moe.py):

- **dispatch** (mesh has an ``expert`` axis of size > 1): the real
  expert-parallel dataflow — ``all_to_all`` token exchange to
  expert-sharded weights. Capacity is each rank's full token count, and a
  top-1 source can never route more than that to one expert, so nothing
  drops and the paths agree exactly.
- **dense** (no expert axis): every expert computes every token, the
  router one-hot selects — the correct-by-construction baseline for tiny
  meshes and CPU CI.

Expert weights carry the ``expert`` logical axis, which
``parallel/sharding.py`` maps onto the ``expert`` mesh axis — one expert's
weights per rank, the standard EP layout.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..runtime.context import DATA_AXIS, EXPERT_AXIS
from .transformer import default_kernel_init


class MoeMlpBlock(nn.Module):
    """Top-1-routed position-wise FFN over ``num_experts`` experts.

    The expert output is scaled by the token's top-1 softmax gate
    probability — the standard trick that gives the router a gradient
    (argmax alone is piecewise-constant and would freeze routing at
    initialization)."""

    num_experts: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    mesh: jax.sharding.Mesh | None = None
    act: Callable = nn.gelu
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        d = x.shape[-1]
        e = self.num_experts
        part = nn.with_logical_partitioning
        # gate replicated (("embed", None)): expert_apply's contract, and
        # splitting a (d, E) vector per expert rank would buy nothing
        gate = self.param("gate", part(default_kernel_init, ("embed", None)),
                          (d, e), jnp.float32)
        w_in = self.param("w_in",
                          part(default_kernel_init, ("expert", "embed", "mlp")),
                          (e, d, self.mlp_dim), jnp.float32)
        b_in = self.param("b_in", part(nn.initializers.zeros, ("expert", "mlp")),
                          (e, self.mlp_dim), jnp.float32)
        w_out = self.param("w_out",
                           part(default_kernel_init, ("expert", "mlp", "embed")),
                           (e, self.mlp_dim, d), jnp.float32)
        b_out = self.param("b_out", part(nn.initializers.zeros, ("expert", "embed")),
                           (e, d), jnp.float32)

        tokens = x.reshape(-1, d)
        params = {
            "w_in": w_in.astype(self.dtype), "b_in": b_in.astype(self.dtype),
            "w_out": w_out.astype(self.dtype), "b_out": b_out.astype(self.dtype),
        }
        gate_c = gate.astype(self.dtype)

        def expert_fn(w, t):
            return self.act(t @ w["w_in"] + w["b_in"]) @ w["w_out"] + w["b_out"]

        mesh = self.mesh
        ep = mesh.shape.get(EXPERT_AXIS, 1) if mesh is not None else 1
        dp = mesh.shape.get(DATA_AXIS, 1) if mesh is not None else 1
        # dispatch needs exactly one expert per expert-axis rank (the
        # minimal mechanism's layout); other expert counts use the dense
        # path with weights still sharded per the logical annotations
        if ep > 1 and ep == e and tokens.shape[0] % (ep * dp) == 0:
            from ..parallel.expert import expert_apply

            # batch_axis: each data group dispatches only its own tokens —
            # without it the global token set would replicate over data and
            # every data rank would duplicate the expert FFN compute
            y = expert_apply(params, expert_fn, gate_c, tokens, mesh,
                             batch_axis=DATA_AXIS if dp > 1 else None)
        else:
            # dense fallback: every expert computes every token; the
            # router's one-hot selects. O(E) flops — fine at proof scale.
            dest = jnp.argmax(tokens @ gate_c, axis=-1)
            ys = jax.vmap(lambda w: expert_fn(w, tokens))(params)
            onehot = jax.nn.one_hot(dest, e, dtype=ys.dtype)
            y = jnp.einsum("etd,te->td", ys, onehot)

        # scale by the top-1 gate probability: the router's gradient path
        # (computed in f32; identical on both branches since both route by
        # argmax of the same logits)
        logits = (tokens @ gate_c).astype(jnp.float32)  # same routing logits
        probs = jax.nn.softmax(logits, axis=-1)
        top_p = jnp.max(probs, axis=-1)
        y = y * top_p[:, None].astype(y.dtype)

        if train:
            # Switch-style load-balance loss: E * sum_e f_e * P_e, where
            # f_e = fraction of tokens routed to e, P_e = mean gate prob.
            # Minimised at uniform routing; without it top-1 routing
            # collapses onto few experts. Tasks read the "losses"
            # collection and add it to the objective.
            f = jnp.mean(jax.nn.one_hot(jnp.argmax(logits, -1), e,
                                        dtype=jnp.float32), axis=0)
            p_mean = jnp.mean(probs, axis=0)
            self.sow("losses", "moe_load_balance", e * jnp.sum(f * p_mean),
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y.reshape(x.shape).astype(self.dtype)
