"""BERT-family masked-LM — the language rung of the BASELINE.md ladder.

No counterpart exists in the reference (zoo = one MLP,
``/root/reference/model.py:8-16``); BASELINE.md names "BERT-base MLM
fine-tune" as ladder rung 4. TPU-first choices:

- Post-LN encoder from ``models/transformer.py`` (flash attention on TPU).
- Embedding table carries logical axes ``("vocab", "embed")`` so tensor
  parallelism can shard the vocab dimension (``parallel/sharding.py``).
- MLM head ties the decoder to the word embedding (standard BERT) — one
  (vocab, embed) matrix, one transpose matmul on the MXU.
- Dynamic masking happens *inside jit* on device (``MlmTask.loss``): the
  host ships raw int32 token ids (4 bytes/token over PCIe) and the 15%
  BERT corruption (80/10/10 mask/random/keep) is drawn from the step rng —
  fresh masks every epoch with zero host cost, where a torch pipeline
  would re-run a Python collator every batch.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from .task import Task
from .transformer import TransformerEncoder, default_kernel_init


class BertEncoder(nn.Module):
    """BERT encoder: embeddings + post-LN transformer stack, returning
    final hidden states; the MLM logits come from the tied embedding."""

    vocab_size: int = 30_522
    max_len: int = 512
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    mlp_dim: int = 3072
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.1
    attn_impl: str = "auto"  # Impl | "ring" (context parallelism)
    mesh: jax.sharding.Mesh | None = None
    remat: bool = False
    # scan-over-layers (models/transformer.py): one compiled block over
    # (num_layers, ...)-stacked weights — O(1) compile time in depth
    scan_layers: bool = False
    # decomposed FSDP (--fsdp_overlap, parallel/overlap.py): prefetched
    # per-layer weight gathers + overlapped grad drain; needs scan_layers
    fsdp_overlap: bool = False
    # compressed DDP (--ddp_overlap, parallel/compress.py): per-layer
    # grad reduce inside the backward scan, in grad_comm wire precision,
    # optional error-feedback residual; needs scan_layers
    ddp_overlap: bool = False
    grad_comm: str = "fp32"
    grad_error_feedback: bool = False
    # ring-decomposed TP collective matmuls (--tp_overlap,
    # parallel/collective_matmul.py); the tied MLM head rides the same
    # ring (ops/lm_head.tp_lm_head_loss). Needs scan_layers + data×model
    tp_overlap: bool = False
    # low-precision compute (--quant_compute, ops/quant.py): the block
    # matmuls run as per-channel-scaled int8/fp8 dots from the fp32
    # masters; fused into the TP rings when tp_overlap is on
    quant_compute: str = "off"
    # blockwise tied MLM head (ops/lm_head.py): return the transformed
    # head hidden states; the task applies table+bias vocab-block-wise,
    # so the (B, T, V) logits tensor never exists
    fused_head: bool = False

    def setup(self):
        embed_dim = self.num_heads * self.head_dim
        self.word_embed = nn.Embed(
            self.vocab_size,
            embed_dim,
            dtype=self.dtype,
            embedding_init=nn.with_logical_partitioning(
                default_kernel_init, ("vocab", "embed")
            ),
            name="word_embeddings",
        )
        self.pos_embed = nn.Embed(
            self.max_len, embed_dim, dtype=self.dtype,
            embedding_init=default_kernel_init, name="position_embeddings",
        )
        self.embed_ln = nn.LayerNorm(dtype=jnp.float32, name="embeddings_ln")
        self.dropout = nn.Dropout(self.dropout_rate)
        self.encoder = TransformerEncoder(
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            pre_norm=False,  # original BERT is post-LN
            attn_impl=self.attn_impl,
            mesh=self.mesh,
            remat=self.remat,
            scan_layers=self.scan_layers,
            fsdp_overlap=self.fsdp_overlap,
            ddp_overlap=self.ddp_overlap,
            grad_comm=self.grad_comm,
            grad_error_feedback=self.grad_error_feedback,
            tp_overlap=self.tp_overlap,
            quant_compute=self.quant_compute,
            name="encoder",
        )
        self.mlm_ln = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")
        self.mlm_dense = nn.Dense(
            self.num_heads * self.head_dim, dtype=self.dtype, name="mlm_dense"
        )
        self.mlm_bias = self.param(
            "mlm_bias", nn.initializers.zeros, (self.vocab_size,), jnp.float32
        )

    def __call__(self, input_ids, attention_mask=None, *, train: bool = True):
        seq_len = input_ids.shape[1]
        x = self.word_embed(input_ids)
        x = x + self.pos_embed(jnp.arange(seq_len))[None]
        x = self.embed_ln(x).astype(self.dtype)
        x = self.dropout(x, deterministic=not train)
        mask = None
        if attention_mask is not None:
            # (B, T) keep-mask -> (B, 1, 1, T) broadcastable over heads/q
            mask = attention_mask[:, None, None, :].astype(bool)
        h = self.encoder(x, mask, train=train)
        # MLM head: transform + tied decoder
        h = nn.gelu(self.mlm_dense(h))
        h = self.mlm_ln(h).astype(self.dtype)
        if self.fused_head:
            return h  # task applies the tied decoder blockwise
        logits = self.word_embed.attend(h)  # (B, T, vocab), tied weights
        return logits.astype(jnp.float32) + self.mlm_bias


class MlmTask(Task):
    """Masked-LM objective with on-device dynamic masking.

    ``batch = {"input_ids": int32 (B, T)}``. Each step draws BERT's 15%
    corruption from the per-step rng: of selected positions 80% become
    ``[MASK]``, 10% a random token, 10% keep; loss is cross-entropy on
    selected positions only.
    """

    MASK_TOKEN = 103  # BERT's [MASK] id
    mask_rate = 0.15
    #: sequence dim of each batch key — the loader shards it over the
    #: ``seq`` mesh axis when context parallelism is on
    seq_dims = {"input_ids": 1, "attention_mask": 1}

    def model_inputs(self, batch):
        if "attention_mask" in batch:
            return (batch["input_ids"], batch["attention_mask"])
        return (batch["input_ids"],)

    def _corrupt(self, input_ids, rng, vocab):
        r_select, r_op, r_tok = jax.random.split(rng, 3)
        u = jax.random.uniform(r_select, input_ids.shape)
        selected = u < self.mask_rate
        op = jax.random.uniform(r_op, input_ids.shape)
        random_tokens = jax.random.randint(r_tok, input_ids.shape, 0, vocab,
                                           dtype=input_ids.dtype)
        corrupted = jnp.where(op < 0.8, self.MASK_TOKEN,
                              jnp.where(op < 0.9, random_tokens, input_ids))
        return jnp.where(selected, corrupted, input_ids), selected

    def loss(self, params, extra_vars, batch, rng, *, train=True):
        input_ids = batch["input_ids"]
        attention_mask = batch.get("attention_mask")
        vocab = self.model.vocab_size
        if rng is None:  # eval: deterministic masking keyed on nothing
            rng = jax.random.PRNGKey(0)
        mask_rng, dropout_rng = jax.random.split(rng)
        corrupted, selected = self._corrupt(input_ids, mask_rng, vocab)
        if attention_mask is not None:
            # padded positions: never corrupted, never scored
            selected = selected & attention_mask.astype(bool)
            corrupted = jnp.where(attention_mask.astype(bool), corrupted,
                                  input_ids)

        out, extra_vars, aux = self._apply_inputs(
            params, extra_vars, (corrupted, attention_mask), dropout_rng,
            train,
        )

        targets = input_ids.astype(jnp.int32)
        if getattr(self.model, "fused_head", False):
            token_logp, hits = self.blockwise_head(
                out, params["word_embeddings"]["embedding"], targets,
                bias=params["mlm_bias"],
                mesh=self.model.mesh if getattr(
                    self.model, "tp_overlap", False) else None)
        else:
            logp = jax.nn.log_softmax(out, axis=-1)
            token_logp = jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            hits = (jnp.argmax(out, -1) == targets).astype(jnp.float32)
        sel = selected.astype(jnp.float32)
        # exactly-once eval: zero out whole padded examples (loader weight)
        sel = sel * self.example_weights(batch, sel.shape[0])[:, None]
        metrics = self.weighted_metrics(
            sel.sum(), train,  # weighted selected-token count
            loss=-(token_logp * sel).sum(),
            mlm_accuracy=(hits * sel).sum(),
        )
        total, metrics = self._with_aux(metrics, aux)
        return total, extra_vars, metrics


def bert_base(dtype=jnp.float32, attn_impl: str = "auto", remat: bool = False,
              seq_len: int = 512, vocab_size: int = 30_522,
              mesh=None, fused_head: bool = False) -> BertEncoder:
    return BertEncoder(vocab_size=vocab_size, max_len=seq_len, dtype=dtype,
                       attn_impl=attn_impl, mesh=mesh, remat=remat,
                       fused_head=fused_head)


def bert_long(seq_len: int = 4096, dtype=jnp.float32, mesh=None,
              vocab_size: int = 30_522, cp_impl: str = "ring",
              **size_overrides) -> BertEncoder:
    """Long-context BERT: context-parallel attention over the ``seq`` mesh
    axis when present (falls back to single-chip blockwise attention
    otherwise), remat per block. The long-context capability rung
    (SURVEY.md §5.7 notes the reference has none; here it is first-class).

    ``cp_impl``: ``"ring"`` (ppermute kv rotation) or ``"ulysses"``
    (all-to-all head scatter — needs heads divisible by the seq-axis size).
    ``size_overrides`` (num_layers, num_heads, ...) scale the encoder —
    the CI-sized registry entry shares this eligibility logic."""
    if cp_impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown cp_impl {cp_impl!r}")
    cp = bool(mesh) and mesh.shape.get("seq", 1) > 1
    return BertEncoder(vocab_size=vocab_size, max_len=seq_len, dtype=dtype,
                       attn_impl=cp_impl if cp else "blockwise",
                       mesh=mesh if cp else None, remat=True,
                       fused_head=True,  # logits never materialise (lm_head)
                       **size_overrides)


def bert_tiny(dtype=jnp.float32, attn_impl: str = "auto",
              seq_len: int = 128, vocab_size: int = 1024) -> BertEncoder:
    """Test-sized BERT: 2 layers, 2 heads — CPU-CI fast."""
    return BertEncoder(vocab_size=vocab_size, max_len=seq_len, num_layers=2,
                       num_heads=2, head_dim=32, mlp_dim=128, dtype=dtype,
                       attn_impl=attn_impl)
