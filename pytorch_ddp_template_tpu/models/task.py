"""Task abstraction: model + loss + metrics, engine-agnostic.

The reference hardwires model construction (``ddp.py:311``), loss choice
(``MSELoss``, ``ddp.py:164,222``) and dataset (``ddp.py:135``) into the
train function. Here each entry of the model zoo supplies a :class:`Task`
— everything the training engine needs, as pure functions over pytrees, so
one jitted engine serves every model family (MLP, ResNet, BERT, ViT).
"""

from __future__ import annotations

from typing import Any, Mapping

import flax.linen as nn
import jax
import jax.numpy as jnp

Variables = Mapping[str, Any]
Batch = Mapping[str, jax.Array]


class Task:
    """A trainable task: Flax module + loss/metrics semantics.

    ``extra_vars`` carries non-parameter variable collections (e.g.
    ``batch_stats`` for BatchNorm); tasks without them use an empty dict,
    and the engine threads them through scan/jit either way.
    """

    def __init__(self, model: nn.Module):
        self.model = model

    # -- init ------------------------------------------------------------
    def init(self, rng: jax.Array, batch: Batch) -> tuple[Any, Any]:
        """Return ``(params, extra_vars)`` for an example batch.

        Scan-over-layers models (``model.scan_layers``) initialise through
        their *unrolled* twin and restack the per-layer subtrees onto the
        leading layer dim: every layer gets exactly the RNG stream the
        unrolled model would give it, so ``--scan_layers`` at seed S starts
        from bit-identical weights to the unrolled run at seed S (pinned by
        tests/test_scan_layers.py). ``nn.scan``'s own split-rng init would
        be statistically equivalent but not interchangeable.
        """
        model = self.model
        if getattr(model, "scan_layers", False):
            model = model.clone(scan_layers=False)
        variables = model.init(rng, *self.model_inputs(batch), train=False)
        if model is not self.model:
            from ..parallel.stacking import restack_layer_trees

            variables = restack_layer_trees(variables)
        params = variables.get("params", {})
        extra = {k: v for k, v in variables.items() if k != "params"}
        return params, extra

    # -- interface for subclasses ----------------------------------------
    def model_inputs(self, batch: Batch) -> tuple[jax.Array, ...]:
        raise NotImplementedError

    def loss(
        self,
        params: Any,
        extra_vars: Any,
        batch: Batch,
        rng: jax.Array,
        *,
        train: bool = True,
    ) -> tuple[jax.Array, Any, dict[str, jax.Array]]:
        """Return ``(scalar_loss, new_extra_vars, metrics)``."""
        raise NotImplementedError

    # -- shared helpers ---------------------------------------------------
    #: vocab tile width for fused_head LM models (ops/lm_head.py)
    head_block = 8192

    def blockwise_head(self, hidden, table, targets, bias=None, mesh=None):
        """``(token_logp, hits)`` via the blockwise LM head — the shared
        fused-head path of the LM tasks (gpt/bert). ``table``/``bias`` may
        arrive boxed (``nn.Partitioned``) straight from init.

        ``mesh`` (the ``--tp_overlap`` path) routes through the ring-
        decomposed TP head instead: the ``model``-sharded vocab table
        stays put and (hidden-chunk, online-stats) bundles rotate past it
        (``ops/lm_head.tp_lm_head_loss``) — same never-materialised
        (B, T, V) contract, gather/psum overlapped with the logit dots."""
        from ..ops.lm_head import lm_head_loss, tp_lm_head_loss

        table = nn.meta.unbox(table)
        bias = None if bias is None else nn.meta.unbox(bias)
        if mesh is not None:
            token_logp, pred = tp_lm_head_loss(hidden, table, targets, mesh,
                                               bias=bias,
                                               block=self.head_block)
        else:
            token_logp, pred = lm_head_loss(hidden, table, targets,
                                            bias=bias,
                                            block=self.head_block)
        return token_logp, (pred == targets).astype(jnp.float32)

    @staticmethod
    def example_weights(batch: Batch, n: int) -> jax.Array:
        """Per-example weights for exactly-once eval.

        ``ShardedLoader(with_validity=True)`` attaches ``__weight__`` — 1.0
        for real examples, 0.0 for SPMD shape padding (shard wrap-around and
        ragged-tail fill; the reference's eval is a stub, ``ddp.py:123-124``,
        and its DistributedSampler double-counts the wrap-around). Absent
        (the train path), every example weighs 1.0, and the weighted forms
        below reduce to plain means.
        """
        w = batch.get("__weight__")
        if w is None:
            return jnp.ones((n,), jnp.float32)
        return w.astype(jnp.float32)

    @staticmethod
    def weighted_metrics(wsum: jax.Array, train: bool,
                         **sums: jax.Array) -> dict[str, jax.Array]:
        """Turn weighted metric *sums* into means, attaching the eval
        denominator. This is the single home of the ``__denom__`` contract
        with ``Trainer.evaluate``: each metric is ``sum / max(wsum, 1)``,
        and in eval mode the unclamped ``wsum`` rides along so the trainer
        can aggregate ``sum(metric*denom)/sum(denom)`` exactly."""
        denom = jnp.maximum(wsum, 1.0)
        metrics = {k: v / denom for k, v in sums.items()}
        if not train:
            metrics["__denom__"] = wsum
        return metrics

    #: weight of sown auxiliary losses (e.g. the MoE load-balance term —
    #: Switch Transformer's standard 1e-2)
    aux_loss_weight = 0.01

    def _apply(self, params, extra_vars, batch, rng, train):
        return self._apply_inputs(params, extra_vars, self.model_inputs(batch),
                                  rng, train)

    def _apply_inputs(self, params, extra_vars, inputs, rng, train):
        """Run the model; returns ``(preds, new_extra, aux)``.

        ``aux`` sums the "losses" collection (modules sow auxiliary
        objectives there, e.g. ``MoeMlpBlock``'s load-balance term) or is
        ``None`` when nothing was sown. Harvesting here means EVERY task
        supports aux-carrying models — a task that forgot would otherwise
        silently train MoE routing with no balance term.
        """
        variables = {"params": params, **extra_vars}
        # train mode always offers the "losses" collection for sowing;
        # whether anything landed is statically known from the result
        mutable = (list(extra_vars) + ["losses"]) if train else False
        kwargs: dict[str, Any] = {"train": train}
        if train and rng is not None:
            kwargs["rngs"] = {"dropout": rng}
        out = self.model.apply(variables, *inputs, mutable=mutable, **kwargs)
        if mutable is False:
            return out, extra_vars, None
        preds, mutated = out
        mutated = dict(mutated)
        leaves = jax.tree.leaves(mutated.pop("losses", {}))
        # per-leaf sum: a scanned block stack sows one (num_layers,) array
        # where the unrolled loop sows num_layers scalars — both must
        # reduce to the same scalar aux
        aux = (sum((jnp.sum(l) for l in leaves), jnp.zeros((), jnp.float32))
               if leaves else None)
        return preds, {**extra_vars, **mutated}, aux

    def _with_aux(self, metrics: dict, aux):
        """Total objective = data loss + weighted aux. ``metrics['loss']``
        stays the pure data loss (comparable with eval curves); the
        regulariser is logged separately as ``aux_loss``."""
        if aux is None:
            return metrics["loss"], metrics
        metrics["aux_loss"] = aux
        return metrics["loss"] + self.aux_loss_weight * aux, metrics


class RegressionTask(Task):
    """MSE regression (reference: ``MSELoss`` ``ddp.py:164,222``) over
    ``batch = {"x": ..., "y": ...}``."""

    def model_inputs(self, batch):
        return (batch["x"],)

    def loss(self, params, extra_vars, batch, rng, *, train=True):
        preds, new_extra, aux = self._apply(params, extra_vars, batch, rng,
                                            train)
        err = jnp.square(preds.astype(jnp.float32) - batch["y"])
        per_example = err.reshape(err.shape[0], -1).mean(axis=1)
        w = self.example_weights(batch, per_example.shape[0])
        metrics = self.weighted_metrics(w.sum(), train,
                                        loss=(per_example * w).sum())
        total, metrics = self._with_aux(metrics, aux)
        return total, new_extra, metrics


class ClassificationTask(Task):
    """Softmax cross-entropy + accuracy over
    ``batch = {"image": uint8 NHWC, "label": int}``. Normalisation to
    [-1, 1] happens on device (uint8 over the wire: 4x less host→device
    bandwidth than f32 — HBM/PCIe economy the reference never needed).

    ``augment`` runs *on device inside the jitted step* (host CPU feeding
    is the classic TPU input bottleneck, SURVEY.md §7 hard part (e); a
    torch pipeline would burn host cores on per-sample transforms):
    ``"crop-flip"`` = pad-4 random crop + horizontal flip (the standard
    CIFAR recipe), ``"flip"`` = horizontal flip only (ImageNet-style when
    stored images are pre-sized). Applied only when ``train=True``.
    """

    def __init__(self, model: nn.Module, augment: str = "none"):
        super().__init__(model)
        if augment not in ("none", "flip", "crop-flip"):
            raise ValueError(f"unknown augment mode {augment!r}")
        self.augment = augment

    def model_inputs(self, batch):
        img = batch["image"].astype(jnp.float32) / 127.5 - 1.0
        return (img,)

    def _augment(self, img: jax.Array, rng: jax.Array) -> jax.Array:
        b, h, w, c = img.shape
        flip_rng, crop_rng = jax.random.split(rng)
        flip = jax.random.bernoulli(flip_rng, 0.5, (b,))
        img = jnp.where(flip[:, None, None, None], img[:, :, ::-1, :], img)
        if self.augment == "crop-flip":
            pad = 4
            # images here are already normalised to [-1, 1]; the standard
            # recipe (torchvision RandomCrop) pads the RAW image with 0 =
            # black, which is -1.0 post-normalisation — not 0.0 (mid-gray)
            padded = jnp.pad(img, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                             constant_values=-1.0)
            offs = jax.random.randint(crop_rng, (b, 2), 0, 2 * pad + 1)
            # per-sample window: vmap(dynamic_slice) lowers to one gather
            img = jax.vmap(
                lambda im, o: jax.lax.dynamic_slice(im, (o[0], o[1], 0),
                                                    (h, w, c))
            )(padded, offs)
        return img

    def loss(self, params, extra_vars, batch, rng, *, train=True):
        (img,) = self.model_inputs(batch)
        if train and self.augment != "none" and rng is not None:
            aug_rng, rng = jax.random.split(rng)
            img = self._augment(img, aug_rng)
        logits, new_extra, aux = self._apply_inputs(
            params, extra_vars, (img,), rng, train
        )
        logits = logits.astype(jnp.float32)
        labels = batch["label"]
        ce = -jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), labels]
        correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        w = self.example_weights(batch, logits.shape[0])
        metrics = self.weighted_metrics(w.sum(), train,
                                        loss=(ce * w).sum(),
                                        accuracy=(correct * w).sum())
        total, metrics = self._with_aux(metrics, aux)
        return total, new_extra, metrics
