"""Model zoo: Flax modules + Task wrappers (see registry)."""

from .mlp import MLP
from .registry import available_models, build, register
from .task import ClassificationTask, RegressionTask, Task

__all__ = [
    "MLP",
    "Task",
    "RegressionTask",
    "ClassificationTask",
    "available_models",
    "build",
    "register",
]
