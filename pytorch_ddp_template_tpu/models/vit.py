"""ViT-B/16 — the final rung of the BASELINE.md config ladder.

No counterpart in the reference (zoo = one MLP,
``/root/reference/model.py:8-16``); BASELINE.md rung 5 is "ViT-B/16 /
ImageNet, bf16 + grad accumulation". TPU-first choices:

- Patchify as a single strided Conv (16x16/s16) — one big NHWC conv the
  MXU eats directly; tokens stay ``(B, 196+1, 768)``, all matmul-shaped.
- Pre-LN encoder from ``models/transformer.py`` (flash attention on TPU,
  bf16 compute / f32 norms under ``--bf16``).
- Classification token + learned position embeddings, mean-free head:
  take the class token, LayerNorm, Dense — logits in f32.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import Impl
from .transformer import TransformerEncoder, default_kernel_init


class VisionTransformer(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    mlp_dim: int = 3072
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0
    attn_impl: Impl = "auto"
    remat: bool = False
    # scan-over-layers (models/transformer.py): one compiled block over
    # (num_layers, ...)-stacked weights — O(1) compile time in depth
    scan_layers: bool = False
    # decomposed FSDP (--fsdp_overlap, parallel/overlap.py): prefetched
    # per-layer weight gathers + overlapped grad drain; needs scan_layers.
    # The mesh rides along only for the overlap modes (ViT has no
    # context-parallel attention to thread it for otherwise).
    fsdp_overlap: bool = False
    # compressed DDP (--ddp_overlap, parallel/compress.py): per-layer
    # grad reduce inside the backward scan, in grad_comm wire precision,
    # optional error-feedback residual; needs scan_layers
    ddp_overlap: bool = False
    grad_comm: str = "fp32"
    grad_error_feedback: bool = False
    # ring-decomposed TP collective matmuls (--tp_overlap). Note: ViT
    # token counts (patches + cls) are rarely divisible by a model-axis
    # size — the encoder's divisibility check refuses such geometries
    # with the exact numbers rather than an opaque shard_map error.
    tp_overlap: bool = False
    # low-precision compute (--quant_compute, ops/quant.py): the block
    # matmuls run as per-channel-scaled int8/fp8 dots from the fp32
    # masters; fused into the TP rings when tp_overlap is on
    quant_compute: str = "off"
    mesh: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        embed_dim = self.num_heads * self.head_dim
        b, h, w, c = x.shape
        x = x.astype(self.dtype)
        x = nn.Conv(
            embed_dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            dtype=self.dtype,
            kernel_init=nn.with_logical_partitioning(
                default_kernel_init, (None, None, None, "embed")
            ),
            name="patch_embed",
        )(x)
        x = x.reshape(b, -1, embed_dim)  # (B, tokens, E)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, embed_dim), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, embed_dim)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed", default_kernel_init, (1, x.shape[1], embed_dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)

        x = TransformerEncoder(
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            dropout_rate=self.dropout_rate,
            pre_norm=True,
            attn_impl=self.attn_impl,
            mesh=self.mesh,
            remat=self.remat,
            scan_layers=self.scan_layers,
            fsdp_overlap=self.fsdp_overlap,
            ddp_overlap=self.ddp_overlap,
            grad_comm=self.grad_comm,
            grad_error_feedback=self.grad_error_feedback,
            tp_overlap=self.tp_overlap,
            quant_compute=self.quant_compute,
            name="encoder",
        )(x, train=train)

        x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x[:, 0])
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def vit_b16(num_classes=1000, dtype=jnp.float32, attn_impl: Impl = "auto",
            remat: bool = False, **kw) -> VisionTransformer:
    return VisionTransformer(num_classes=num_classes, dtype=dtype,
                             attn_impl=attn_impl, remat=remat, **kw)


def vit_tiny(num_classes=10, dtype=jnp.float32, attn_impl: Impl = "auto",
             **kw) -> VisionTransformer:
    """Test-sized ViT: 32px/8px patches, 2 layers — CPU-CI fast."""
    return VisionTransformer(num_classes=num_classes, patch_size=8,
                             num_layers=2, num_heads=2, head_dim=32,
                             mlp_dim=128, dtype=dtype, attn_impl=attn_impl,
                             **kw)
