"""Pipeline-parallel causal LM: the user-launchable PP path.

The reference has no pipeline parallelism (SURVEY.md §2b: "PP: No");
round 4 added the GPipe mechanism and this entry, and round 16 replaced
the plain fill/drain schedule with the real menu (``--pipe_schedule``):

- ``gpipe`` — the round-4 masked fill/drain loop, backward by AD
  through the schedule (kept as the parity/bench baseline; O(M)
  activation residency — AD saves every tick's residuals);
- ``1f1b`` (default) — one-forward-one-backward interleaving
  (Narayanan et al., SC'21) through the fused slot loop in
  ``parallel/pipeline.py``: the per-microbatch tail (final LN + tied
  head + loss) runs on the LAST stage inside the schedule so backward
  drains while later microbatches still fill, and each stage
  recomputes its block from the saved boundary activation — O(P)
  activation residency;
- ``zb`` — zero-bubble (Qi et al., ICLR'24, ZB-H1-flavoured): backward
  splits into the critical-path dx pass and deferred dw products
  computed from stashed (input-activation, output-grad) taps at every
  linear site — every dw unit drains as ONE batched post-loop wave,
  the drain region doing the work the bubble used to waste.

Design: the task (not a monolithic flax module) owns the pipeline
composition —

- embedding / final LayerNorm / tied head are tiny and replicated (the
  standard PP layout keeps them off the pipeline); under 1f1b/zb the
  final-LN+head *tail* is additionally applied per microbatch on the
  last stage inside the schedule (same math, microbatch-summed);
- the block stack is initialised per layer from the shared
  :class:`~.transformer.EncoderBlock`, stacked ``(P, layers_per_stage,
  ...)`` and annotated with the ``pipe_stage`` logical axis, so
  ``parallel.sharding.shard_tree`` places each stage's weights on its
  pipeline rank (a real memory split, like FSDP does over ``data``);
- each stage runs its layers as a *stage-local scan* under
  ``--scan_layers`` (one compiled block body over the
  ``(layers_per_stage, ...)`` stack) or as an unrolled loop otherwise —
  the checkpoint layout is identical either way;
- the zb tap kernel is a hand-rolled twin of the block forward built
  from the SAME primitives flax lowers to (``_plain_dense``,
  ``ops.attention.attention``, ``nn.LayerNorm.apply``) — bit-identical
  outputs, pinned by test — so the deferred dw products are pure
  einsums over the taps with no second recompute.

Since round 22 the 1f1b schedule composes with ONE in-stage
decomposition (``--tp_overlap`` / ``--ddp_overlap`` /
``--fsdp_overlap``) through the boundary-hoisted collective waves in
``parallel/pipeline.py``. The pipe×tp stage kernel here is the phased
Megatron layout (column-parallel qkv/fc1, row-parallel out/fc2,
replicated activations, two model all-reduces per layer) with every
cross-model sum routed through the driver's injected ``psum`` so it
issues at the slot body's top level, and every local vjp segment
routed through the injected ``guard``. The blocks' init metadata
carries the same ``_BLOCK_LOGICAL_AXES`` placement the non-pipe
decomposed schedules use, so the stage weights genuinely shard over
``model`` (and the names resolve to nothing on model-free meshes).
What still refuses is named in ``models/registry.py``.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from ..parallel.pipeline import (
    PIPE_SCHEDULES,
    PipeStageKernel,
    build_pipe_table,
    pipeline_apply,
    pipelined_loss,
    schedule_bubble_fraction,
)
from ..runtime.context import DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from ..utils import get_logger
from .gpt import CausalLmTask
from .transformer import EncoderBlock, _plain_dense, default_kernel_init

log = get_logger(__name__)

#: logical axis name for the stacked stage dim (parallel/sharding.py maps
#: it onto the ``pipe`` mesh axis)
PIPE_STAGE_AXIS = "pipe_stage"


@functools.lru_cache(maxsize=64)
def _cached_table(kind: str, n_micro: int, n_stages: int):
    return build_pipe_table(kind, n_micro, n_stages)


class PipelinedGptTask(CausalLmTask):
    """Causal-LM task whose block stack executes as a pipeline.

    Inherits the next-token loss/metrics of :class:`CausalLmTask`; only
    ``init``, the forward (``_apply_inputs``) and — under 1f1b/zb — the
    training ``loss`` are pipeline-aware.
    """

    def __init__(self, mesh: jax.sharding.Mesh, *, vocab_size: int,
                 seq_len: int, num_layers: int, num_heads: int,
                 head_dim: int, mlp_dim: int,
                 dtype: jnp.dtype = jnp.float32, n_micro: int = 4,
                 pipe_schedule: str = "1f1b", scan_layers: bool = False,
                 tp_overlap: bool = False, ddp_overlap: bool = False,
                 fsdp_overlap: bool = False, grad_comm: str = "fp32"):
        # no monolithic flax module: registry knob guards (--remat /
        # --fused_head) see model=None and refuse with intent
        self.model = None
        self.mesh = mesh
        if pipe_schedule not in PIPE_SCHEDULES:
            raise ValueError(
                f"unknown --pipe_schedule {pipe_schedule!r}; expected one "
                f"of {PIPE_SCHEDULES}")
        self.pipe_schedule = pipe_schedule
        self.scan_layers = scan_layers
        on = [n for n, v in (("tp", tp_overlap), ("ddp", ddp_overlap),
                             ("fsdp", fsdp_overlap)) if v]
        if len(on) > 1:
            raise ValueError(
                "the pipelined entries compose pipe with exactly ONE of "
                f"tp/ddp/fsdp per run, got {'+'.join(on)} — the slot "
                "boundary carries one uniform collective wave")
        self.compose = on[0] if on else "none"
        self.grad_comm = grad_comm
        if self.compose != "none" and pipe_schedule != "1f1b":
            raise ValueError(
                f"pipe×{self.compose} rides the 1f1b slot loop only "
                f"(got --pipe_schedule {pipe_schedule!r}); see "
                "parallel.pipeline.pipelined_loss")
        # Validation is DEFERRED to first use (init/forward): dataset-only
        # consumers of the registry (tools/make_file_dataset.py,
        # input_bench) build the entry under the default mesh and never
        # run the pipeline — they must not be refused. The single check
        # lives in _require_pipeline; CLI users still fail fast, at
        # Trainer.init_state.
        n = mesh.shape.get(PIPE_AXIS, 1)
        self.n_stages = n if n >= 2 else None
        if self.n_stages is not None:
            if num_layers % self.n_stages:
                raise ValueError(
                    f"num_layers {num_layers} not divisible by pipe axis "
                    f"size {self.n_stages}"
                )
            self.layers_per_stage = num_layers // self.n_stages
            if self.compose != "none":
                # the compose modes have a real mesh contract (model
                # axis for tp, data axis for ddp/fsdp) — check it where
                # the pipeline itself becomes live, same deferred spot
                # as the stage-count check above
                from ..parallel.schedule import validate_schedule_mesh

                validate_schedule_mesh(
                    mesh, pipe=True, tp=tp_overlap, ddp=ddp_overlap,
                    fsdp=fsdp_overlap)
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.mlp_dim = mlp_dim
        self.dtype = dtype
        self.n_micro = n_micro
        self._clamp_warned = False
        # dropout 0: the pipelined forward is RNG-free, so stage_fn needs
        # no per-stage rng plumbing through the ppermute schedule
        self._block = EncoderBlock(
            num_heads=num_heads, head_dim=head_dim, mlp_dim=mlp_dim,
            dtype=dtype, dropout_rate=0.0, pre_norm=True, attn_impl="auto",
            mesh=None, causal=True,
        )
        self._ln = nn.LayerNorm(dtype=jnp.float32)

    def _require_pipeline(self) -> None:
        if self.n_stages is None:
            raise ValueError(
                "this model runs its block stack as a pipeline and needs a "
                "pipe axis of size >= 2 in --mesh (e.g. --mesh data:4,pipe:2 "
                "on 8 devices)"
            )

    # -- microbatch accounting --------------------------------------------
    def effective_microbatches(self, batch_size: int) -> int:
        """The microbatch count a batch of ``batch_size`` examples will
        actually pipeline with: ``gcd(--pipe_microbatches, per-replica
        batch)`` — the clamp that keeps every microbatch SPMD-uniform."""
        from ..parallel.pipeline import effective_pipe_microbatches

        per_replica = batch_size // self.mesh.shape.get(DATA_AXIS, 1)
        return effective_pipe_microbatches(self.n_micro, per_replica)

    def bubble_fraction(self, batch_size: int) -> float:
        """Static schedule-model bubble fraction at this geometry."""
        if self.n_stages is None:
            return 0.0
        return schedule_bubble_fraction(
            self.pipe_schedule, self.effective_microbatches(batch_size),
            self.n_stages)

    def model_wire_bytes_per_step(self, batch_size: int) -> int:
        """Static model-axis wire figure for the r22 pipe×tp compose
        wave (zero for every other compose mode): the attribution
        engine uses it to split the all-reduce census between the data
        grad reduce and the TP psums on pipe×tp meshes
        (obs/attribution.py::static_cost_model)."""
        if self.compose != "tp" or self.n_stages is None:
            return 0
        from ..parallel.schedule import PipelineSchedule

        model = self.mesh.shape.get(MODEL_AXIS, 1)
        data = self.mesh.shape.get(DATA_AXIS, 1)
        m = self.effective_microbatches(batch_size)
        mb = max((batch_size // max(data, 1)) // max(m, 1), 1)
        sched = PipelineSchedule(self.mesh, self.pipe_schedule, m,
                                 tp=True)
        return sched.tp_wave_bytes_per_step(
            mb, self.seq_len, self.embed_dim, self.layers_per_stage,
            model, itemsize=jnp.dtype(self.dtype).itemsize)

    def _microbatch_count(self, b: int) -> int:
        """Effective count for a concrete batch, with the clamp policy:
        a clamp to 1 microbatch on a real pipeline is a REFUSAL (the
        schedule fully serialises — bubble fraction (P-1)/P, every
        schedule identical), a clamp to fewer-than-requested warns
        once. Delegates the gcd itself to
        :meth:`effective_microbatches` — ONE copy of the clamp
        formula (a batch smaller than the data axis clamps to 1 there
        and lands in the refusal below, not in an opaque reshape)."""
        data = self.mesh.shape.get(DATA_AXIS, 1)
        per_replica = b // data
        m = self.effective_microbatches(b)
        if m == 1 and self.n_stages is not None and self.n_stages > 1:
            raise ValueError(
                f"pipeline would serialise: gcd(--pipe_microbatches="
                f"{self.n_micro}, per-replica batch={per_replica}) == 1, "
                f"so every schedule degenerates to one microbatch with "
                f"bubble fraction (P-1)/P = "
                f"{(self.n_stages - 1) / self.n_stages:.2f}. Fix: make "
                f"the per-replica batch (global batch {b} / data axis "
                f"{data}) share a factor >= 2 with --pipe_microbatches — "
                f"e.g. raise --per_device_train_batch_size or set "
                f"--pipe_microbatches to a divisor of {per_replica}"
            )
        if m < self.n_micro and not self._clamp_warned:
            # a partially-coprime batch/microbatch combination still
            # shrinks the overlap — say so once, at trace time, instead
            # of letting the fill/drain bubble grow invisibly
            self._clamp_warned = True
            log.warning(
                "--pipe_microbatches clamped: gcd(n_micro, per-replica "
                "batch) < requested — the pipeline bubble grows; pick a "
                "per-replica batch divisible by the microbatch count",
                {"requested": self.n_micro, "effective": m,
                 "per_replica_batch": per_replica},
            )
        return m

    # -- init -------------------------------------------------------------
    def init(self, rng, batch):
        self._require_pipeline()
        ids = batch["input_ids"]
        t = ids.shape[-1]
        k_wte, k_wpe, k_ln, k_blocks = jax.random.split(rng, 4)
        dummy = jnp.zeros((1, t, self.embed_dim), self.dtype)
        layers = [
            nn.meta.unbox(self._block.init(
                jax.random.fold_in(k_blocks, i), dummy, None, train=False,
            )["params"])
            for i in range(self.num_layers)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
        from ..parallel.schedule import _BLOCK_LOGICAL_AXES, _path_keys

        def _stage_leaf(path, a):
            r = a.reshape(
                self.n_stages, self.layers_per_stage, *a.shape[1:])
            keys = _path_keys(path)
            axes = (_BLOCK_LOGICAL_AXES.get(keys[-2:])
                    if len(keys) >= 2 else None)
            if axes is None or len(axes) != r.ndim - 2:
                raise ValueError(
                    f"pipelined init: unknown block param at path "
                    f"{'/'.join(keys)} — extend _BLOCK_LOGICAL_AXES "
                    "(parallel/schedule.py) so its (pipe, model) "
                    "placement is known")
            # (stage, layer, *param) with the stage dim on 'pipe' and
            # the trailing dims on the SAME logical placement the
            # non-pipe decomposed schedules use — under a model-free
            # mesh the trailing names resolve to nothing (replicated),
            # so this is the old layout there
            return nn.Partitioned(
                r, names=(PIPE_STAGE_AXIS, None) + tuple(axes))

        staged = jax.tree_util.tree_map_with_path(_stage_leaf, stacked)
        params = {
            "wte": default_kernel_init(
                k_wte, (self.vocab_size, self.embed_dim), jnp.float32),
            "wpe": default_kernel_init(
                k_wpe, (self.seq_len, self.embed_dim), jnp.float32),
            "blocks": staged,
            "final_ln": nn.meta.unbox(
                self._ln.init(k_ln, jnp.zeros((1, t, self.embed_dim)))
                ["params"]),
        }
        return params, {}

    # -- stage kernels -----------------------------------------------------
    def _stage_fwd(self, stage_params, h):
        """One pipeline stage = its layers applied in sequence: a
        stage-local ``lax.scan`` over the ``(layers_per_stage, ...)``
        stack under ``--scan_layers`` (one compiled block body), an
        unrolled loop otherwise. Same math, same checkpoint layout."""
        block = self._block
        if self.scan_layers:
            def body(carry, layer_params):
                return block.apply({"params": layer_params}, carry, None,
                                   train=False), None

            out, _ = lax.scan(body, h, stage_params)
            return out
        out = h
        for i in range(self.layers_per_stage):
            layer = jax.tree.map(lambda a, i=i: a[i], stage_params)
            out = block.apply({"params": layer}, out, None, train=False)
        return out

    def _block_fwd_tapped(self, lp, x, pr):
        """Tapped twin of ``EncoderBlock`` (pre-LN, causal, dropout 0):
        identical primitives in identical order (``_plain_dense`` IS
        DenseGeneral's contraction; ``ops.attention.attention`` is the
        same dispatch the block uses), plus zero-valued probes added at
        every linear-site output. The probes' vjp cotangents are the
        per-site output grads and the returned taps the per-site input
        activations — together the full input of the deferred dw
        products."""
        dt = self.dtype
        at = lp["attention"]
        h1f = self._ln.apply({"params": lp["ln_attn"]}, x) + pr["ln_attn"]
        h1 = h1f.astype(dt)
        q = _plain_dense(h1, at["query"]["kernel"], at["query"]["bias"],
                         1, dt) + pr["q"]
        k = _plain_dense(h1, at["key"]["kernel"], at["key"]["bias"],
                         1, dt) + pr["k"]
        v = _plain_dense(h1, at["value"]["kernel"], at["value"]["bias"],
                         1, dt) + pr["v"]
        ctx = attention(q, k, v, mask=None, causal=True,
                        impl=self._block.attn_impl)
        o = _plain_dense(ctx, at["out"]["kernel"], at["out"]["bias"],
                         2, dt) + pr["out"]
        x1 = x + o
        h2f = self._ln.apply({"params": lp["ln_mlp"]}, x1) + pr["ln_mlp"]
        h2 = h2f.astype(dt)
        f1 = _plain_dense(h2, lp["mlp"]["fc1"]["kernel"],
                          lp["mlp"]["fc1"]["bias"], 1, dt) + pr["fc1"]
        a1 = nn.gelu(f1)
        f2 = _plain_dense(a1, lp["mlp"]["fc2"]["kernel"],
                          lp["mlp"]["fc2"]["bias"], 1, dt) + pr["fc2"]
        y = x1 + f2
        taps = {"x": x, "h1": h1, "ctx": ctx, "x1": x1, "h2": h2, "a1": a1}
        return y, taps

    def _stage_fwd_tapped(self, stage_params, h, probes):
        """Stage forward with per-layer taps; probes/taps carry a
        leading ``(layers_per_stage, ...)`` axis (the scan's xs/ys)."""
        def body(carry, inputs):
            lp, pr = inputs
            y, taps = self._block_fwd_tapped(lp, carry, pr)
            return y, taps

        return lax.scan(body, h, (stage_params, probes))

    def _make_probes(self, stage_params, x_sds):
        """Zero probes for one microbatch: per layer, one per linear
        site (LN outputs in f32, dense outputs in the compute dtype)."""
        mb, t, e = x_sds.shape
        hk = (mb, t, self.num_heads, self.head_dim)
        dt = x_sds.dtype
        one = {
            "ln_attn": jnp.zeros((mb, t, e), jnp.float32),
            "q": jnp.zeros(hk, dt),
            "k": jnp.zeros(hk, dt),
            "v": jnp.zeros(hk, dt),
            "out": jnp.zeros((mb, t, e), dt),
            "ln_mlp": jnp.zeros((mb, t, e), jnp.float32),
            "fc1": jnp.zeros((mb, t, self.mlp_dim), dt),
            "fc2": jnp.zeros((mb, t, e), dt),
        }
        return jax.tree.map(
            lambda a: jnp.zeros((self.layers_per_stage, *a.shape),
                                a.dtype), one)

    def _dw_from_taps(self, stage_params, taps, g_probes):
        """The deferred weight-grad products: pure einsums over the
        stashed (input-activation, output-grad) pairs — exactly the
        terms the fused vjp would have computed, just later. Leaves
        carry leading ``(slots, layers_per_stage, ...)`` axes; the slot
        and example axes contract, the layer axis stays."""
        dt = self.dtype
        f32 = jnp.float32

        def dense_dw(x, g):  # (S, L, mb, T, in...) x (S, L, mb, T, out...)
            return jnp.einsum("slbti,slbto->lio", x.astype(dt),
                              g.astype(dt)).astype(f32)

        def bsum(g):
            return jnp.sum(g.astype(f32), axis=(0, 2, 3))

        t, g = taps, g_probes
        gq = jnp.einsum("slbte,slbthk->lehk", t["h1"].astype(dt),
                        g["q"].astype(dt)).astype(f32)
        gk = jnp.einsum("slbte,slbthk->lehk", t["h1"].astype(dt),
                        g["k"].astype(dt)).astype(f32)
        gv = jnp.einsum("slbte,slbthk->lehk", t["h1"].astype(dt),
                        g["v"].astype(dt)).astype(f32)
        gout = jnp.einsum("slbthk,slbte->lhke", t["ctx"].astype(dt),
                          g["out"].astype(dt)).astype(f32)

        def ln_grads(ln_params, x, gy):
            # exact LN param grads via a per-(slot, layer) vjp over the
            # SAME flax apply the forward used — elementwise-cheap
            def one(pp, xx, gg):
                _, pull = jax.vjp(
                    lambda p_: self._ln.apply({"params": p_}, xx), pp)
                (gp,) = pull(gg)
                return gp

            over_layers = jax.vmap(one, in_axes=(0, 0, 0))
            over_slots = jax.vmap(over_layers, in_axes=(None, 0, 0))
            gp = over_slots(ln_params, x, gy)  # (S, L, ...)
            return jax.tree.map(lambda a: jnp.sum(a, axis=0), gp)

        return {
            "attention": {
                "query": {"kernel": gq, "bias": bsum(g["q"])},
                "key": {"kernel": gk, "bias": bsum(g["k"])},
                "value": {"kernel": gv, "bias": bsum(g["v"])},
                "out": {"kernel": gout, "bias": bsum(g["out"])},
            },
            "mlp": {
                "fc1": {"kernel": dense_dw(t["h2"], g["fc1"]),
                        "bias": bsum(g["fc1"])},
                "fc2": {"kernel": dense_dw(t["a1"], g["fc2"]),
                        "bias": bsum(g["fc2"])},
            },
            "ln_attn": ln_grads(stage_params["ln_attn"], t["x"],
                                g["ln_attn"]),
            "ln_mlp": ln_grads(stage_params["ln_mlp"], t["x1"],
                               g["ln_mlp"]),
        }

    # -- tensor-parallel stage kernel (pipe×tp, r22) -----------------------
    #
    # Megatron phased layout over model-sharded stage weights with
    # replicated activations: qkv/fc1 column-parallel (no forward
    # collective — outputs local over heads/mlp), out/fc2 row-parallel
    # (forward psums the partial products; their biases are replicated
    # and added ONCE, after the psum). The backward never differentiates
    # through a collective: ``jax.vjp`` is applied to the purely-local
    # segments below, the cross-model sums of the activation cotangents
    # and the (partial) LN param grads are issued manually — one joint
    # psum per segment, between the guards, uniform across stages.

    def _tp_attn_seg_params(self, lp):
        at = lp["attention"]
        return {"ln_attn": lp["ln_attn"], "query": at["query"],
                "key": at["key"], "value": at["value"],
                "out_kernel": at["out"]["kernel"]}

    def _tp_mlp_seg_params(self, lp):
        return {"ln_mlp": lp["ln_mlp"], "fc1": lp["mlp"]["fc1"],
                "fc2_kernel": lp["mlp"]["fc2"]["kernel"]}

    def _tp_seg_attn(self, seg_p, x):
        """LN → column-parallel qkv → attention over local heads →
        row-parallel out contraction. Returns the model-PARTIAL out
        product (the caller psums it); purely local — safe to vjp."""
        dt = self.dtype
        h1 = self._ln.apply({"params": seg_p["ln_attn"]}, x).astype(dt)
        q = _plain_dense(h1, seg_p["query"]["kernel"],
                         seg_p["query"]["bias"], 1, dt)
        k = _plain_dense(h1, seg_p["key"]["kernel"],
                         seg_p["key"]["bias"], 1, dt)
        v = _plain_dense(h1, seg_p["value"]["kernel"],
                         seg_p["value"]["bias"], 1, dt)
        ctx = attention(q, k, v, mask=None, causal=True,
                        impl=self._block.attn_impl)
        axes = (ctx.ndim - 2, ctx.ndim - 1)
        return lax.dot_general(
            ctx.astype(dt), seg_p["out_kernel"].astype(dt),
            ((axes, (0, 1)), ((), ())))

    def _tp_seg_mlp(self, seg_p, x1):
        """LN → column-parallel fc1 → gelu → row-parallel fc2
        contraction; returns the model-PARTIAL fc2 product."""
        dt = self.dtype
        h2 = self._ln.apply({"params": seg_p["ln_mlp"]}, x1).astype(dt)
        f1 = _plain_dense(h2, seg_p["fc1"]["kernel"],
                          seg_p["fc1"]["bias"], 1, dt)
        a1 = nn.gelu(f1)
        return lax.dot_general(
            a1, seg_p["fc2_kernel"].astype(dt),
            (((a1.ndim - 1,), (0,)), ((), ())))

    def _tp_stage_fwd(self, stage_w, x, psum):
        """Phased stage forward: two ``psum`` calls per layer (out and
        fc2 partials), issued by the driver at the slot body's top
        level. Taps are the per-layer ``(x, x1)`` residual-stream
        points the backward sweep's segment vjps restart from."""
        dt = self.dtype
        h = x
        taps = []
        for li in range(self.layers_per_stage):
            lp = jax.tree.map(lambda a, li=li: a[li], stage_w)
            o = (psum(self._tp_seg_attn(self._tp_attn_seg_params(lp), h))
                 + lp["attention"]["out"]["bias"].astype(dt))
            x1 = h + o
            f2 = (psum(self._tp_seg_mlp(self._tp_mlp_seg_params(lp), x1))
                  + lp["mlp"]["fc2"]["bias"].astype(dt))
            taps.append((h, x1))
            h = x1 + f2
        return h, tuple(taps)

    @staticmethod
    def _tp_seg_vjp(seg, seg_p, x, g):
        """vjp of one purely-local segment: (param grads, input
        cotangent). The param grads of the column/row kernels and the
        qkv/fc1 biases are local-COMPLETE (replicated activations ×
        local cotangents); the LN grads inside ``seg_p`` come out
        model-PARTIAL (their cotangent flows through the local-heads
        sum) — the caller psums them jointly with ``dx``."""
        _, pull = jax.vjp(seg, seg_p, x)
        dp, dx = pull(g)
        return dp, dx

    def _tp_stage_bwd(self, stage_w, taps, gy, psum, guard):
        """Phased stage backward, layers reversed. Per layer: the mlp
        and attn segments' local vjps run under ``guard`` (collective-
        free), and ONE joint psum per segment — (activation cotangent,
        LN param grads) — issues between them, uniform across stages
        (idle stages feed zeros). The replicated out/fc2 biases are
        excluded from the segments: their grads are plain sums of the
        (replicated, zero-when-idle) cotangents, no collective at all."""
        f32 = jnp.float32
        g = gy
        gw_layers = []
        for li in reversed(range(self.layers_per_stage)):
            lp = jax.tree.map(lambda a, li=li: a[li], stage_w)
            # the forward sweep of the SAME slot produced these for the
            # microbatch being backpropped (on B slots it is the
            # recompute-from-boundary) — no second recompute here
            x, x1 = taps[li]
            attn_p = self._tp_attn_seg_params(lp)
            mlp_p = self._tp_mlp_seg_params(lp)
            db_fc2 = jnp.sum(g.astype(f32), axis=(0, 1)).astype(
                lp["mlp"]["fc2"]["bias"].dtype)
            d_mlp, d_x1_part = guard(
                lambda: self._tp_seg_vjp(self._tp_seg_mlp, mlp_p, x1, g))
            d_x1_seg, d_ln_mlp = psum((d_x1_part, d_mlp["ln_mlp"]))
            d_x1 = g + d_x1_seg
            db_out = jnp.sum(d_x1.astype(f32), axis=(0, 1)).astype(
                lp["attention"]["out"]["bias"].dtype)
            d_attn, d_x_part = guard(
                lambda: self._tp_seg_vjp(
                    self._tp_seg_attn, attn_p, x, d_x1))
            d_x_seg, d_ln_attn = psum((d_x_part, d_attn["ln_attn"]))
            g = d_x1 + d_x_seg
            gw_layers.append({
                "attention": {
                    "query": d_attn["query"], "key": d_attn["key"],
                    "value": d_attn["value"],
                    "out": {"kernel": d_attn["out_kernel"],
                            "bias": db_out},
                },
                "mlp": {
                    "fc1": d_mlp["fc1"],
                    "fc2": {"kernel": d_mlp["fc2_kernel"],
                            "bias": db_fc2},
                },
                "ln_attn": d_ln_attn,
                "ln_mlp": d_ln_mlp,
            })
        gw_layers.reverse()
        gw = jax.tree.map(lambda *xs: jnp.stack(xs), *gw_layers)
        return g, gw

    # -- tail (last stage, per microbatch) ---------------------------------
    def _tail_terms(self, tail_p, y, ids_mb, wt_mb):
        """Per-microbatch final-LN + tied head + next-token loss sums —
        the same math ``CausalLmTask.loss`` applies to the whole batch,
        restricted to one microbatch (sums, not means: the caller's
        ``weighted_metrics`` supplies the shared denominator)."""
        h = self._ln.apply({"params": tail_p["final_ln"]},
                           y.astype(jnp.float32))
        logits = (h.astype(self.dtype)
                  @ tail_p["wte"].T.astype(self.dtype)).astype(jnp.float32)
        targets = ids_mb[:, 1:].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        token_logp = jnp.take_along_axis(
            logp, targets[..., None], axis=-1)[..., 0]
        hits = (jnp.argmax(logits[:, :-1], -1) == targets
                ).astype(jnp.float32)
        w = wt_mb[:, None]
        return -(token_logp * w).sum(), (hits * w).sum()

    def _tail_fwd(self, tail_p, y, ids_mb, wt_mb):
        return self._tail_terms(tail_p, y, ids_mb, wt_mb)

    def _tail_bwd(self, tail_p, y, ids_mb, wt_mb):
        (loss, hits), pull = jax.vjp(
            lambda tp, y_: self._tail_terms(tp, y_, ids_mb, wt_mb),
            tail_p, y)
        d_tail, gy = pull((jnp.ones((), jnp.float32),
                           jnp.zeros((), jnp.float32)))
        return gy.astype(self.dtype), loss, hits, d_tail

    def _kernel(self) -> PipeStageKernel:
        return PipeStageKernel(
            fwd=self._stage_fwd,
            tail_fwd=self._tail_fwd,
            tail_bwd=self._tail_bwd,
            fwd_tapped=self._stage_fwd_tapped,
            make_probes=self._make_probes,
            dw_from_taps=self._dw_from_taps,
            tp_fwd=self._tp_stage_fwd,
            tp_bwd=self._tp_stage_bwd,
        )

    # -- forward (gpipe / eval) -------------------------------------------
    def _embed(self, params, ids):
        wte = nn.meta.unbox(params["wte"])
        wpe = nn.meta.unbox(params["wpe"])
        t = ids.shape[-1]
        return (wte[ids] + wpe[:t][None]).astype(self.dtype)

    def _apply_inputs(self, params, extra_vars, inputs, rng, train):
        self._require_pipeline()
        (ids,) = inputs
        b, t = ids.shape
        x = self._embed(params, ids)
        m = self._microbatch_count(b)
        xm = x.reshape(m, b // m, t, self.embed_dim)
        blocks = nn.meta.unbox(params["blocks"])
        out = pipeline_apply(blocks, self._stage_fwd, xm, self.mesh)
        out = out.reshape(b, t, self.embed_dim)
        h = self._ln.apply(
            {"params": nn.meta.unbox(params["final_ln"])},
            out.astype(jnp.float32))
        wte = nn.meta.unbox(params["wte"])
        logits = (h.astype(self.dtype) @ wte.T.astype(self.dtype))
        return logits.astype(jnp.float32), extra_vars, None

    # -- loss --------------------------------------------------------------
    def loss(self, params, extra_vars, batch, rng, *, train=True):
        if self.pipe_schedule == "gpipe" or not train:
            # gpipe: AD through the masked fill/drain loop (the r4
            # baseline). Eval: the F-only loop + whole-batch tail —
            # same per-example terms, no backward schedule to fuse.
            return super().loss(params, extra_vars, batch, rng,
                                train=train)
        self._require_pipeline()
        ids = batch["input_ids"]
        b, t = ids.shape
        m = self._microbatch_count(b)
        x = self._embed(params, ids)
        xm = x.reshape(m, b // m, t, self.embed_dim)
        ids_m = jnp.asarray(ids).reshape(m, b // m, t)
        w = self.example_weights(batch, b)
        wt_m = w.reshape(m, b // m)
        table = _cached_table(self.pipe_schedule, m, self.n_stages)
        tail_p = {
            "final_ln": nn.meta.unbox(params["final_ln"]),
            "wte": nn.meta.unbox(params["wte"]),
        }
        blocks = nn.meta.unbox(params["blocks"])
        extra = {}
        if self.compose == "tp":
            from ..parallel.schedule import staged_tp_specs

            extra = dict(compose="tp",
                         stage_specs=staged_tp_specs(blocks, self.mesh))
        elif self.compose == "ddp":
            extra = dict(compose="ddp", grad_comm=self.grad_comm)
            if self.grad_comm != "fp32":
                if rng is None:
                    raise ValueError(
                        "lossy --grad_comm under pipe×ddp needs the "
                        "training rng (per-slot stochastic rounding)")
                extra["comm_rng"] = jax.random.fold_in(rng, 0x9e22)
        elif self.compose == "fsdp":
            extra = dict(compose="fsdp")
        loss_sum, hits_sum = pipelined_loss(
            table, self._kernel(), blocks,
            tail_p, xm, ids_m, wt_m, self.mesh, **extra)
        metrics = self.weighted_metrics(
            w.sum() * (t - 1), train,
            loss=loss_sum, next_token_accuracy=hits_sum)
        return metrics["loss"], extra_vars, metrics
