"""Pipeline-parallel causal LM: the user-launchable PP path.

The reference has no pipeline parallelism (SURVEY.md §2b: "PP: No") and
round 4 left the GPipe mechanism library-only (``parallel/pipeline.py`` +
tests, nothing a user could launch — VERDICT.md round-4 weak #3). This
module closes that: ``--model gpt-pipe-tiny --mesh data:4,pipe:2`` trains
a decoder-only LM whose transformer block stack runs as a GPipe
fill/drain pipeline over the ``pipe`` mesh axis, through the ordinary
:class:`~..train.engine.Trainer`.

Design: the task (not a monolithic flax module) owns the pipeline
composition —

- embedding / final LayerNorm / tied head are tiny and replicated (the
  standard PP layout keeps them off the pipeline);
- the block stack is initialised per layer from the shared
  :class:`~.transformer.EncoderBlock`, stacked ``(P, layers_per_stage,
  ...)`` and annotated with the ``pipe_stage`` logical axis, so
  ``parallel.sharding.shard_tree`` places each stage's weights on its
  pipeline rank (a real memory split, like FSDP does over ``data``);
- the forward reshapes the batch into ``n_micro`` microbatches and runs
  ``parallel.pipeline.pipeline_apply`` (one SPMD program, activations
  hopping stage-to-stage over ``lax.ppermute``); AD through the schedule
  is exact (tests/test_pipeline.py), so the jitted train step needs no
  pipeline-specific backward.

Scope note: stages carry no intra-stage TP annotations (compose ``pipe``
with ``data``; use the non-pipe entries for TP/CP composition).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.pipeline import pipeline_apply
from ..runtime.context import PIPE_AXIS
from ..utils import get_logger
from .gpt import CausalLmTask
from .transformer import EncoderBlock, default_kernel_init

log = get_logger(__name__)

#: logical axis name for the stacked stage dim (parallel/sharding.py maps
#: it onto the ``pipe`` mesh axis)
PIPE_STAGE_AXIS = "pipe_stage"


class PipelinedGptTask(CausalLmTask):
    """Causal-LM task whose block stack executes as a GPipe pipeline.

    Inherits the next-token loss/metrics of :class:`CausalLmTask`; only
    ``init`` and the forward (``_apply_inputs``) are pipeline-aware.
    """

    def __init__(self, mesh: jax.sharding.Mesh, *, vocab_size: int,
                 seq_len: int, num_layers: int, num_heads: int,
                 head_dim: int, mlp_dim: int,
                 dtype: jnp.dtype = jnp.float32, n_micro: int = 4):
        # no monolithic flax module: registry knob guards (--remat /
        # --fused_head) see model=None and refuse with intent
        self.model = None
        self.mesh = mesh
        # Validation is DEFERRED to first use (init/forward): dataset-only
        # consumers of the registry (tools/make_file_dataset.py,
        # input_bench) build the entry under the default mesh and never
        # run the pipeline — they must not be refused. The single check
        # lives in _require_pipeline; CLI users still fail fast, at
        # Trainer.init_state.
        n = mesh.shape.get(PIPE_AXIS, 1)
        self.n_stages = n if n >= 2 else None
        if self.n_stages is not None:
            if num_layers % self.n_stages:
                raise ValueError(
                    f"num_layers {num_layers} not divisible by pipe axis "
                    f"size {self.n_stages}"
                )
            self.layers_per_stage = num_layers // self.n_stages
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.dtype = dtype
        self.n_micro = n_micro
        self._clamp_warned = False
        # dropout 0: the pipelined forward is RNG-free, so stage_fn needs
        # no per-stage rng plumbing through the ppermute schedule
        self._block = EncoderBlock(
            num_heads=num_heads, head_dim=head_dim, mlp_dim=mlp_dim,
            dtype=dtype, dropout_rate=0.0, pre_norm=True, attn_impl="auto",
            mesh=None, causal=True,
        )
        self._ln = nn.LayerNorm(dtype=jnp.float32)

    def _require_pipeline(self) -> None:
        if self.n_stages is None:
            raise ValueError(
                "this model runs its block stack as a pipeline and needs a "
                "pipe axis of size >= 2 in --mesh (e.g. --mesh data:4,pipe:2 "
                "on 8 devices)"
            )

    # -- init -------------------------------------------------------------
    def init(self, rng, batch):
        self._require_pipeline()
        ids = batch["input_ids"]
        t = ids.shape[-1]
        k_wte, k_wpe, k_ln, k_blocks = jax.random.split(rng, 4)
        dummy = jnp.zeros((1, t, self.embed_dim), self.dtype)
        layers = [
            nn.meta.unbox(self._block.init(
                jax.random.fold_in(k_blocks, i), dummy, None, train=False,
            )["params"])
            for i in range(self.num_layers)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
        staged = jax.tree.map(
            lambda a: nn.Partitioned(
                a.reshape(self.n_stages, self.layers_per_stage, *a.shape[1:]),
                names=(PIPE_STAGE_AXIS,) + (None,) * a.ndim,
            ),
            stacked,
        )
        params = {
            "wte": default_kernel_init(
                k_wte, (self.vocab_size, self.embed_dim), jnp.float32),
            "wpe": default_kernel_init(
                k_wpe, (self.seq_len, self.embed_dim), jnp.float32),
            "blocks": staged,
            "final_ln": nn.meta.unbox(
                self._ln.init(k_ln, jnp.zeros((1, t, self.embed_dim)))
                ["params"]),
        }
        return params, {}

    # -- forward ----------------------------------------------------------
    def _apply_inputs(self, params, extra_vars, inputs, rng, train):
        import math

        self._require_pipeline()
        (ids,) = inputs
        b, t = ids.shape
        wte = nn.meta.unbox(params["wte"])
        wpe = nn.meta.unbox(params["wpe"])
        x = (wte[ids] + wpe[:t][None]).astype(self.dtype)

        # microbatch count: at most n_micro, constrained so each data
        # replica's shard divides evenly (pipeline_apply shards the
        # microbatch dim over ``data`` — real pipe x data composition)
        from ..runtime.context import DATA_AXIS

        per_replica = b // self.mesh.shape.get(DATA_AXIS, 1)
        m = math.gcd(self.n_micro, per_replica)
        if m < self.n_micro and not self._clamp_warned:
            # a coprime batch/microbatch combination silently serialises
            # the pipeline (m=1 == no overlap at all) — say so once, at
            # trace time, instead of letting the fill/drain bubble eat the
            # speedup invisibly
            self._clamp_warned = True
            log.warning(
                "--pipe_microbatches clamped: gcd(n_micro, per-replica "
                "batch) < requested — the GPipe fill/drain bubble grows; "
                "pick a per-replica batch divisible by the microbatch count",
                {"requested": self.n_micro, "effective": m,
                 "per_replica_batch": per_replica},
            )
        xm = x.reshape(m, b // m, t, self.embed_dim)

        block = self._block

        def stage_fn(stage_params, h):
            # one pipeline stage = its layers applied in sequence
            def body(carry, layer_params):
                return block.apply({"params": layer_params}, carry, None,
                                   train=False), None

            out, _ = lax.scan(body, h, stage_params)
            return out

        blocks = nn.meta.unbox(params["blocks"])
        out = pipeline_apply(blocks, stage_fn, xm, self.mesh)
        out = out.reshape(b, t, self.embed_dim)
        h = self._ln.apply(
            {"params": nn.meta.unbox(params["final_ln"])},
            out.astype(jnp.float32))
        logits = (h.astype(self.dtype) @ wte.T.astype(self.dtype))
        return logits.astype(jnp.float32), extra_vars, None
