"""Shared transformer encoder: the backbone of the BERT and ViT rungs.

The reference has no transformer (its zoo is a 2-layer MLP,
``/root/reference/model.py:8-16``); BASELINE.md's config ladder adds
BERT-base MLM and ViT-B/16, which share this encoder. TPU-first choices:

- Attention routes through ``ops.attention`` (Pallas flash kernel on TPU,
  XLA elsewhere); heads/head_dim sized to MXU lanes (head_dim 64/128).
- Compute dtype configurable (bf16 under ``--bf16``); LayerNorm and
  softmax statistics stay f32.
- Weights are stored with *logical axis names* via
  ``nn.with_logical_partitioning`` — ``parallel/sharding.py`` maps the
  logical names (``embed``, ``mlp``, ``heads``, ``kv``) onto mesh axes,
  which is how tensor parallelism turns on without touching model code.
"""

from __future__ import annotations

import math
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import Impl, attention

default_kernel_init = nn.initializers.normal(stddev=0.02)

#: logical axis name of the stacked leading layer dim under
#: ``scan_layers`` (``parallel/sharding.py`` replicates it for DDP/TP;
#: ``fsdp_reshard`` prefers it as the split dim — one uniform,
#: always-dividable axis across every leaf of the stack)
SCAN_LAYER_AXIS = "layers"


def _dense(features, dtype, name, logical_axes, kernel_init=None):
    return nn.DenseGeneral(
        features,
        dtype=dtype,
        kernel_init=nn.with_logical_partitioning(
            kernel_init or default_kernel_init, logical_axes
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros, logical_axes[1:]
        ),
        name=name,
    )


class _DenseParams(nn.Module):
    """Parameter-tree twin of an ``nn.DenseGeneral``: declares the same
    ``kernel``/``bias`` params (names, shapes, init streams, logical axes)
    under the same submodule name, but returns them instead of applying
    the matmul — so ``--tp_overlap`` can route the compute through the
    ring-decomposed collective matmuls (``parallel/collective_matmul.py``)
    while checkpoints and ``Task.init`` stay bit-interchangeable with the
    GSPMD-default path. ``in_features`` are the contraction dims, raw
    (unflattened), exactly as DenseGeneral stores them."""

    in_features: tuple[int, ...]
    features: tuple[int, ...]
    logical_axes: tuple
    kernel_init: Any = None

    @nn.compact
    def __call__(self):
        inner = self.kernel_init or default_kernel_init
        n_in = len(self.in_features)

        def flat_init(rng, shape, dtype=jnp.float32):
            # DenseGeneral's kernel_init_wrap: the initializer sees the
            # flattened 2D (fan_in, fan_out) shape, so fan-dependent
            # inits (lecun/he/...) draw the same values as the GSPMD
            # path, not just the shape-invariant default
            flat = (math.prod(shape[:n_in]), math.prod(shape[n_in:]))
            return jnp.reshape(inner(rng, flat, dtype), shape)

        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(flat_init, self.logical_axes),
            self.in_features + self.features, jnp.float32,
        )
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(
                nn.initializers.zeros,
                self.logical_axes[len(self.in_features):],
            ),
            self.features, jnp.float32,
        )
        return kernel, bias


def _plain_dense(x, kernel, bias, n_axes: int, dtype):
    """DenseGeneral's contraction, applied directly — the init-time path
    of the TP-overlapped layers (shapes/params only; init never needs the
    ring schedule) and the reference semantics the ring ops reproduce."""
    x = x.astype(dtype)
    kernel = kernel.astype(dtype)
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    kaxes = tuple(range(n_axes))
    y = jax.lax.dot_general(x, kernel, ((axes, kaxes), ((), ())))
    return y + bias.astype(dtype)


def _quant_or_plain(x, kernel, bias, n_axes: int, dtype, quant: str,
                    initializing: bool):
    """Dispatch one block matmul: the fp32-master low-precision dot
    (``ops/quant.py``) under ``--quant_compute``, DenseGeneral semantics
    otherwise. Init always takes the plain path — shapes/params only,
    and the quantized apply consumes the same ``_DenseParams`` twins, so
    the param tree stays bit-interchangeable with the default path."""
    if initializing or quant == "off":
        return _plain_dense(x, kernel, bias, n_axes, dtype)
    from ..ops.quant import quant_dense

    return quant_dense(x, kernel, bias, n_axes, quant, dtype)


class MultiHeadAttention(nn.Module):
    """Self-attention with fused-qkv-friendly layout and op dispatch.

    ``attn_impl="ring"`` runs ring attention over the ``seq`` mesh axis
    (context parallelism for long sequences, ``parallel/ring.py``);
    ``mesh`` must then be set (threaded from the encoder).
    """

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0
    attn_impl: str = "auto"  # Impl | "ring"
    mesh: jax.sharding.Mesh | None = None
    causal: bool = False
    # ring-decomposed TP matmuls (--tp_overlap): qkv becomes ONE fused
    # all-gather-matmul ring (the activation rotates once for all three
    # projections) and the out projection a matmul-reduce-scatter ring
    # (parallel/collective_matmul.py); param tree unchanged
    tp_overlap: bool = False
    # tp_local: the caller already traces this module INSIDE a shard_map
    # region covering the `model` axis (the ddp×tp composed schedule,
    # parallel/schedule.py) — run the same ring kernels per-shard with no
    # second region; num_heads/head_dim still describe the GLOBAL
    # geometry, the local arrays carry the per-shard slices
    tp_local: bool = False
    # low-precision compute (--quant_compute, ops/quant.py): qkv/out run
    # as per-channel-scaled int8/fp8 dots from the fp32 masters — via the
    # quantized ring kernels under tp_overlap (the ppermute carries the
    # narrow tensor), via quant_dense otherwise; param tree unchanged
    # (_DenseParams twins)
    quant_compute: str = "off"

    def _tp_qkv(self, x):
        from ..parallel.collective_matmul import (
            tp_column_dense, tp_column_dense_local,
        )

        embed = x.shape[-1]
        params = [
            _DenseParams((embed,), (self.num_heads, self.head_dim),
                         ("embed", "heads", "kv"), name=name)()
            for name in ("query", "key", "value")
        ]
        kernels = [k for k, _ in params]
        biases = [b for _, b in params]
        if self.is_initializing():
            return [_plain_dense(x, k, b, 1, self.dtype)
                    for k, b in params]
        x = x.astype(self.dtype)
        kernels = [k.astype(self.dtype) for k in kernels]
        biases = [b.astype(self.dtype) for b in biases]
        if self.tp_local:
            return tp_column_dense_local(x, kernels, biases,
                                         quant=self.quant_compute)
        return tp_column_dense(x, kernels, biases, self.mesh,
                               quant=self.quant_compute)

    def _tp_out(self, out, features):
        from ..parallel.collective_matmul import (
            tp_row_dense, tp_row_dense_local,
        )

        kernel, bias = _DenseParams(
            (self.num_heads, self.head_dim), (features,),
            ("heads", "kv", "embed"), name="out")()
        if self.is_initializing():
            return _plain_dense(out, kernel, bias, 2, self.dtype)
        if self.tp_local:
            return tp_row_dense_local(out.astype(self.dtype),
                                      kernel.astype(self.dtype),
                                      bias.astype(self.dtype),
                                      quant=self.quant_compute)
        return tp_row_dense(out.astype(self.dtype),
                            kernel.astype(self.dtype),
                            bias.astype(self.dtype), self.mesh,
                            quant=self.quant_compute)

    def _quant_qkv(self, x):
        """Non-TP low-precision qkv: the same ``_DenseParams`` twins the
        ring path uses, applied through ``ops.quant.quant_dense`` —
        checkpoints stay bit-interchangeable with the DenseGeneral
        path."""
        embed = x.shape[-1]
        params = [
            _DenseParams((embed,), (self.num_heads, self.head_dim),
                         ("embed", "heads", "kv"), name=name)()
            for name in ("query", "key", "value")
        ]
        return [_quant_or_plain(x, k, b, 1, self.dtype,
                                self.quant_compute,
                                self.is_initializing())
                for k, b in params]

    def _quant_out(self, out, features):
        kernel, bias = _DenseParams(
            (self.num_heads, self.head_dim), (features,),
            ("heads", "kv", "embed"), name="out")()
        return _quant_or_plain(out, kernel, bias, 2, self.dtype,
                               self.quant_compute, self.is_initializing())

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = True):
        features = x.shape[-1]
        if self.tp_overlap:
            q, k, v = self._tp_qkv(x)
        elif self.quant_compute != "off":
            q, k, v = self._quant_qkv(x)
        else:
            proj = lambda name: nn.DenseGeneral(
                (self.num_heads, self.head_dim),
                dtype=self.dtype,
                kernel_init=nn.with_logical_partitioning(
                    default_kernel_init, ("embed", "heads", "kv")
                ),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros, ("heads", "kv")
                ),
                name=name,
            )
            q = proj("query")(x)
            k = proj("key")(x)
            v = proj("value")(x)
        if self.attn_impl in ("ring", "ulysses"):
            if self.mesh is None:
                raise ValueError(f"attn_impl={self.attn_impl!r} requires mesh")
            kv_mask = None
            if mask is not None:
                # key-padding masks (B, 1, 1, T) become a (B, T) kv-validity
                # vector (rotated with its chunk on the ring path; gathered
                # once on the ulysses path); arbitrary (S, T) masks would
                # need both dims sharded — unsupported
                if mask.ndim != 4 or mask.shape[1] != 1 or mask.shape[2] != 1:
                    raise ValueError(
                        "context-parallel attention supports key-padding "
                        f"masks of shape (B, 1, 1, T) only; got {mask.shape}"
                    )
                kv_mask = mask[:, 0, 0, :]
            if self.attn_impl == "ring":
                from ..parallel.ring import ring_attention as cp_attention
            else:
                from ..parallel.ulysses import ulysses_attention as cp_attention

            out = cp_attention(q, k, v, self.mesh, causal=self.causal,
                               kv_mask=kv_mask)
        else:
            out = attention(q, k, v, mask=mask, causal=self.causal,
                            impl=self.attn_impl)
        if self.tp_overlap:
            out = self._tp_out(out, features)
        elif self.quant_compute != "off":
            out = self._quant_out(out, features)
        else:
            out = nn.DenseGeneral(
                features,
                axis=(-2, -1),
                dtype=self.dtype,
                kernel_init=nn.with_logical_partitioning(
                    default_kernel_init, ("heads", "kv", "embed")
                ),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros, ("embed",)
                ),
                name="out",
            )(out)
        if self.dropout_rate:
            out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        return out


class MlpBlock(nn.Module):
    """Position-wise feed-forward; hidden dim shards over ``mlp``.

    Under ``tp_overlap`` the two matmuls ride the ring-decomposed TP
    collectives: fc1 as an all-gather-matmul consuming seq-sharded
    activations chunk by chunk, fc2 as a matmul-reduce-scatter whose
    partial products reduce around the ring (the gelu between them is
    token-local and runs at the GSPMD level on the feature-sharded
    hidden). Param tree identical to the DenseGeneral path."""

    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0
    act: Callable = nn.gelu
    tp_overlap: bool = False
    tp_local: bool = False  # already inside a model-axis shard_map region
    mesh: jax.sharding.Mesh | None = None
    # low-precision compute (--quant_compute): fc1/fc2 as scaled int8/fp8
    # dots — quantized ring kernels under tp_overlap, quant_dense
    # otherwise; fp32 masters, param tree unchanged
    quant_compute: str = "off"

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        features = x.shape[-1]
        if self.tp_overlap:
            from ..parallel.collective_matmul import (
                tp_column_dense, tp_column_dense_local, tp_row_dense,
                tp_row_dense_local,
            )

            k1, b1 = _DenseParams((features,), (self.mlp_dim,),
                                  ("embed", "mlp"), name="fc1")()
            if self.is_initializing():
                h = _plain_dense(x, k1, b1, 1, self.dtype)
            elif self.tp_local:
                (h,) = tp_column_dense_local(
                    x.astype(self.dtype), [k1.astype(self.dtype)],
                    [b1.astype(self.dtype)], quant=self.quant_compute)
            else:
                (h,) = tp_column_dense(
                    x.astype(self.dtype), [k1.astype(self.dtype)],
                    [b1.astype(self.dtype)], self.mesh,
                    quant=self.quant_compute)
            h = self.act(h)
            k2, b2 = _DenseParams((self.mlp_dim,), (features,),
                                  ("mlp", "embed"), name="fc2")()
            if self.is_initializing():
                h = _plain_dense(h, k2, b2, 1, self.dtype)
            elif self.tp_local:
                h = tp_row_dense_local(h.astype(self.dtype),
                                       k2.astype(self.dtype),
                                       b2.astype(self.dtype),
                                       quant=self.quant_compute)
            else:
                h = tp_row_dense(h.astype(self.dtype),
                                 k2.astype(self.dtype),
                                 b2.astype(self.dtype), self.mesh,
                                 quant=self.quant_compute)
        elif self.quant_compute != "off":
            k1, b1 = _DenseParams((features,), (self.mlp_dim,),
                                  ("embed", "mlp"), name="fc1")()
            h = _quant_or_plain(x, k1, b1, 1, self.dtype,
                                self.quant_compute, self.is_initializing())
            h = self.act(h)
            k2, b2 = _DenseParams((self.mlp_dim,), (features,),
                                  ("mlp", "embed"), name="fc2")()
            h = _quant_or_plain(h, k2, b2, 1, self.dtype,
                                self.quant_compute, self.is_initializing())
        else:
            h = _dense(self.mlp_dim, self.dtype, "fc1", ("embed", "mlp"))(x)
            h = self.act(h)
            h = _dense(features, self.dtype, "fc2", ("mlp", "embed"))(h)
        if self.dropout_rate:
            h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return h


class EncoderBlock(nn.Module):
    """Pre-LN (ViT) or post-LN (BERT) encoder block."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0
    pre_norm: bool = True
    attn_impl: str = "auto"
    mesh: jax.sharding.Mesh | None = None
    causal: bool = False
    moe_experts: int = 0  # >0: FFN = top-1 MoE over this many experts
    tp_overlap: bool = False  # ring-decomposed TP matmuls (qkv/out/fc1/fc2)
    tp_local: bool = False  # already inside a model-axis shard_map region
    #                         (the ddp×tp composed schedule): geometry
    #                         fields then describe the PER-SHARD slice
    quant_compute: str = "off"  # low-precision fc1/fc2/qkv/out dots
    #                             (--quant_compute, ops/quant.py)

    @nn.compact
    def __call__(self, x, mask=None, train: bool = True):
        # ``train`` is positional (not keyword-only) so nn.remat can pin it
        # via static_argnums=(3,) — self counts as argnum 0
        ln = lambda name: nn.LayerNorm(dtype=jnp.float32, name=name)
        attn = MultiHeadAttention(
            self.num_heads, self.head_dim, self.dtype,
            self.dropout_rate, self.attn_impl, self.mesh, self.causal,
            tp_overlap=self.tp_overlap, tp_local=self.tp_local,
            quant_compute=self.quant_compute,
            name="attention",
        )
        if self.moe_experts:
            from .moe import MoeMlpBlock

            mlp = MoeMlpBlock(self.moe_experts, self.mlp_dim, self.dtype,
                              self.mesh, dropout_rate=self.dropout_rate,
                              name="mlp")
        else:
            mlp = MlpBlock(self.mlp_dim, self.dtype, self.dropout_rate,
                           tp_overlap=self.tp_overlap,
                           tp_local=self.tp_local, mesh=self.mesh,
                           quant_compute=self.quant_compute,
                           name="mlp")
        if self.pre_norm:
            x = x + attn(ln("ln_attn")(x).astype(self.dtype), mask, train=train)
            x = x + mlp(ln("ln_mlp")(x).astype(self.dtype), train=train)
        else:
            x = ln("ln_attn")(x + attn(x, mask, train=train)).astype(self.dtype)
            x = ln("ln_mlp")(x + mlp(x, train=train)).astype(self.dtype)
        return x


class TransformerEncoder(nn.Module):
    """Stack of encoder blocks with optional remat and scan-over-layers.

    ``remat`` applies ``nn.remat`` (jax.checkpoint) per block — trading
    FLOPs for HBM, the standard TPU recipe for deep/long-sequence configs.

    ``scan_layers`` drives ONE compiled block body over weights stacked on
    a leading ``(num_layers, ...)`` dim via ``nn.scan`` (the T5X/MaxText
    ``remat_scan`` idiom): XLA traces/lowers/optimises the block once
    instead of ``num_layers`` times, so compile time stops growing with
    depth. Composed with ``remat``, the checkpoint sits *inside* the scan
    body — activations saved only at layer boundaries, one block's worth
    of recompute (the remat-scan memory profile). Parameters land under a
    single ``layers`` subtree whose leading dim carries the
    :data:`SCAN_LAYER_AXIS` logical name: replicated for DDP/TP
    (``parallel/sharding.py``) and the preferred FSDP split dim. Scanned
    and unrolled are numerically interchangeable — ``Task.init`` derives
    scanned init by stacking the unrolled per-layer RNG streams
    (``parallel/stacking.py``), and ``tools/convert_checkpoint.py``
    restacks saved checkpoints either way.
    """

    num_layers: int
    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.0
    pre_norm: bool = True
    attn_impl: str = "auto"
    mesh: jax.sharding.Mesh | None = None
    causal: bool = False
    remat: bool = False
    moe_experts: int = 0
    scan_layers: bool = False
    # decomposed-FSDP execution (--fsdp_overlap, parallel/overlap.py):
    # explicit per-layer weight gathers pipelined one layer ahead of
    # compute, grad scatters drained under the previous layer's backward.
    # Requires scan_layers (the stacked layout IS the schedule's unit) and
    # a data-only mesh; init still runs through nn.scan so the param
    # layout, checkpoints and Task.init interchangeability are unchanged.
    fsdp_overlap: bool = False
    # compressed-DDP execution (--ddp_overlap, parallel/compress.py):
    # replicated params, per-layer cross-replica grad reduce issued
    # inside the backward scan iteration in grad_comm wire precision,
    # optional error-feedback residual (collection "comm_residual",
    # threaded from TrainState by the engine). Same scan_layers/data-only
    # requirements as fsdp_overlap; param layout unchanged.
    ddp_overlap: bool = False
    grad_comm: str = "fp32"
    grad_error_feedback: bool = False
    # decomposed tensor-parallel collective matmuls (--tp_overlap,
    # parallel/collective_matmul.py): inside the scanned stack the
    # Megatron matmuls become ring all-gather-matmul (fc1/fused-qkv) and
    # matmul-reduce-scatter (fc2/out) shard_map regions over the `model`
    # axis, with activations sequence-sharded over `model` between them;
    # hand-written custom_vjps pipeline the transposed collectives the
    # same way. Requires scan_layers and a data×model mesh; MoE and the
    # other overlap modes refused with intent.
    tp_overlap: bool = False
    # low-precision compute (--quant_compute {off,int8,fp8},
    # ops/quant.py): the block matmuls (fc1/fc2/qkv/out) run as
    # per-channel-scaled narrow dots from the fp32 masters — fused into
    # the ring collective matmuls under tp_overlap (the ppermute carries
    # the narrow tensor + scales), via quant_dense otherwise. Param tree
    # bit-interchangeable with the default path (_DenseParams twins);
    # MoE refused with intent (the expert dispatch has no quant path)
    quant_compute: str = "off"

    def _validate_quant(self) -> None:
        from ..ops.quant import QUANT_COMPUTE_MODES

        if self.quant_compute not in QUANT_COMPUTE_MODES:
            raise ValueError(
                f"unknown quant_compute mode {self.quant_compute!r}; "
                f"expected one of {QUANT_COMPUTE_MODES}")
        if self.moe_experts:
            raise ValueError(
                "--quant_compute does not compose with MoE blocks yet "
                "(the expert dispatch and per-expert FFNs have no "
                "quantized path); drop one of the two"
            )

    def _validate_tp(self, x) -> None:
        from ..parallel.collective_matmul import (
            validate_tp_mesh, _check_divisible,
        )

        from ..runtime.context import MODEL_AXIS

        # Task.init drives the unrolled twin (scan_layers=False clone) for
        # bit-interchangeable param stacking — the scan requirement binds
        # at apply time only
        if not self.scan_layers and not self.is_initializing():
            raise ValueError(
                "--tp_overlap needs --scan_layers: the ring-decomposed "
                "block is compiled once and driven over the stacked "
                "layers; pass both flags"
            )
        if self.moe_experts:
            raise ValueError(
                "--tp_overlap does not compose with MoE blocks yet (the "
                "expert dispatch needs in-region handling); drop one of "
                "the two"
            )
        if self.attn_impl in ("ring", "ulysses"):
            raise ValueError(
                "--tp_overlap does not compose with context-parallel "
                f"attention (attn_impl={self.attn_impl!r} needs a 'seq' "
                "mesh axis the TP rings refuse); drop one of the two"
            )
        validate_tp_mesh(self.mesh)
        n = self.mesh.shape[MODEL_AXIS]
        _check_divisible("sequence length", x.shape[1], n)
        _check_divisible("num_heads", self.num_heads, n)
        _check_divisible("mlp_dim", self.mlp_dim, n)

    @property
    def _ef_active(self) -> bool:
        return (self.ddp_overlap and self.grad_error_feedback
                and self.grad_comm != "fp32")

    def _declare_comm_residual(self, src_key: str) -> None:
        """Create the zero error-feedback residual as a ``comm_residual``
        collection variable during init, shaped from the just-created
        block params under ``src_key`` (``layer_0`` in the unrolled twin
        Task.init drives, the stacked subtree in a direct scanned init).
        Declared at the encoder level in both twins, so the collection
        path — which the engine round-trips through TrainState — is
        layout-independent. Composed with ``tp_overlap`` (r17, the r11
        named refusal lifted) each leaf is sized for the model-SHARDED
        local grads the ddp×tp drain reduces: ``(L, data, model,
        padded_local)`` per ``compress.residual_shape_tp``."""
        from ..parallel.compress import init_residual
        from ..runtime.context import DATA_AXIS, MODEL_AXIS

        if self.mesh is None:
            raise ValueError(
                "--grad_error_feedback needs the device mesh at init to "
                "size the per-replica residual (models/registry.py threads "
                "it; pass mesh= when building directly)"
            )
        src = nn.meta.unbox(self.scope.get_variable("params", src_key))
        if src is None:
            raise ValueError(
                f"comm_residual init found no {src_key!r} block params"
            )
        stacked_shapes = jax.tree.map(
            lambda p: (jax.ShapeDtypeStruct(p.shape, p.dtype)
                       if src_key == SCAN_LAYER_AXIS
                       else jax.ShapeDtypeStruct((self.num_layers,) + p.shape,
                                                 p.dtype)),
            src,
        )
        data_size = self.mesh.shape.get(DATA_AXIS, 1)
        tp_specs = None
        model_size = self.mesh.shape.get(MODEL_AXIS, 1)
        if self.tp_overlap and model_size > 1:
            from ..parallel.schedule import stacked_tp_specs

            tp_specs = stacked_tp_specs(stacked_shapes, self.mesh)
        self.variable("comm_residual", "residual",
                      lambda: init_residual(stacked_shapes, data_size,
                                            tp_specs=tp_specs,
                                            model_size=model_size))

    def _ddp_forward(self, block_cls, x, mask, train):
        """Drive the stacked block via ``parallel.compress.ddp_overlap_scan``:
        same replicated weights, same math, but each layer's grad reduce
        happens inside its own backward iteration in ``grad_comm`` wire
        precision. Composed with ``tp_overlap`` the region covers
        ``data × model``, the block runs the LOCAL ring kernels
        (``tp_local`` — geometry scaled to the per-shard slice), and each
        layer's drain merges TP's ``data``-psum of weight grads with the
        bucket reduce. Numerics match the nn.scan path to reduction
        reassociation under fp32 comms and dropout-free training; with
        dropout active each replica folds the layer index and its data-
        (and under tp, model-) axis coordinate into the stream
        (statistically equivalent, not bit-interchangeable — documented
        in README)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.compress import ddp_overlap_scan, validate_ddp_mesh
        from ..runtime.context import DATA_AXIS, MODEL_AXIS

        if self.moe_experts:
            raise ValueError(
                "--ddp_overlap does not compose with MoE blocks yet (the "
                "sown load-balance losses and expert dispatch need "
                "in-region handling); drop one of the two"
            )
        validate_ddp_mesh(self.mesh, tp=self.tp_overlap)
        stacked = nn.meta.unbox(
            self.scope.get_variable("params", SCAN_LAYER_AXIS))
        if stacked is None:
            raise ValueError(
                "ddp_overlap apply found no stacked "
                f"'{SCAN_LAYER_AXIS}' params — was the model initialised "
                "with scan_layers?"
            )
        tp_specs = None
        tp_n = 1
        if self.tp_overlap:
            from ..parallel.schedule import stacked_tp_specs

            tp_specs = stacked_tp_specs(stacked, self.mesh)
            tp_n = self.mesh.shape[MODEL_AXIS]
        block = block_cls(
            # under tp the block traces INSIDE the region: its geometry
            # fields must describe the per-shard slice (flax validates
            # param shapes at apply against these)
            self.num_heads // tp_n, self.head_dim,
            self.mlp_dim // tp_n, self.dtype,
            self.dropout_rate, self.pre_norm, self.attn_impl, self.mesh,
            self.causal, moe_experts=self.moe_experts,
            tp_overlap=self.tp_overlap, tp_local=self.tp_overlap,
            quant_compute=self.quant_compute,
            parent=None, name=SCAN_LAYER_AXIS,
        )
        lossy = self.grad_comm != "fp32"
        base_rng = None
        if train and self.has_rng("dropout") and (self.dropout_rate or lossy):
            base_rng = self.make_rng("dropout")
        if train and lossy and base_rng is None:
            raise ValueError(
                f"--grad_comm {self.grad_comm} training needs an rng "
                "stream for stochastic rounding; apply with "
                "rngs={'dropout': key} (the engine always passes one)"
            )
        drop_rng = base_rng if (train and self.dropout_rate) else None
        # decorrelate the stochastic-rounding stream from every per-layer
        # dropout fold (which use indices 0..num_layers-1)
        comm_rng = (jax.random.fold_in(base_rng, self.num_layers + 1)
                    if (train and lossy) else None)
        residual = None
        if train and self._ef_active:
            if not self.has_variable("comm_residual", "residual"):
                raise ValueError(
                    "--grad_error_feedback apply found no comm_residual "
                    "state — the engine threads TrainState.comm_residual "
                    "in as the 'comm_residual' collection (fresh inits "
                    "create it; see train/engine.py)"
                )
            residual = self.scope.get_variable("comm_residual", "residual")

        def apply_one(w, y, k, extras):
            m, r = extras
            rngs = None
            if r is not None:
                # per-layer, per-replica dropout stream: apply_one runs
                # inside the shard_map region, so the axis fold gives
                # each replica its own mask over its own batch shard
                # (and, composed with tp, its own seq chunk)
                rr = jax.random.fold_in(jax.random.fold_in(r, k),
                                        jax.lax.axis_index(DATA_AXIS))
                if self.tp_overlap:
                    rr = jax.random.fold_in(
                        rr, jax.lax.axis_index(MODEL_AXIS))
                rngs = {"dropout": rr}
            # positional train: the remat wrapper pins it static via
            # static_argnums=(3,) (self counts as argnum 0)
            if self.remat:
                return block.apply({"params": w}, y, m, train, rngs=rngs)
            return block.apply({"params": w}, y, m, train=train, rngs=rngs)

        extras = (mask, drop_rng)
        extras_specs = (None if mask is None else P(DATA_AXIS),
                        None if drop_rng is None else P())
        return ddp_overlap_scan(
            apply_one, stacked, x, extras, extras_specs, self.mesh,
            # eval never runs the backward, so the wire mode is moot —
            # fp32 keeps the rng-free eval path from demanding an rng
            # (and anyone differentiating an eval-mode loss gets exact
            # grads, which is what a probe wants)
            grad_comm=self.grad_comm if train else "fp32",
            residual=residual, comm_rng=comm_rng, tp_specs=tp_specs)

    def _overlap_forward(self, block_cls, x, mask, train):
        """Drive the stacked block through the unified decomposed scan at
        the GSPMD level: ``fsdp_overlap`` (± ``tp_overlap``) rides
        ``parallel.overlap.overlap_scan`` (the fsdp gather/scatter
        schedule, with the Megatron model placement threaded through the
        region specs when composed), ``tp_overlap`` alone rides the null
        weight schedule (``parallel.schedule.PlainSchedule``) — the
        block's own ring collective matmuls carry the model-axis
        overlap, and the per-layer backward structure drains each
        layer's ``data``-psum of TP weight grads inside its own
        iteration. Numerics match the nn.scan path bit-for-bit in eval
        mode and dropout-free training (TP rows to ring reassociation);
        with dropout active the per-layer streams are folded from the
        layer index rather than nn.scan's split — statistically
        equivalent, not bit-identical."""
        from ..parallel.overlap import overlap_scan

        flag = "--fsdp_overlap" if self.fsdp_overlap else "--tp_overlap"
        if self.moe_experts:
            raise ValueError(
                f"{flag} does not compose with MoE blocks yet (the "
                "sown load-balance losses and expert dispatch need "
                "in-region handling); drop one of the two"
            )
        stacked = nn.meta.unbox(
            self.scope.get_variable("params", SCAN_LAYER_AXIS))
        if stacked is None:
            raise ValueError(
                f"{flag} apply found no stacked "
                f"'{SCAN_LAYER_AXIS}' params — was the model initialised "
                "with scan_layers?"
            )
        tp_specs = None
        if self.tp_overlap and self.fsdp_overlap:
            # only the gather/scatter specs consume the TP placement;
            # tp-alone (PlainSchedule) slices replicated-over-data
            # weights and needs no spec table
            from ..parallel.schedule import stacked_tp_specs

            tp_specs = stacked_tp_specs(stacked, self.mesh)
        block = block_cls(
            self.num_heads, self.head_dim, self.mlp_dim, self.dtype,
            self.dropout_rate, self.pre_norm, self.attn_impl, self.mesh,
            self.causal, moe_experts=self.moe_experts,
            tp_overlap=self.tp_overlap,
            quant_compute=self.quant_compute,
            parent=None, name=SCAN_LAYER_AXIS,
        )
        dropout_rng = None
        if train and self.dropout_rate and self.has_rng("dropout"):
            dropout_rng = self.make_rng("dropout")

        def apply_one(w, y, k, extras):
            mask, base_rng = extras
            rngs = (None if base_rng is None
                    else {"dropout": jax.random.fold_in(base_rng, k)})
            # positional train: the remat wrapper pins it static via
            # static_argnums=(3,) (self counts as argnum 0)
            if self.remat:
                return block.apply({"params": w}, y, mask, train, rngs=rngs)
            return block.apply({"params": w}, y, mask, train=train,
                               rngs=rngs)

        # mask/rng ride as explicit custom_vjp args (tracers must not be
        # closed over); None entries vanish from the pytree harmlessly
        if self.fsdp_overlap:
            return overlap_scan(apply_one, stacked, x, (mask, dropout_rng),
                                self.mesh, tp_specs=tp_specs)
        from ..parallel.collective_matmul import validate_tp_mesh
        from ..parallel.schedule import PlainSchedule, decomposed_scan

        validate_tp_mesh(self.mesh)
        return decomposed_scan(PlainSchedule(), apply_one, stacked, x,
                               (mask, dropout_rng))

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = True):
        if self.quant_compute != "off":
            self._validate_quant()
        if self.tp_overlap:
            self._validate_tp(x)
        block_cls = EncoderBlock
        if self.remat:
            block_cls = nn.remat(EncoderBlock, static_argnums=(3,))
        if self.scan_layers:
            if not self.is_initializing():
                if self.fsdp_overlap:
                    return self._overlap_forward(block_cls, x, mask, train)
                if self.ddp_overlap:
                    return self._ddp_forward(block_cls, x, mask, train)
                if self.tp_overlap:
                    # tp alone also rides the unified decomposed scan
                    # (PlainSchedule): one scanned body whose per-layer
                    # backward drains each layer's TP weight-grad psum
                    # inside its own iteration
                    return self._overlap_forward(block_cls, x, mask, train)
            block = block_cls(
                self.num_heads, self.head_dim, self.mlp_dim, self.dtype,
                self.dropout_rate, self.pre_norm, self.attn_impl, self.mesh,
                self.causal, moe_experts=self.moe_experts,
                tp_overlap=self.tp_overlap,
                quant_compute=self.quant_compute,
                name=SCAN_LAYER_AXIS,
            )

            def body(blk, carry, _):
                # positional train: the remat wrapper pins it static via
                # static_argnums=(3,) (self counts as argnum 0)
                y = blk(carry, mask, train) if self.remat else blk(
                    carry, mask, train=train)
                return y, None

            x, _ = nn.scan(
                body,
                # params stack on a new leading dim; sown aux losses (MoE
                # load-balance) stack per layer too — Task._apply_inputs
                # sums leaves, so an (L,) stack and L scalars agree
                variable_axes={"params": 0, "losses": 0},
                # distinct per-layer init/dropout streams — without the
                # split every layer would initialise identically, the
                # classic scan-over-layers pitfall
                split_rngs={"params": True, "dropout": True},
                length=self.num_layers,
                metadata_params={nn.meta.PARTITION_NAME: SCAN_LAYER_AXIS},
            )(block, x, None)
            if self._ef_active and self.is_initializing():
                self._declare_comm_residual(SCAN_LAYER_AXIS)
            return x
        for layer in range(self.num_layers):
            block = block_cls(
                self.num_heads, self.head_dim, self.mlp_dim, self.dtype,
                self.dropout_rate, self.pre_norm, self.attn_impl, self.mesh,
                self.causal, moe_experts=self.moe_experts,
                tp_overlap=self.tp_overlap,
                quant_compute=self.quant_compute,
                name=f"layer_{layer}",
            )
            x = block(x, mask, train) if self.remat else block(
                x, mask, train=train)
        if self._ef_active and self.is_initializing():
            # the unrolled twin drives scan-layers init (Task.init's
            # bit-interchangeable restack); declare the residual here too
            # so the restacked variables carry it at the same path
            self._declare_comm_residual("layer_0")
        return x
