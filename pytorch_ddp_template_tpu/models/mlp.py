"""The reference's toy model, TPU-native.

``FooModel`` (``/root/reference/model.py:8-16``) is
``Linear(10,10) → ReLU → Linear(10,5)``. Same architecture here as a Flax
module with a configurable width/dtype so the identical code path scales
from the toy config to wide MLPs that actually exercise the MXU.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Dense → ReLU stack. Defaults reproduce FooModel's 10→10→5."""

    features: Sequence[int] = (10, 5)
    dtype: jnp.dtype = jnp.float32  # compute dtype; bf16 under --bf16

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = True) -> jnp.ndarray:
        x = x.astype(self.dtype)
        for i, feat in enumerate(self.features):
            x = nn.Dense(feat, dtype=self.dtype, name=f"dense_{i}")(x)
            if i != len(self.features) - 1:
                x = nn.relu(x)
        return x
