"""ResNet family (18/50) — the vision rungs of the BASELINE.md config ladder.

The reference's zoo is a single hardcoded MLP (``/root/reference/model.py:8-16``,
constructed at ``ddp.py:311``); BASELINE.json names ResNet-50 images/sec/chip
as the headline metric, so this file provides the standard ResNet-v1.5
family as Flax modules, TPU-first:

- NHWC layout throughout (the TPU-native convolution layout; XLA tiles
  NHWC convs directly onto the MXU).
- Compute dtype is configurable (bf16 under ``--bf16``); BatchNorm statistics
  and the final logits stay f32 for numerical stability.
- BatchNorm batch statistics live in the ``batch_stats`` collection, threaded
  through the engine as ``extra_vars``. Under ``jit`` with the batch sharded
  over the ``data`` mesh axis, the batch-mean/variance reductions are *global*
  (GSPMD inserts the cross-replica collective) — i.e. sync-BN for free, where
  the reference's DDP keeps per-GPU local statistics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

ModuleDef = Any

#: named-checkpoint tag on every block conv output — the handle the
#: selective-remat policy (``remat_save_convs``) saves by name. Transparent
#: (identity) when no remat policy consumes it.
CONV_OUT = "conv_out"


class BasicBlock(nn.Module):
    """Two 3x3 convs; the ResNet-18/34 residual block."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = checkpoint_name(self.conv(self.filters, (3, 3), self.strides)(x),
                            CONV_OUT)
        y = self.norm()(y)
        y = self.act(y)
        y = checkpoint_name(self.conv(self.filters, (3, 3))(y), CONV_OUT)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = checkpoint_name(
                self.conv(self.filters, (1, 1), self.strides,
                          name="conv_proj")(residual), CONV_OUT)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck; the ResNet-50/101/152 block (v1.5:
    stride on the 3x3, not the first 1x1)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = checkpoint_name(self.conv(self.filters, (1, 1))(x), CONV_OUT)
        y = self.norm()(y)
        y = self.act(y)
        y = checkpoint_name(
            self.conv(self.filters, (3, 3), self.strides)(y), CONV_OUT)
        y = self.norm()(y)
        y = self.act(y)
        y = checkpoint_name(self.conv(self.filters * 4, (1, 1))(y), CONV_OUT)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = checkpoint_name(
                self.conv(self.filters * 4, (1, 1), self.strides,
                          name="conv_proj")(residual), CONV_OUT)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5, NHWC, with an ImageNet (7x7/s2 + maxpool) or CIFAR
    (3x3/s1, no pool) stem."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    stem: str = "imagenet"  # or "cifar"
    # BatchNorm compute dtype. f32 is the conservative default; bf16 keeps
    # the normalise/scale/ReLU traffic in 2-byte lanes between convs (the
    # running statistics stay f32 either way via param_dtype), measured as
    # HBM-bandwidth relief on the conv families (tools/mfu_probe.py).
    norm_dtype: jnp.dtype = jnp.float32
    # Rematerialise each residual block in backward: saves only block
    # boundaries, recomputing interior activations — a bandwidth-for-flops
    # trade that can pay on an HBM-bound step where the MXU sits 75% idle
    # (tools/mfu_probe.py --remat measures whether it does here).
    remat: bool = False
    # Selective remat (with ``remat``): save every block conv output by
    # name and recompute only the norm/ReLU chains in backward — the
    # roofline analysis's "cut activation traffic without re-running
    # convs" lever (BENCH.md "Where the ResNet-50 MFU goes"): full-block
    # remat re-runs the convs (measured a net loss on the HBM-bound
    # step), while this spends only cheap elementwise recompute to drop
    # the post-norm activation stores.
    remat_save_convs: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.norm_dtype,
            param_dtype=jnp.float32,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        elif self.stem == "space_to_depth":
            # MLPerf-style stem: fold 2x2 spatial blocks into channels
            # (H,W,3 -> H/2,W/2,12) and swap the 7x7/s2 conv for 4x4/s1 —
            # the same downsampling, but the conv input has 12 channels
            # instead of 3, a shape the MXU tiles far less wastefully.
            # Not weight-compatible with the imagenet stem (fresh stem
            # params); the trunk is unchanged.
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), (1, 1), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = act(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")

        if self.remat:
            policy = (jax.checkpoint_policies.save_only_these_names(CONV_OUT)
                      if self.remat_save_convs else None)
            block_cls = nn.remat(self.block_cls, policy=policy)
        else:
            block_cls = self.block_cls
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                # explicit name: nn.remat changes the class-derived scope
                # name, so without this a remat toggle would silently
                # re-key the whole param tree and orphan checkpoints.
                # (One-time break: checkpoints written before these names
                # existed — BasicBlock_N/BottleneckBlock_N keys — cannot
                # be restored into this tree.)
                x = block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    act=act,
                    strides=strides,
                    name=f"stage{i}_block{j}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
