"""ctypes binding to the native host runtime (``native/native.cc``).

The reference's native capability arrives through third-party CUDA
libraries (NCCL/apex, SURVEY.md §2c); this framework's first-party native
layer targets the host input path instead — the classic TPU bottleneck
(SURVEY.md §7 hard part (e)): epoch permutation, synthetic sample
fabrication, and batch row gather, all C++ with counter-based RNG.

Graceful degradation: if ``libddptpu_native.so`` is absent (not built) or
``DDPTPU_NATIVE=0``, callers fall back to their numpy paths. The native
RNG streams are *defined* by (seed, counter) keys, so data is reproducible
across runs and hosts on the same path; the numpy fallback is a separate
deterministic stream (documented in data/dataset.py).

Build: ``make -C native`` (plain g++, no deps).
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path

import numpy as np

_LIB_NAME = "libddptpu_native.so"


def _find_library() -> ctypes.CDLL | None:
    if os.environ.get("DDPTPU_NATIVE", "1") == "0":
        return None
    candidates = [
        Path(os.environ.get("DDPTPU_NATIVE_LIB", "")),
        Path(__file__).resolve().parent.parent / "native" / _LIB_NAME,
    ]
    for path in candidates:
        if path and path.is_file():
            try:
                return ctypes.CDLL(str(path))
            except OSError:
                continue
    return None


_lib = _find_library()

if _lib is not None:
    _lib.ddp_permutation.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    _lib.ddp_synth_u8.argtypes = [
        ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
    ]
    _lib.ddp_gather_rows.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
    ]


def available() -> bool:
    return _lib is not None


def default_threads() -> int:
    return max(1, min(8, os.cpu_count() or 1))


def permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """Fisher-Yates permutation of [0, n) keyed on (seed, epoch)."""
    if _lib is None:
        raise RuntimeError("native library not available")
    out = np.empty(n, np.int64)
    _lib.ddp_permutation(seed, epoch, n, out)
    return out


def synth_u8(seed: int, indices: np.ndarray, bytes_per_sample: int,
             n_threads: int | None = None) -> np.ndarray:
    """Deterministic per-sample byte streams keyed on (seed, index);
    returns ``(len(indices), bytes_per_sample)`` uint8."""
    if _lib is None:
        raise RuntimeError("native library not available")
    idx = np.ascontiguousarray(indices, np.int64)
    out = np.empty((len(idx), bytes_per_sample), np.uint8)
    _lib.ddp_synth_u8(seed, idx, len(idx), bytes_per_sample, out,
                      n_threads or default_threads())
    return out


def gather_rows(src: np.ndarray, indices: np.ndarray,
                n_threads: int | None = None) -> np.ndarray:
    """``src[indices]`` for a 2D+ C-contiguous array via threaded memcpy."""
    if _lib is None:
        raise RuntimeError("native library not available")
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, np.int64)
    idx = np.where(idx < 0, idx + len(src), idx)  # numpy negative-index semantics
    if len(idx) and (idx.min() < 0 or idx.max() >= len(src)):
        raise IndexError(
            f"gather index out of range [0, {len(src)}): "
            f"min={idx.min()}, max={idx.max()}"
        )
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], initial=1))
    out = np.empty((len(idx), *src.shape[1:]), src.dtype)
    _lib.ddp_gather_rows(
        src.view(np.uint8).reshape(len(src), row_bytes),
        idx, len(idx), row_bytes,
        out.view(np.uint8).reshape(len(idx), row_bytes),
        n_threads or default_threads(),
    )
    return out
