"""Distributed runtime context: init, mesh, teardown.

Capability parity with the reference's ``setup``/``cleanup``
(``/root/reference/ddp.py:80-121``), TPU-first:

- The reference spawns one process per GPU and rendezvouses over a TCP
  store (``MASTER_ADDR``/``MASTER_PORT``, ``ddp.py:103``). Under JAX one
  process per *host* drives all local chips; multi-host rendezvous is
  ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)``, discovered automatically on TPU pods.
- The reference binds a device per process (``ddp.py:100-101``). Here
  device placement is declarative: a :class:`jax.sharding.Mesh` over all
  global devices, with named axes. DDP's implicit gradient allreduce
  (``ddp.py:194-195, 231``) becomes sharding-induced ``psum`` over the
  ``data`` axis — XLA emits the collectives over ICI/DCN.
- ``set_seed`` (``ddp.py:44-49``) seeds three global RNGs identically on
  every rank; JAX threads explicit ``PRNGKey`` state instead. We fold in
  the process index for host-local streams (data order) while keeping a
  shared key for init (parameter broadcast equivalence).
"""

from __future__ import annotations

import atexit
import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import TrainingConfig
from ..utils import get_logger, redirect_warnings_to_logger

log = get_logger(__name__)

#: Canonical mesh axis names, each with a real mechanism: ``data`` carries
#: the DDP capability (sharding-induced psum), ``model`` tensor-parallel
#: weight sharding (parallel/sharding.py), ``seq`` ring/Ulysses context
#: parallelism (parallel/ring.py, ulysses.py), ``pipe`` the GPipe schedule
#: (parallel/pipeline.py), ``expert`` all_to_all MoE dispatch
#: (parallel/expert.py).
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def parse_mesh_spec(spec: str, n_devices: int) -> dict[str, int]:
    """Parse ``"data:4,model:2"`` into an ordered ``{axis: size}`` dict.

    A single ``-1`` size is inferred from the device count (like a reshape
    wildcard). Validates the product against ``n_devices``.
    """
    axes: dict[str, int] = {}
    wildcard: str | None = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size_s = part.partition(":")
        size = int(size_s) if size_s else -1
        if size == -1:
            if wildcard is not None:
                raise ValueError(f"mesh spec {spec!r}: more than one -1 axis")
            wildcard = name
        axes[name] = size
    if wildcard is not None:
        known = int(np.prod([s for s in axes.values() if s != -1])) if len(axes) > 1 else 1
        if n_devices % known:
            raise ValueError(f"mesh spec {spec!r} does not divide {n_devices} devices")
        axes[wildcard] = n_devices // known
    total = int(np.prod(list(axes.values())))
    if total != n_devices:
        raise ValueError(
            f"mesh spec {spec!r} covers {total} devices but {n_devices} are present"
        )
    return axes


def make_mesh(spec: str = "data:-1", devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a named Mesh over the global device array.

    Devices are laid out in their default (ICI-contiguous) order so that the
    innermost mesh axis maps to physically adjacent chips — collectives on
    that axis ride ICI, not DCN. For multi-slice topologies put ``data``
    outermost (DCN-friendly allreduce) and model/seq axes innermost.
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = parse_mesh_spec(spec, len(devices))
    shape = tuple(axes.values())
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


@dataclasses.dataclass
class RuntimeContext:
    """What ``setup()`` hands the trainer (reference mutates ``args``;
    we return an explicit context object)."""

    mesh: Mesh
    seed_key: jax.Array  # shared across hosts — param init / dropout
    host_key: jax.Array  # folded with process_index — data order etc.
    config: TrainingConfig

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def data_sharding(self, *trailing_axes: str | None) -> NamedSharding:
        """Sharding for a batch array: leading dim split over ``data``."""
        return NamedSharding(self.mesh, P(DATA_AXIS, *trailing_axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


_initialized = False

#: the XLA latency-hiding-scheduler pack (``--xla_overlap_flags``): lets the
#: TPU scheduler run collectives asynchronously under compute — the
#: compiler half of the decomposed-FSDP story (``parallel/overlap.py``
#: makes the gathers *schedulable*; these flags make the scheduler *use*
#: that freedom). The set follows the public MaxText/XLA guidance for
#: overlapping FSDP collectives; unknown flags are rejected by the flag
#: parser at backend init, which is why the pack is opt-in rather than
#: always-on (CPU/GPU backends of other jaxlib builds may not know the
#: tpu-prefixed ones).
OVERLAP_XLA_FLAGS: tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)


def apply_overlap_xla_flags() -> list[str]:
    """Append :data:`OVERLAP_XLA_FLAGS` to ``XLA_FLAGS`` (idempotent).

    Returns the flags actually added (already-present ones are skipped so
    an operator's explicit setting wins). Must run BEFORE the first
    backend touch — XLA reads the env exactly once at client init; the
    CLI path (``ddp.py`` → ``runtime.init``) satisfies this, and the
    startup log records what was set so a too-late call is auditable.
    """
    import os

    current = os.environ.get("XLA_FLAGS", "")
    # compare FLAG NAMES token-wise, not as substrings: a pack flag that
    # prefixes an operator-set longer flag (…_fusion vs …_fusion_fuse_all_
    # gather) must not be mistaken for already-present
    current_names = {t.split("=", 1)[0] for t in current.split()}
    added = [f for f in OVERLAP_XLA_FLAGS
             if f.split("=", 1)[0] not in current_names]
    if added:
        os.environ["XLA_FLAGS"] = (current + " " + " ".join(added)).strip()
    return added


def init(config: TrainingConfig) -> RuntimeContext:
    """Establish the distributed context. Reference: ``setup`` ddp.py:80-115.

    Single-process (no coordinator configured, one host) skips
    ``jax.distributed.initialize`` entirely — the same code path then runs
    from a laptop CPU to a v4-32 pod (SURVEY.md §4: the reference's CPU path
    is its de-facto fake backend; ours is literally the same path).
    """
    global _initialized
    redirect_warnings_to_logger(log)
    # Sharding-invariant PRNG. The legacy threefry lowering draws
    # DIFFERENT bits once GSPMD spatially partitions a consumer: on a
    # data:2,seq:2,model:2 mesh the jitted eval's MLM mask was a different
    # (valid) 15% subset than the same seed drawn eagerly — the "numeric
    # drift" that parked tests/test_eval_exact.py's seq-mesh case. The
    # partitionable implementation's contract is bit-identical draws
    # regardless of sharding; it changes every stream's values vs older
    # releases (fresh runs only — checkpointed state is data, not seeds).
    jax.config.update("jax_threefry_partitionable", True)
    if config.xla_overlap_flags:
        # unknown flags in XLA_FLAGS are FATAL at backend init (verified
        # on this CPU jaxlib: "F ... Unknown flags in XLA_FLAGS"), so the
        # TPU-oriented pack only applies when a TPU plugin can actually be
        # the backend; the skip is logged so a mis-targeted run is
        # auditable rather than silently unflagged
        import importlib.util
        import os as _os

        cpu_forced = config.cpu or _os.environ.get(
            "JAX_PLATFORMS", "").strip().lower() == "cpu"
        has_tpu = any(importlib.util.find_spec(m) is not None
                      for m in ("axon", "libtpu"))
        if cpu_forced or not has_tpu:
            log.warning(
                "--xla_overlap_flags skipped",
                {"reason": "cpu backend forced" if cpu_forced
                 else "no TPU plugin importable",
                 "flags_not_set": list(OVERLAP_XLA_FLAGS)},
            )
        else:
            added = apply_overlap_xla_flags()
            log.info(
                "xla overlap flags",
                {"added": added,
                 "already_set": [f for f in OVERLAP_XLA_FLAGS
                                 if f not in added]},
            )
    if config.cpu:
        jax.config.update("jax_platforms", "cpu")
    if config.coordinator_address is not None and not _initialized:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        _initialized = True
        atexit.register(shutdown)

    devices = jax.devices()
    if jax.process_count() > 1:
        # RNG-path agreement: data order / synthetic streams come from the
        # native C++ RNG when libddptpu_native.so is present, else numpy.
        # A mixed fleet would silently break the disjoint-cover sharding
        # invariant (each stream is deterministic, but they differ).
        from .. import native
        from jax.experimental import multihost_utils

        flags = np.asarray(multihost_utils.process_allgather(
            np.asarray([1 if native.available() else 0], np.int32)
        )).reshape(-1)
        if len(set(flags.tolist())) > 1:
            raise RuntimeError(
                "native host runtime availability differs across processes "
                f"({flags.tolist()}); build native/ on every host or set "
                "DDPTPU_NATIVE=0 everywhere"
            )
    mesh = make_mesh(config.mesh, devices)
    seed_key = jax.random.PRNGKey(config.seed)
    host_key = jax.random.fold_in(seed_key, jax.process_index())
    log.info(
        "runtime initialised",
        {
            "process": f"{jax.process_index()}/{jax.process_count()}",
            "local_devices": jax.local_device_count(),
            "global_devices": len(devices),
            "platform": devices[0].platform,
            "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
            "seed": config.seed,
        },
    )
    return RuntimeContext(mesh=mesh, seed_key=seed_key, host_key=host_key, config=config)


def shutdown() -> None:
    """Teardown (reference: ``cleanup`` ddp.py:118-121). Safe to call twice."""
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 - shutdown must never raise at exit
            pass
        _initialized = False
