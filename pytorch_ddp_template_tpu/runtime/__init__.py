"""Distributed runtime: context init/teardown, mesh construction."""

from .context import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    RuntimeContext,
    init,
    make_mesh,
    parse_mesh_spec,
    shutdown,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "PIPE_AXIS",
    "EXPERT_AXIS",
    "RuntimeContext",
    "init",
    "make_mesh",
    "parse_mesh_spec",
    "shutdown",
]
