"""pytorch_ddp_template_tpu — a TPU-native distributed training framework.

A from-scratch JAX/XLA/pjit framework with the capability envelope of the
PyTorch DDP template it is benchmarked against (see SURVEY.md): synchronous
data-parallel training over a device mesh, per-host sharded input pipelines,
in-jit gradient accumulation and global-norm clipping, warmup-linear LR
schedules, bf16 mixed precision, step-numbered checkpoint/resume, structured
rank-aware logging, and single-host / TPU-pod / SLURM launchers — with
gradient allreduce expressed as XLA collectives over ICI/DCN instead of NCCL.
"""

__version__ = "0.1.0"

from .config import TrainingConfig, build_arg_parser, parse_args  # noqa: F401
