"""Supervisor policy: turn watchtower verdicts into fleet actions.

Four rounds of observability (r12 sentry, r13 goodput, r14 fleet
attribution, r15 memory tripwires) DETECT trouble; until r18 every
confirmed verdict ended as a triage bundle and a log line — a human
still had to checkpoint, drain the sick host and relaunch. Bamboo
(Thorpe et al., NSDI'23) is the production argument for closing the
loop automatically on preemptible fleets: capacity comes and goes, so
the *run* must be the thing that knows how to move. ``--supervise``
adds that policy layer:

- **off** (default) — verdicts stay what they were: bundles + logs.
- **warn** — the supervisor evaluates every confirmed verdict against
  its action table and logs exactly what it WOULD do, recording the
  decision (``acted: false``) in ``<output_dir>/supervisor.json`` —
  the dry-run for operators building trust.
- **act** — the action executes: checkpoint now (durable, plus a hot
  snapshot when the layer is on) → mark the named host for eviction →
  stop the fleet coherently through the SAME device-side stop
  agreement SIGTERM rides (r6) → the relaunch resumes on the healthy
  subset, resharding in-restore (``checkpoint/reshard.py``) when the
  surviving shape differs. The restart gap books to the goodput
  ledger's ``evict_resume`` bucket — the supervisor's decisions are
  themselves metered.

Action table (the verdict kinds the r12/r14/r15 sentry confirms):

========================  ==========================================
verdict                   action (act mode)
========================  ==========================================
``straggler``             checkpoint → evict the NAMED host → resume
                          on the healthy subset
``mem_pressure``          checkpoint → restart (no host to evict; a
                          shrinking-capacity restart rides the same
                          reshard path)
``regression``            record + log only (a slower-but-correct run
                          is information; restart-looping on it would
                          burn goodput chasing noise)
``anomaly``               record + log only (NaN/spike: restarting
                          replays the same math — the r12 halt mode
                          already owns the stop decision)
========================  ==========================================

Threading contract: ``on_verdict`` arrives on the telemetry drain
thread (the same path that feeds the sentry); the loop polls
``poll()`` once per iteration and performs the action on the loop
thread — first actionable verdict wins, later ones are recorded but
do not re-fire (one coordinated stop per attempt is the whole point).

This module also hosts the deterministic **fault-injection harness**
(``--inject_fault kind:step[:param]``) that drives the elastic stack
in tests and ``BENCH_MODE=elastic``: ``crash`` (hard ``os._exit`` —
no atexit, no final save), ``hang-host`` (the process wedges),
``slow-host`` (a per-step sleep from that step on — a synthetic
straggler the fleet layer must attribute), ``corrupt-hot-snapshot``
(flip bytes in the newest hot generation — the restore fallback must
catch it).
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any

from ..utils import get_logger, is_main_process
from ..utils.serialization import json_sanitize

log = get_logger(__name__)

FILENAME = "supervisor.json"

#: verdict kind -> supervisor action (see the module table)
VERDICT_ACTIONS = {
    "straggler": "evict",
    "mem_pressure": "restart",
    "regression": "observe",
    "anomaly": "observe",
}

#: actions that stop the run (and therefore fire at most once)
_STOPPING = ("evict", "restart")

_DAY_S = 86_400.0


class Supervisor:
    """Evaluate confirmed verdicts against the action table; the engine
    executes (act) or logs (warn) what :meth:`poll` hands it.

    **Hysteresis (r19, ROADMAP r18 open (d))** — two guards keep a
    flapping host from evict-looping the fleet, both enforced from the
    ``supervisor.json`` decision ledger this class already writes (so
    they hold ACROSS attempts — the loop is exactly a restart cycle):

    - *cooldown*: a stopping verdict landing within ``cooldown_s`` of
      the previous acted stop is downgraded to observe-only (recorded
      with ``suppressed: "cooldown"``). A host that goes sick, gets
      evicted, and immediately re-triggers on the resumed subset gets
      one recovery window before the supervisor may stop the run again.
    - *eviction budget*: at most ``evict_budget_per_day`` acted
      evictions in any trailing 24 h, counted over the persisted ledger
      plus this attempt (``suppressed: "budget"`` past it). Restarts
      (mem_pressure) spend cooldown but not the eviction budget — they
      drain no host.

    Suppressed verdicts still land in the decision log and
    ``/status`` — the operator sees what the policy refused and why.
    """

    def __init__(self, mode: str, output_dir: str | Path, *,
                 cooldown_s: float = 600.0,
                 evict_budget_per_day: int = 4):
        if mode not in ("warn", "act"):
            raise ValueError(f"unknown supervisor mode {mode!r}; "
                             "expected warn | act")
        if cooldown_s < 0:
            raise ValueError(
                f"supervisor cooldown_s must be >= 0, got {cooldown_s}")
        if evict_budget_per_day < 0:
            raise ValueError(
                "supervisor evict_budget_per_day must be >= 0 "
                f"(0 = unlimited), got {evict_budget_per_day}")
        self.mode = mode
        self.cooldown_s = float(cooldown_s)
        self.evict_budget_per_day = int(evict_budget_per_day)
        self.path = Path(output_dir) / FILENAME
        self._lock = threading.Lock()
        #: serialises _write() — on_verdict (drain thread) and
        #: mark_acted (loop thread) both publish the same tmp file, and
        #: interleaved truncating writes would garble the one artifact
        #: the relauncher consults. Separate from _lock: _write calls
        #: state(), which takes _lock itself
        self._write_lock = threading.Lock()
        self._pending: dict[str, Any] | None = None
        self._delivered = False
        self.decisions: list[dict[str, Any]] = []
        #: acted stopping decisions from PRIOR attempts' ledger
        #: (``(time, action)`` pairs) — what cooldown/budget meter
        self._prior_stops: list[tuple[float, str]] = self._load_prior_stops()

    def _load_prior_stops(self) -> list[tuple[float, str]]:
        """Best-effort read of the previous attempts' acted stopping
        decisions from the ledger on disk; a missing or corrupt file is
        a fresh history, never an error."""
        try:
            if not self.path.is_file():
                return []
            doc = json.loads(self.path.read_text())
            # older attempts' stops ride the ledger's own stop_history
            # (each attempt rewrites the file; the history key is how a
            # third attempt still sees the first one's evictions)
            stops = [
                (float(t), str(a))
                for t, a in doc.get("stop_history", [])
                if isinstance(t, (int, float)) and a in _STOPPING
            ]
            stops += [
                (float(d.get("time", 0.0)), str(d.get("action")))
                for d in doc.get("decisions", [])
                if d.get("acted") and d.get("action") in _STOPPING
                and isinstance(d.get("time"), (int, float))
            ]
            # bound the carried history: nothing older than the 24h
            # budget window matters once the cooldown has also lapsed
            horizon = time.time() - 2 * _DAY_S
            return sorted((t, a) for t, a in stops if t >= horizon)
        except Exception:  # noqa: BLE001 - policy must not kill startup
            log.exception("supervisor.json unreadable; hysteresis "
                          "starts with a fresh history")
            return []

    def _all_stops(self) -> list[tuple[float, str]]:
        """Acted stopping decisions, prior attempts + this one; call
        under ``self._lock``."""
        return self._prior_stops + [
            (float(d["time"]), d["action"]) for d in self.decisions
            if d["acted"] and d["action"] in _STOPPING]

    def _hysteresis_veto(self, action: str, now: float) -> str | None:
        """Why ``action`` may not claim the stop right now, or None.
        Call under ``self._lock``."""
        stops = self._all_stops()
        if self.cooldown_s > 0 and stops:
            last = max(t for t, _ in stops)
            if now - last < self.cooldown_s:
                return "cooldown"
        if action == "evict" and self.evict_budget_per_day > 0:
            recent = sum(1 for t, a in stops
                         if a == "evict" and now - t < _DAY_S)
            if recent >= self.evict_budget_per_day:
                return "budget"
        return None

    # -- drain-thread side -------------------------------------------------
    def on_verdict(self, kind: str, step: int,
                   verdict: dict[str, Any] | None = None) -> None:
        """Feed one confirmed verdict; safe from any thread, never
        raises. The first verdict whose action stops the run claims the
        pending slot (the engine's next poll executes it); every
        verdict is recorded in the decision log regardless."""
        try:
            action = VERDICT_ACTIONS.get(kind, "observe")
            scalars = dict(verdict or {})
            host = scalars.get("host")
            now = time.time()
            decision = {
                "kind": kind,
                "action": action,
                "step": int(step),
                "host": int(host) if host is not None else None,
                "mode": self.mode,
                "acted": False,
                "time": now,
                "suppressed": None,
                "verdict": scalars,
            }
            claim = False
            suppressed = None
            with self._lock:
                if action in _STOPPING:
                    suppressed = self._hysteresis_veto(action, now)
                    if suppressed is not None:
                        decision["action"] = "observe"
                        decision["suppressed"] = suppressed
                self.decisions.append(decision)
                if (decision["action"] in _STOPPING
                        and self._pending is None):
                    claim = True
                    self._pending = decision
            if claim:
                log.warning(
                    "supervisor: %s verdict at step %d -> %s%s (%s mode)",
                    kind, int(step), action,
                    f" host {int(host)}" if host is not None else "",
                    self.mode)
            elif suppressed is not None:
                log.warning(
                    "supervisor: %s verdict at step %d would %s but the "
                    "%s guard vetoed it (%s) — recorded observe-only",
                    kind, int(step), action, suppressed,
                    "a stop landed inside the cooldown window"
                    if suppressed == "cooldown" else
                    f"{self.evict_budget_per_day} acted evictions in the "
                    "trailing 24h exhaust the budget")
            elif action == "observe":
                log.info(
                    "supervisor: %s verdict at step %d recorded "
                    "(action table says observe-only)", kind, int(step))
            self._write()
        except Exception:  # noqa: BLE001 - policy must not kill telemetry
            log.exception("supervisor verdict handling failed")

    # -- loop side ---------------------------------------------------------
    def poll(self) -> dict[str, Any] | None:
        """The pending stopping decision, exactly once (later polls
        return None) — an attribute read + lock, safe every iteration."""
        if self._pending is None or self._delivered:
            return None
        with self._lock:
            if self._pending is None or self._delivered:
                return None
            self._delivered = True
            return dict(self._pending)

    def mark_acted(self, decision: dict[str, Any]) -> None:
        """The engine reports the action executed (act mode): the
        decision log and the durable ``supervisor.json`` record it —
        the artifact the relauncher and the operator read."""
        with self._lock:
            for d in self.decisions:
                # full identity: one window can carry SAME-step same-kind
                # verdicts for different hosts (two stragglers behind one
                # sick switch) — only the executed decision may be marked,
                # or eviction() hands the relauncher the wrong host
                if (d["step"] == decision["step"]
                        and d["kind"] == decision["kind"]
                        and d["host"] == decision.get("host")
                        and not d["acted"]):
                    d["acted"] = True
                    break
        self._write()

    # -- reporting ---------------------------------------------------------
    def eviction(self) -> dict[str, Any] | None:
        """The active eviction plan (the acted evict decision), or
        None — what a relauncher consults to drop the sick host."""
        with self._lock:
            for d in reversed(self.decisions):
                if d["action"] == "evict" and d["acted"]:
                    return {"host": d["host"], "step": d["step"],
                            "kind": d["kind"]}
        return None

    def state(self) -> dict[str, Any]:
        """JSON-ready snapshot for ``/status``."""
        with self._lock:
            return {
                "mode": self.mode,
                "cooldown_s": self.cooldown_s,
                "evict_budget_per_day": self.evict_budget_per_day,
                "decisions": [dict(d) for d in self.decisions],
                "pending": (dict(self._pending)
                            if self._pending is not None else None),
                "acted": any(d["acted"] for d in self.decisions),
                "suppressed_total": sum(
                    1 for d in self.decisions if d.get("suppressed")),
            }

    def _write(self) -> None:
        """Persist the decision log (host 0, atomic, best-effort)."""
        if not is_main_process():
            return
        try:
            with self._write_lock:
                with self._lock:
                    history = list(self._prior_stops)
                payload = {
                    "schema": "supervisor/v1",
                    **self.state(),
                    "stop_history": history,
                    "eviction": self.eviction(),
                    "note": "decisions the supervisor took (act) or "
                            "would have taken (warn); `eviction` is the "
                            "plan a relauncher consults to resume on "
                            "the healthy subset",
                }
                self.path.parent.mkdir(parents=True, exist_ok=True)
                tmp = self.path.with_suffix(".json.tmp")
                tmp.write_text(json.dumps(json_sanitize(payload),
                                          indent=2, allow_nan=False))
                tmp.replace(self.path)
        except Exception:  # noqa: BLE001
            log.exception("supervisor.json write failed")


# -- deterministic fault injection ----------------------------------------

FAULT_KINDS = ("crash", "hang-host", "corrupt-hot-snapshot", "slow-host")


class FaultInjector:
    """Parse and fire ``--inject_fault kind:step[:param]`` — the
    deterministic harness behind the elastic tests and
    ``BENCH_MODE=elastic``. One injector per process; ``maybe_fire``
    is called once per loop iteration AFTER that step's save blocks
    (so a ``crash`` at step N leaves step N's hot snapshot durable —
    the scenario the hot tier exists for)."""

    def __init__(self, kind: str, step: int, param: float | None = None):
        self.kind = kind
        self.step = int(step)
        self.param = param
        self._slow_active = False

    @classmethod
    def parse(cls, spec: str | None) -> "FaultInjector | None":
        """``kind:step[:param]`` -> injector; None/empty -> None; a
        malformed spec raises with the grammar named (config
        validation calls this, so ``--inject_fault`` typos fail at
        parse time)."""
        if not spec:
            return None
        parts = str(spec).split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"--inject_fault {spec!r}: expected kind:step[:param] "
                f"with kind one of {', '.join(FAULT_KINDS)}")
        kind = parts[0]
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"--inject_fault kind {kind!r} unknown; expected one of "
                f"{', '.join(FAULT_KINDS)}")
        try:
            step = int(parts[1])
        except ValueError:
            raise ValueError(
                f"--inject_fault {spec!r}: step must be an integer")
        if step < 1:
            raise ValueError(
                f"--inject_fault {spec!r}: step must be >= 1")
        param = None
        if len(parts) == 3:
            try:
                param = float(parts[2])
            except ValueError:
                raise ValueError(
                    f"--inject_fault {spec!r}: param must be a number")
        return cls(kind, step, param)

    def maybe_fire(self, step: int, *, hot=None) -> None:
        """Fire when ``step`` reaches the injection point. ``slow-host``
        keeps firing (a per-step sleep from its step on); the other
        kinds are one-shots."""
        if self.kind == "slow-host":
            if step >= self.step:
                if not self._slow_active:
                    self._slow_active = True
                    log.warning(
                        "fault injection: slow-host active from step %d "
                        "(+%.3fs per step) — this host should be named "
                        "by the fleet straggler attribution", step,
                        self.param or 0.25)
                time.sleep(self.param if self.param is not None else 0.25)
            return
        if step != self.step:
            return
        if self.kind == "crash":
            log.error(
                "fault injection: hard crash at step %d (os._exit — no "
                "atexit, no final save; the newest hot snapshot / "
                "durable step is the recovery point)", step)
            os._exit(137)
        if self.kind == "hang-host":
            log.error(
                "fault injection: hanging this host at step %d (the "
                "fleet layer should see the missing window; kill and "
                "resume on the healthy subset)", step)
            while True:  # pragma: no cover - a deliberate wedge
                time.sleep(60)
        if self.kind == "corrupt-hot-snapshot":
            if hot is None:
                log.warning(
                    "fault injection: corrupt-hot-snapshot at step %d "
                    "but --hot_save_steps is off — nothing to corrupt",
                    step)
            else:
                hot.corrupt_latest()
