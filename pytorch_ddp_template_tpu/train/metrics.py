"""Metrics emission: TensorBoard + JSONL, main-process only.

Capability parity with the reference's TB block
(``/root/reference/ddp.py:36-39, 128-129, 246-252``): ``lr`` and windowed
mean ``loss`` scalars every ``logging_steps``, written by the main process
only. Two fixes over the reference:

- the reference's loss window divides by ``logging_steps`` while
  accumulating per *micro*-batch, mis-scaling the reported loss whenever
  ``gradient_accumulation_steps > 1`` (SURVEY.md §2d); here the window is a
  true mean over optimizer steps (accumulation is inside the jitted step).
- scalars also go to a ``metrics.jsonl`` file, so runs are machine-readable
  without TB and the bench harness can consume them directly.

On top of the writer sit the telemetry sinks the train loop emits into:

- :class:`AsyncTelemetry` (default) accepts *device arrays* and drains them
  on a background thread via ``jax.device_get`` — emitting at a logging
  boundary never blocks the loop on the in-flight step, so ``logging_steps``
  stops being a hidden host-sync cadence. Scalars may therefore land in
  TB/JSONL up to one interval after their step; step keys are unchanged.
- :class:`SyncTelemetry` (``--telemetry sync``) reproduces the pre-async
  behaviour — inline host conversion, blocking on the in-flight step — and
  exists as the measured "before" leg of ``host_overhead_pct`` in
  ``BENCH_MODE=e2e`` (BENCH.md).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from collections.abc import Mapping
from pathlib import Path
from typing import Any, Callable

from ..utils import get_logger, is_main_process
from ..utils.serialization import json_sanitize

log = get_logger(__name__)

#: ``metrics.jsonl`` record schema version, stamped on every record so
#: ``tools/bench_diff.py`` and external scrapers can evolve safely.
#: History: v1 = the pre-r14 implicit schema (step/time + flat floats,
#: non-finite as ``null``+``"<key>_repr"``, vectors JSONL-only);
#: v2 = v1 plus this very field. Bump when a record's MEANING changes,
#: not when fields are added (additive keys are always legal).
SCHEMA_VERSION = 2


class MetricsWriter:
    """Host-0 scalar writer: TensorBoard events (if available) + JSONL.

    JSONL values may be scalars or flat lists (the r12 health pack's
    ``per_layer_grad_norm`` vector); lists go to JSONL only (TensorBoard
    scalars are scalars). Non-finite values are serialised as ``null``
    with the original spelling in a ``"<key>_repr"`` sibling
    (``utils/serialization.json_sanitize``): the anomaly sentry
    intentionally surfaces NaNs, and ``json.dumps``'s bare ``NaN`` token
    would break every downstream JSON parser on exactly the record that
    matters most."""

    def __init__(self, directory: str | Path):
        self.active = is_main_process()
        self._tb = None
        if not self.active:
            return
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self._jsonl = (self.directory / "metrics.jsonl").open("a", buffering=1)
        try:  # tensorboard is optional; JSONL is the always-on channel
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=str(self.directory))
        except Exception:  # noqa: BLE001
            log.info("tensorboard unavailable; writing JSONL metrics only")

    def write(self, step: int, scalars: dict[str, Any]) -> None:
        if not self.active:
            return
        record = {"step": step, "time": time.time(),
                  "schema_version": SCHEMA_VERSION}
        record.update({
            k: [float(x) for x in v] if isinstance(v, (list, tuple))
            else float(v)
            for k, v in scalars.items()
        })
        # allow_nan=False is the enforcement: a non-finite value that
        # somehow dodged the sanitiser raises HERE (and the telemetry
        # sink logs-and-drops) instead of corrupting the JSONL stream
        self._jsonl.write(json.dumps(json_sanitize(record),
                                     allow_nan=False) + "\n")
        if self._tb is not None:
            for k, v in scalars.items():
                if isinstance(v, (list, tuple)):
                    continue  # vectors are a JSONL-only channel
                self._tb.add_scalar(k, float(v), global_step=step)

    def close(self) -> None:
        if not self.active:
            return
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


def _fetch(v: Any):
    """Host-convert one value: device/host scalars → float, device/host
    VECTORS (the per-layer health channel) → flat list of floats."""
    import jax
    import numpy as np

    if isinstance(v, (jax.Array, np.ndarray)):
        arr = np.asarray(jax.device_get(v))
        return [float(x) for x in arr.ravel()] if arr.ndim else float(arr)
    return float(v)


def _to_host(scalars: dict[str, Any]) -> dict[str, float]:
    """Resolve an emitted record to host floats (blocking). Values may be:

    - a device array or host number → fetched/cast;
    - a list/tuple of either → fetched and MEANED (the loss window rides
      as raw per-step device scalars; the mean belongs on the drain
      thread, not as extra dispatches on the hot loop);
    - a zero-arg callable → called here, returning a float or a flat dict
      merged into the record (``StepTimer.summary`` percentiles are numpy
      work the hot loop should not pay).
    """
    out: dict[str, float] = {}
    for k, v in scalars.items():
        if callable(v):
            v = v()
        if isinstance(v, Mapping):
            out.update({k2: _fetch(v2) for k2, v2 in v.items()})
        elif isinstance(v, (list, tuple)):
            vals = [_fetch(x) for x in v]
            out[k] = sum(vals) / len(vals) if vals else 0.0
        else:
            out[k] = _fetch(v)
    return out


#: callback signature: (kind, step, host_scalars) — runs on whichever thread
#: performed the host conversion (the drain thread for AsyncTelemetry)
OnWrite = Callable[[str, int, dict[str, float]], None]

#: health-record consumer: (step, host_scalars) — the anomaly sentry's
#: ``observe``. ``kind="health"`` records route HERE instead of the
#: writer: they flow every step (the sentry's per-step feed) and would
#: otherwise multiply the metrics.jsonl volume by logging_steps; the
#: logging-boundary progress record carries the same fields durably.
OnHealth = Callable[[int, dict[str, Any]], None]

#: fleet-record consumer: (step, host_scalars) — the r14 fleet
#: watchtower's ``observe``. ``kind="fleet"`` records route HERE, never
#: to the writer: the cross-host allgather belongs on the drain thread
#: (it may block on a lagging peer), and the aggregated table is served
#: by the status endpoint rather than duplicated into metrics.jsonl
#: (the progress record already carries this host's raw signals).
OnFleet = Callable[[int, dict[str, Any]], None]

#: mem-record resolver: (step, scalars) -> flat record | None — the r15
#: memory watchtower's ``observe``. ``kind="mem"`` records route here
#: FIRST: the loop emits an empty marker at the perf cadence and the
#: drain thread does the ``device.memory_stats()`` poll (host-side PJRT
#: bookkeeping, still not the hot loop's business). Unlike health/fleet
#: the RESOLVED record then goes to the writer — the HBM watermark is a
#: durable low-cadence channel like ``perf``, not a per-step feed.
OnMem = Callable[[int, dict[str, Any]], "dict[str, Any] | None"]


class SyncTelemetry:
    """Inline sink: convert-and-write at emit time, blocking on the
    in-flight step. This is the pre-async loop behaviour, kept selectable
    (``--telemetry sync``) as the before-measurement for
    ``host_overhead_pct`` — it converts on every process (as the old loop
    did), not just where the writer is active."""

    def __init__(self, writer: MetricsWriter):
        self.writer = writer
        self.latest: dict[str, float] = {}
        self.on_write: OnWrite | None = None
        self.on_health: OnHealth | None = None
        self.on_fleet: OnFleet | None = None
        self.on_mem: OnMem | None = None

    def emit(self, step: int, scalars: dict[str, Any],
             kind: str = "progress") -> None:
        if kind == "health":
            # inline conversion, like everything else in sync mode: the
            # sentry still works, it just blocks on the in-flight step
            # (the async sink is the production path — BENCH_MODE=obs)
            if self.on_health is not None:
                self.on_health(step, _to_host(scalars))
            return
        if kind == "fleet":
            # inline exchange, same sync-mode contract: the allgather
            # blocks the loop here (async is the production path)
            if self.on_fleet is not None:
                self.on_fleet(step, _to_host(scalars))
            return
        if kind == "mem":
            # inline poll, same sync-mode contract; the resolved record
            # (when the monitor produced one) writes like any other
            if self.on_mem is None:
                return
            rec = self.on_mem(step, dict(scalars))
            if not rec:
                return
            scalars = rec
        host = _to_host(scalars)
        self.latest = host
        self.writer.write(step, host)
        if self.on_write is not None:
            self.on_write(kind, step, host)

    def close(self) -> None:
        pass


class AsyncTelemetry:
    """Background sink: ``emit`` enqueues device arrays and returns without
    touching them; a drain thread does the ``jax.device_get`` and the
    TB/JSONL writes. The hot loop therefore never blocks on a logging
    boundary — by the time the drain thread fetches a scalar, the step that
    produced it has long retired, so even the fetch is cheap.

    Delivery contract: every emitted record is written exactly once, in
    emission order, before :meth:`close` returns — including when training
    crashes (the trainer closes the sink in a ``finally``), so the final
    interval's scalars are never dropped. ``latest`` exposes the most
    recently drained record (used for the lagged tqdm postfix)."""

    _SENTINEL = object()

    def __init__(self, writer: MetricsWriter, *, maxsize: int = 256):
        self.writer = writer
        self.latest: dict[str, float] = {}
        self.on_write: OnWrite | None = None
        self.on_health: OnHealth | None = None
        self.on_fleet: OnFleet | None = None
        self.on_mem: OnMem | None = None
        # bounded: if the writer ever falls an entire queue behind, emit
        # blocks rather than growing host buffers without limit
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._closed = False
        # lazy: the drain thread starts on first emit, so a Trainer that
        # never logs (logging_steps=0, bench legs, eval-only) holds no
        # live thread to leak when it is dropped without close()
        self._thread: threading.Thread | None = None

    def emit(self, step: int, scalars: dict[str, Any],
             kind: str = "progress") -> None:
        if self._closed:  # late emit (e.g. from a finally): write inline
            self._write_one(kind, int(step), dict(scalars))
            return
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._drain, daemon=True, name="telemetry-drain"
            )
            self._thread.start()
        self._q.put((kind, int(step), dict(scalars)))

    def _write_one(self, kind: str, step: int, scalars: dict[str, Any]) -> None:
        if kind == "health":
            # per-step sentry feed: converted on this (drain) thread —
            # by now the producing step has retired, so the fetch is the
            # same deferred-cost contract as every other record — and
            # handed to the sentry, never to the writer (volume)
            if self.on_health is None:
                return
            try:
                self.on_health(step, _to_host(scalars))
            except Exception:  # noqa: BLE001 - sentry must not kill drain
                log.exception("health record dropped")
            return
        if kind == "fleet":
            # the r14 cross-host exchange: converted + allgathered on
            # this (drain) thread so a lagging peer can never stall the
            # hot loop; routed to the FleetMonitor, never to the writer
            if self.on_fleet is None:
                return
            try:
                self.on_fleet(step, _to_host(scalars))
            except Exception:  # noqa: BLE001 - fleet must not kill drain
                log.exception("fleet record dropped")
            return
        if kind == "mem":
            # the r15 HBM watermark: the device.memory_stats() poll runs
            # on this (drain) thread — the loop only emitted a cadence
            # marker. The monitor's resolved record (watermark, per-
            # device rows, frac-of-limit) then writes like a perf record
            if self.on_mem is None:
                return
            try:
                rec = self.on_mem(step, dict(scalars))
            except Exception:  # noqa: BLE001 - mem must not kill drain
                log.exception("mem record dropped")
                return
            if not rec:
                return
            scalars = rec
        if not self.writer.active and self.on_write is None:
            return  # non-main process: nothing consumes the conversion
        try:
            host = _to_host(scalars)
            self.latest = host
            self.writer.write(step, host)
            if self.on_write is not None:
                self.on_write(kind, step, host)
        except Exception:  # noqa: BLE001 - telemetry must never kill training
            log.exception("telemetry write failed (record dropped)")

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            self._write_one(*item)

    def close(self) -> None:
        """Flush everything queued, then stop the drain thread. Idempotent;
        safe to call from exception handlers — any records the thread did
        not get to are drained inline so nothing is lost."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._q.put(self._SENTINEL)
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                # drain thread wedged (hung filesystem / TB write): it
                # still owns the queue — draining here too would interleave
                # two writers and could swallow its sentinel, parking it on
                # q.get() forever. Leave the queue to it.
                log.error("telemetry drain thread did not stop within 60s; "
                          "queued records may be delayed")
                return
        while True:  # thread never started or died mid-queue: finish its work
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not self._SENTINEL:
                self._write_one(*item)


def make_telemetry(kind: str, writer: MetricsWriter) -> SyncTelemetry | AsyncTelemetry:
    if kind == "async":
        return AsyncTelemetry(writer)
    if kind == "sync":
        return SyncTelemetry(writer)
    raise ValueError(f"unknown telemetry mode {kind!r}; expected async|sync")
