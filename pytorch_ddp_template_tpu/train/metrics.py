"""Metrics emission: TensorBoard + JSONL, main-process only.

Capability parity with the reference's TB block
(``/root/reference/ddp.py:36-39, 128-129, 246-252``): ``lr`` and windowed
mean ``loss`` scalars every ``logging_steps``, written by the main process
only. Two fixes over the reference:

- the reference's loss window divides by ``logging_steps`` while
  accumulating per *micro*-batch, mis-scaling the reported loss whenever
  ``gradient_accumulation_steps > 1`` (SURVEY.md §2d); here the window is a
  true mean over optimizer steps (accumulation is inside the jitted step).
- scalars also go to a ``metrics.jsonl`` file, so runs are machine-readable
  without TB and the bench harness can consume them directly.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

from ..utils import get_logger, is_main_process

log = get_logger(__name__)


class MetricsWriter:
    """Host-0 scalar writer: TensorBoard events (if available) + JSONL."""

    def __init__(self, directory: str | Path):
        self.active = is_main_process()
        self._tb = None
        if not self.active:
            return
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self._jsonl = (self.directory / "metrics.jsonl").open("a", buffering=1)
        try:  # tensorboard is optional; JSONL is the always-on channel
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=str(self.directory))
        except Exception:  # noqa: BLE001
            log.info("tensorboard unavailable; writing JSONL metrics only")

    def write(self, step: int, scalars: dict[str, Any]) -> None:
        if not self.active:
            return
        record = {"step": step, "time": time.time()}
        record.update({k: float(v) for k, v in scalars.items()})
        self._jsonl.write(json.dumps(record) + "\n")
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, float(v), global_step=step)

    def close(self) -> None:
        if not self.active:
            return
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
