"""The training engine: jitted SPMD train step + orchestration loop.

Capability parity with the reference's ``train`` (``/root/reference/
ddp.py:126-288``), redesigned for XLA rather than translated:

- The reference's hot loop is Python: forward (``ddp.py:221``), loss scale
  for accumulation (``:227-228``), ``loss.backward()`` with DDP's bucketed
  NCCL allreduce (``:231``), clip (``:238-239``), ``optimizer.step()``
  (``:240``), scheduler (``:241``). Here that *entire* sequence — forward,
  backward, cross-replica gradient mean, clip-by-global-norm, SGD update,
  schedule — is one jitted function. XLA fuses it and overlaps the ICI
  collectives with backward compute (what DDP's bucketing hand-builds).
- Gradient accumulation runs *inside* jit via ``lax.scan`` over a leading
  microbatch axis (no recompilation, no Python-loop dispatch overhead),
  preserving the reference's clip-AFTER-accumulate ordering
  (``ddp.py:237-242``, SURVEY.md §7 hard part (b)).
- The cross-replica gradient mean needs no explicit ``psum``: the batch is
  sharded over the ``data`` mesh axis and params are replicated, so GSPMD
  inserts the reduce — ``lax.psum`` semantics without naming it (the whole
  NCCL-DDP replacement, SURVEY.md §5.8).

Steady-state host discipline (the async-dispatch contract): the loop never
converts a device value to host inline. Scalars for ``logging_steps`` go to
a telemetry sink as device arrays (drained off-thread); the multi-process
preemption-stop agreement is a device-side reduction over per-process stop
votes *inside* the jitted step (no ``process_allgather`` cadence); the only
blocking point is the bounded dispatch-depth barrier — one host read per
iteration of a scalar produced ``--max_inflight_steps`` dispatches ago,
which in steady state has already retired and costs ~nothing.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..checkpoint.manager import CheckpointManager
from ..config import TrainingConfig
from ..data.loader import ShardedLoader
from ..models.task import Task
from ..runtime.context import DATA_AXIS, RuntimeContext
from ..utils import get_logger, is_main_process
from ..obs.goodput import GoodputLedger
from ..obs.health import HEALTH_KEYS
from ..utils.divergence import DivergenceMonitor
from ..utils.profiler import StepTimer, TraceWindow, annotate
from .metrics import MetricsWriter, SyncTelemetry, make_telemetry
from .schedule import SCHEDULES

log = get_logger(__name__)

#: the per-step scalars handed to the anomaly sentry (``kind="health"``
#: telemetry records): loss/grad_norm for the spike detector plus the
#: whole health pack for the flight-record ring buffer
SENTRY_FEED_KEYS = ("loss", "grad_norm") + HEALTH_KEYS


class TrainState(flax.struct.PyTreeNode):
    """Replicated training state. ``extra_vars`` holds non-param collections
    (e.g. BatchNorm ``batch_stats``); ``rng`` is the shared base key.

    ``comm_residual`` (``--grad_error_feedback``) is the per-replica
    gradient-compression residual — NOT replicated: leaves are
    ``(num_layers, data_size, padded)`` sharded over ``data`` on dim 1
    (``parallel/compress.py``). It is the one field the backward pass
    writes: the compressed per-layer reduce returns the updated residual
    through its primal input's cotangent slot, and ``step_fn`` threads
    that cotangent back in here. ``None`` whenever error feedback is off
    (the default), in which case checkpoints are byte-compatible with
    pre-residual ones (``checkpoint/manager.py`` stores the residual as
    a separate item)."""

    step: jax.Array
    params: Any
    extra_vars: Any
    opt_state: Any
    rng: jax.Array
    comm_residual: Any = None


def make_optimizer(config: TrainingConfig, total_steps: int) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """clip_by_global_norm → optimizer(warmup-linear) as one optax chain.

    Default matches the reference's update rule (clip ``ddp.py:238-239``,
    ``optim.SGD(lr=1e-3)`` ``ddp.py:183``, schedule ``ddp.py:52-61``).
    The adaptive family replaces the reference's fp16 FusedAdam path,
    which never ran (unimported ``FusedSGD`` NameError, SURVEY.md §2d).
    Optimizer state (momentum/adam moments) mirrors the param tree, so
    ``parallel.shard_tree`` places it with the params' shardings under
    tensor parallelism."""
    schedule = SCHEDULES[config.lr_schedule](
        config.learning_rate, config.warmup_steps, total_steps
    )
    # standard decay mask for the weight-decaying family: norms/biases/
    # other 1-D params are excluded (decaying a LayerNorm scale toward 0
    # fights the normalisation; every major transformer recipe masks these)
    decay_mask = lambda params: jax.tree.map(lambda p: p.ndim > 1, params)
    kind = config.optimizer
    if kind == "sgd":
        opt = optax.sgd(learning_rate=schedule)
    elif kind == "momentum":
        opt = optax.sgd(learning_rate=schedule, momentum=config.momentum)
    elif kind == "adam":
        opt = optax.adam(learning_rate=schedule, b1=config.adam_beta1,
                         b2=config.adam_beta2, eps=config.adam_eps)
    elif kind == "adamw":
        opt = optax.adamw(learning_rate=schedule, b1=config.adam_beta1,
                          b2=config.adam_beta2, eps=config.adam_eps,
                          weight_decay=config.weight_decay, mask=decay_mask)
    elif kind == "lamb":
        # layerwise-adaptive family (this and lars): the standard recipe
        # for the very large global batches a TPU pod makes cheap, where
        # plain SGD/Adam need impractical LR tuning. --adam_eps applies
        # here too (config over optax's 1e-6 default, same as adam/adamw).
        opt = optax.lamb(learning_rate=schedule, b1=config.adam_beta1,
                         b2=config.adam_beta2, eps=config.adam_eps,
                         weight_decay=config.weight_decay, mask=decay_mask)
    elif kind == "lars":
        opt = optax.lars(learning_rate=schedule, momentum=config.momentum,
                         weight_decay=config.weight_decay,
                         weight_decay_mask=decay_mask)
    else:
        raise ValueError(f"unknown optimizer {kind!r}")
    tx = optax.chain(
        optax.clip_by_global_norm(config.max_grad_norm),
        opt,
    )
    return tx, schedule


def make_stop_flags(mesh: jax.sharding.Mesh, flag: bool) -> jax.Array:
    """Per-process preemption votes as a device array, one int32 element per
    device (this process writes ``flag`` to each of its local devices).
    ``jnp.max`` over it inside the jitted step is the cross-process stop
    agreement — GSPMD emits the all-reduce, no host collective exists."""
    sharding = NamedSharding(mesh, P(mesh.axis_names))
    val = np.asarray([1 if flag else 0], dtype=np.int32)
    arrays = [jax.device_put(val, d) for d in mesh.local_devices]
    return jax.make_array_from_single_device_arrays(
        (mesh.devices.size,), sharding, arrays
    )


def make_train_step(
    task: Task,
    tx: optax.GradientTransformation,
    schedule: optax.Schedule,
    accum_steps: int = 1,
    with_stop: bool = False,
    health: bool = False,
) -> Callable[..., tuple[TrainState, dict[str, jax.Array]]]:
    """Build the jitted SPMD train step.

    ``health=True`` (the default production Trainer path, ``--health_pack``)
    extends the step metrics with the device-side health bundle
    (``obs/health.py``: param norm, update ratio, non-finite counts,
    per-layer grad norms for scanned stacks, EF-residual norm) — a few
    fused reductions computed where the operands already live, drained
    through the telemetry channel like every other metric: zero extra
    host syncs. Default False so direct callers (bench parity legs,
    tests) keep their metric trees bit-stable.

    ``with_stop=True`` (multi-process runs) adds a third argument — the
    :func:`make_stop_flags` votes array — and a ``stop_agreed`` entry in
    the metrics: the device-side reduction of the fleet's preemption
    votes. The votes array is NOT donated: the trainer prebuilds one
    array per flag value and re-passes it every step, so the steady state
    pays zero per-step H2D transfers. The loop reads the agreement
    through the bounded dispatch-depth barrier, so stop agreement costs
    zero blocking host collectives (the old ``--preempt_sync_steps``
    allgather cadence).

    Batch layout: ``(global_batch, ...)`` sharded over ``data`` when
    ``accum_steps == 1``; ``(accum, micro, ...)`` sharded over ``data`` on
    the micro dim otherwise (see ``ShardedLoader``).

    Sharding contract: shardings live on the *data* — the state arrives
    sharded from ``Trainer.init_state`` (replicated for pure DDP; weights
    split over ``model`` under tensor parallelism via
    ``parallel.sharding``), batches arrive sharded from ``ShardedLoader``
    (``data`` batch dim, optionally ``seq`` for context parallelism), and
    jit compiles for whatever it receives. GSPMD then propagates: grads
    and optimizer updates inherit param shardings, batch reductions emit
    the cross-replica psum (the NCCL-DDP replacement, SURVEY.md §5.8).
    """

    def loss_fn(params, extra_vars, batch, rng):
        loss, new_extra, metrics = task.loss(params, extra_vars, batch, rng, train=True)
        return loss, (new_extra, metrics)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step_fn(state: TrainState, batch: dict[str, jax.Array],
                stop_flags: jax.Array | None = None):
        rng = jax.random.fold_in(state.rng, state.step)
        # static pytree-structure property: error feedback is on exactly
        # when the state carries a residual tree
        ef = getattr(state, "comm_residual", None) is not None
        new_residual = state.comm_residual if ef else None

        if accum_steps == 1:
            if ef:
                # the compressed per-layer reduce updates the residual in
                # BACKWARD; the only in-jit channel for backward-produced
                # state is a cotangent, so the residual rides into the
                # model as the "comm_residual" collection and its updated
                # value comes back as that input's "gradient"
                # (parallel/compress.py module docstring)
                ev_in = {**state.extra_vars,
                         "comm_residual": state.comm_residual}
                (loss, (new_extra, metrics)), (grads, ev_ct) = (
                    jax.value_and_grad(loss_fn, argnums=(0, 1),
                                       has_aux=True)(
                        state.params, ev_in, batch, rng))
                new_residual = ev_ct["comm_residual"]
                new_extra = {k: v for k, v in dict(new_extra).items()
                             if k != "comm_residual"}
            else:
                (loss, (new_extra, metrics)), grads = grad_fn(
                    state.params, state.extra_vars, batch, rng
                )
        else:
            if ef:
                # sequential EF semantics (each microbatch compensates the
                # previous one's residual) cannot ride the accumulation
                # scan; config.__post_init__ refuses the combination, this
                # guards direct make_train_step users
                raise ValueError(
                    "--grad_error_feedback does not compose with "
                    "gradient accumulation; see config.py"
                )
            # lax.scan over microbatches: sum grads, thread extra_vars
            # (BatchNorm stats advance per microbatch, like the reference's
            # sequential micro-steps).
            def body(carry, inputs):
                i, microbatch = inputs
                grad_sum, extra = carry
                # distinct dropout mask per microbatch, like the reference's
                # sequential micro-steps advancing torch's global RNG
                (loss, (new_extra, metrics)), grads = grad_fn(
                    state.params, extra, microbatch, jax.random.fold_in(rng, i)
                )
                grad_sum = jax.tree.map(jnp.add, grad_sum, grads)
                return (grad_sum, new_extra), (loss, metrics)

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            (grad_sum, new_extra), (losses, metrics) = jax.lax.scan(
                body,
                (zero_grads, state.extra_vars),
                (jnp.arange(accum_steps), batch),
            )
            # mean over microbatches == the reference's loss/accum scaling
            # (ddp.py:227-228) applied to grads after accumulation
            grads = jax.tree.map(lambda g: g / accum_steps, grad_sum)
            loss = jnp.mean(losses)
            metrics = jax.tree.map(jnp.mean, metrics)

        grad_norm = optax.global_norm(grads)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            extra_vars=new_extra,
            opt_state=new_opt_state,
            comm_residual=new_residual,
        )
        out_metrics = dict(metrics)
        # tasks report the pure data loss in metrics (comparable with eval
        # curves); the differentiated total may add regularisers (aux_loss)
        out_metrics.setdefault("loss", loss)
        out_metrics["grad_norm"] = grad_norm
        out_metrics["lr"] = schedule(state.step)
        if health:
            from ..obs.health import health_metrics

            out_metrics.update(health_metrics(
                loss=loss, grads=grads, params=state.params,
                updates=updates, residual=new_residual))
        if stop_flags is not None:
            # device-side stop agreement: OR of every process's vote.
            # Replicated output — each host reads the identical value, so
            # all hosts observing it at the same lagged iteration take the
            # identical stop decision at the identical global_step.
            out_metrics["stop_agreed"] = jnp.max(stop_flags)
        return new_state, out_metrics

    return jax.jit(step_fn, donate_argnums=(0,))


def make_eval_step(task: Task):
    """Jitted eval step: loss/metrics only, no mutation (the reference's
    ``evaluate`` is a stub, ``ddp.py:123-124`` — this one is real)."""

    def step_fn(state: TrainState, batch):
        loss, _, metrics = task.loss(
            state.params, state.extra_vars, batch, None, train=False
        )
        out = dict(metrics)
        out["loss"] = loss
        return out

    return jax.jit(step_fn)


class Trainer:
    """Orchestrates epochs/steps/logging/checkpointing around the jitted step."""

    def __init__(self, config: TrainingConfig, ctx: RuntimeContext, task: Task,
                 dataset, eval_dataset=None):
        self.config = config
        self.ctx = ctx
        self.task = task
        self.dataset = dataset
        self.eval_dataset = eval_dataset
        self.loader = ShardedLoader(
            dataset,
            ctx.mesh,
            config.train_batch_size * config.gradient_accumulation_steps,
            seed=config.seed,
            accum_steps=config.gradient_accumulation_steps,
            seq_dims=getattr(task, "seq_dims", None),
        )
        # Step accounting (reference: t_total math ddp.py:154-161). One
        # loader batch == one optimizer step, so the reference's
        # microbatch/accum bookkeeping collapses.
        steps_per_epoch = self.loader.steps_per_epoch
        if steps_per_epoch == 0:
            raise ValueError("dataset smaller than one global batch")
        if config.max_steps > 0:
            self.total_steps = config.max_steps
            self.num_epochs = -(-config.max_steps // steps_per_epoch)
        else:
            self.total_steps = int(steps_per_epoch * config.num_train_epochs)
            self.num_epochs = -(-self.total_steps // steps_per_epoch)
        self.steps_per_epoch = steps_per_epoch

        self.tx, self.schedule = make_optimizer(config, self.total_steps)
        # multi-process runs carry the preemption-stop agreement inside the
        # jitted step (device-side reduction of per-process votes);
        # single-process runs keep the two-arg signature and act on the
        # local flag directly — no device work for a host-local decision
        self._with_stop = jax.process_count() > 1
        # prebuilt per-flag vote arrays (built on first use): the votes
        # input is re-passed, never donated, so the hot loop performs no
        # per-step H2D transfer for stop agreement
        self._stop_votes: dict[bool, jax.Array] = {}
        self.train_step = make_train_step(
            task, self.tx, self.schedule, config.gradient_accumulation_steps,
            with_stop=self._with_stop, health=config.health_pack,
        )
        self.eval_step = make_eval_step(task)
        self.ckpt = CheckpointManager(
            config.output_dir,
            max_to_keep=config.keep_checkpoints or None,
        )
        # hot-checkpoint tier (--hot_save_steps, checkpoint/hot.py):
        # fast local-disk snapshots layered under the durable orbax
        # saves; restore prefers the newest VALID hot generation over
        # an older durable step. Built whenever the flag is on OR a
        # prior attempt left snapshots behind (a restart without the
        # flag must still restore from the freshest state available)
        self.hot = None
        from ..checkpoint.hot import DIRNAME as HOT_DIRNAME
        from ..checkpoint.hot import HotCheckpointManager

        if config.hot_save_steps or (Path(config.output_dir)
                                     / HOT_DIRNAME).is_dir():
            self.hot = HotCheckpointManager(config.output_dir)
        # supervisor policy (--supervise, train/supervisor.py): the
        # drain-thread verdict feeds (straggler/mem_pressure/regression)
        # queue decisions; the loop polls and, in act mode, executes
        # checkpoint -> evict -> coordinated stop
        self.supervisor = None
        if config.supervise != "off":
            from .supervisor import Supervisor

            self.supervisor = Supervisor(
                config.supervise, config.output_dir,
                cooldown_s=config.supervise_cooldown_s,
                evict_budget_per_day=config.supervise_evict_budget)
        # deterministic fault injection (--inject_fault): the elastic
        # test harness; fires in the loop after the save blocks
        from .supervisor import FaultInjector

        self.fault = FaultInjector.parse(config.inject_fault)
        self._supervisor_stop = False
        self.metrics_writer = MetricsWriter(config.output_dir)
        self.telemetry = make_telemetry(config.telemetry, self.metrics_writer)
        # shared with bench.py's e2e full-loop leg: steady-state step-time
        # percentiles with side-work intervals discarded
        self.step_timer = StepTimer()
        # hot-save discard cooldown: the snapshot's blocking device_get
        # drains the dispatch pipeline and its local-disk write keeps
        # bleeding (OS writeback competes with compute — measurable on
        # the CPU backend) for about one interval after the save
        # returns, so the save interval AND the next are not
        # steady-state step times
        self._hot_discard = 0
        self.divergence = DivergenceMonitor(lag=max(config.max_inflight_steps, 1))
        # anomaly sentry + flight recorder (--anomaly warn|halt): the
        # sentry consumes the per-step health feed ON the telemetry drain
        # thread (kind="health" records route to on_health, never to the
        # writer); the loop polls its trigger once per iteration. Every
        # process runs its own sentry over the replicated scalars — the
        # halt agreement still travels device-side, so a lone divergent
        # host cannot split the fleet's stop decision.
        self.sentry = None
        self.recorder = None
        if config.anomaly != "off":
            from ..obs.sentry import AnomalySentry, FlightRecorder

            self.sentry = AnomalySentry(
                config.anomaly, window=config.anomaly_window,
                threshold=config.anomaly_threshold)
            self.telemetry.on_health = self.sentry.observe
            self.recorder = FlightRecorder(config.output_dir)
        # halt machinery: _halt_vote feeds the device-side stop agreement
        # (multi-process) / the local stop check (single-process) once the
        # post-trigger flight trace has its steps; _flight_trace is armed
        # by the trigger handler and stepped by the loop
        self._halt_vote = False
        self._halt_at_step: int | None = None
        self._flight_trace: TraceWindow | None = None
        # goodput ledger (obs/goodput.py): always on — host-side float
        # adds per iteration + one JSON write per perf interval. Loads
        # any prior attempt's buckets from <output_dir>/goodput.json so
        # a preempted-and-restarted run reports TRUE end-to-end goodput
        self.goodput = GoodputLedger(config.output_dir)
        # fleet watchtower (--fleet, obs/fleet.py): the loop emits this
        # host's window as a kind="fleet" telemetry record at the perf
        # cadence; the DRAIN thread allgathers + aggregates and, on a
        # sustained straggler, feeds the sentry a `straggler` trigger
        self.fleet = None
        if config.fleet:
            from ..obs.fleet import FleetMonitor

            self.fleet = FleetMonitor(
                threshold=config.straggler_threshold,
                windows=config.straggler_windows,
                on_straggler=self._on_straggler)
            self.telemetry.on_fleet = self.fleet.observe
        # live status endpoint (--status_port, obs/server.py): built and
        # started in train() (it serves run-scoped state), closed in the
        # crash-safe finally; None = off
        self.status = None
        # perf-regression tripwire (obs/regression.py): the prior
        # attempt's steady-state fingerprint loads here; the first perf
        # snapshot with enough steady samples compares against it, and
        # the end of the run writes this attempt's fingerprint
        from ..obs.regression import PerfBaseline

        self.baseline = PerfBaseline(config.output_dir)
        self._baseline_checked = False
        self._last_perf_rec: dict[str, float] = {}
        # goodput totals at the last fleet window (the window ships
        # bucket DELTAS for THIS attempt, not lifetime totals — snapshot
        # the prior attempts' baggage now)
        self._fleet_gp_mark: dict[str, float] = self.goodput.totals()
        # perf attribution (--perf_report): built by _startup_reports
        # from the shared AOT compile; None = no attribution records
        self.perf = None
        # memory X-ray (--mem_report, obs/memory.py): compile-time
        # split + donation audit ride _startup_reports; the runtime
        # watermark poller runs on the telemetry drain thread
        # (kind="mem" records at the perf cadence); the capacity
        # tripwire feeds the sentry as a mem_pressure trigger
        self.memory = None
        if config.mem_report:
            from ..obs.memory import MemoryMonitor

            self.memory = MemoryMonitor(
                ctx.mesh.local_devices,
                budget_frac=config.mem_budget_frac,
                on_pressure=self._on_mem_pressure)
            self.telemetry.on_mem = self.memory.observe
        # mid-run retrace detection (goodput `compile` bucket + the
        # shape-change warning): the jit cache grows exactly when a
        # dispatch traced+compiled a new executable
        self._jit_cache_size = 0
        # side-work durations measured where they happen, consumed by
        # the next timer tick's goodput split (the tick interval is the
        # wall-clock they are part of)
        self._pending: dict[str, float] = {
            "compile": 0.0, "checkpoint_save": 0.0,
            "hot_checkpoint_save": 0.0, "eval": 0.0, "other": 0.0}
        # cumulative loop time spent blocked in the dispatch-depth
        # barrier's fence read — the device-wait measure the perf
        # attribution splits into compute vs comm
        self._device_wait_s = 0.0

    # -- state ------------------------------------------------------------
    def init_state(self) -> TrainState:
        example = next(iter(self.loader.epoch(0)))
        if self.config.gradient_accumulation_steps > 1:
            example = jax.tree.map(lambda x: x[0], example)
        params, extra = self.task.init(self.ctx.seed_key, example)
        opt_state = self.tx.init(params)
        # the error-feedback residual inits as a model collection (the
        # encoder declares it, so the collection path is pathed by flax)
        # but lives as its own TrainState field: it is per-replica state
        # the optimizer must never touch, clipped by nothing, written by
        # the backward pass
        residual = (extra.pop("comm_residual", None)
                    if isinstance(extra, dict) else None)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            extra_vars=extra,
            opt_state=opt_state,
            # clone: the state is donated every step, and donating the
            # context's own key buffer would delete it for later use
            rng=jax.random.clone(self.ctx.seed_key),
            # attached after shard_tree: the residual is per-replica, and
            # letting shard_tree replicate it first would transiently
            # cost data_size x the stacked params PER DEVICE in fp32
            comm_residual=None,
        )
        # Place the state onto the mesh per its logical annotations: the
        # DDP-construction param broadcast (ddp.py:194-195) as a sharding —
        # replicated for plain-DDP models, split over ``model`` for
        # tensor-parallel meshes (parallel/sharding.py rules).
        from ..parallel.sharding import (
            fsdp_reshard, shard_tree, zero1_reshard,
        )

        state = shard_tree(state, self.ctx.mesh)
        if residual is not None:
            # per-replica residual: (L, data_size, padded) leaves split
            # over ``data`` on dim 1 — each replica holds exactly its own
            # compensation state, placed directly (never replicated).
            # Under ddp×tp (r17) the leaves are (L, data, model,
            # padded_local) and dim 2 additionally splits over ``model``
            from ..runtime.context import MODEL_AXIS

            def _place(x):
                spec = (P(None, DATA_AXIS, MODEL_AXIS) if x.ndim == 4
                        else P(None, DATA_AXIS))
                return jax.device_put(
                    x, NamedSharding(self.ctx.mesh, spec))

            state = state.replace(
                comm_residual=jax.tree.map(_place, residual))
        # scan-over-layers stacks every block weight on a leading
        # (num_layers, ...) dim — prefer splitting THERE so the whole
        # stack shards uniformly at layer granularity (one dividable axis
        # for FSDP instead of a per-leaf assortment of largest dims)
        prefer = 0 if self.config.scan_layers else None
        if self.config.fsdp:
            # full ZeRO-3 split: weights, grads (via GSPMD propagation)
            # and optimizer mirrors all live sharded over ``data``
            state = state.replace(
                params=fsdp_reshard(state.params, self.ctx.mesh,
                                    prefer_dim=prefer),
                opt_state=fsdp_reshard(state.opt_state, self.ctx.mesh,
                                       prefer_dim=prefer),
            )
        elif self.config.zero1:
            state = state.replace(
                opt_state=zero1_reshard(state.opt_state, self.ctx.mesh,
                                        prefer_dim=prefer)
            )
        return state

    def restore_or_init(self) -> tuple[TrainState, int]:
        # config compatibility is validated BEFORE the (expensive) template
        # init: a doomed restore should fail in milliseconds with its
        # intent message, not after a full model init + placement
        want = self.config.global_step if self.config.global_step > 0 else None
        durable_latest = self.ckpt.latest_step()
        if want is not None and durable_latest is None:
            # an explicit --global_step that cannot be honoured must not
            # silently restart from scratch
            raise FileNotFoundError(
                f"--global_step {want} requested but no checkpoints exist "
                f"under {self.ckpt.directory}"
            )
        # hot tier (r18): the newest local snapshot's MANIFEST alone
        # decides hot-vs-durable (a full array read + CRC on a multi-GB
        # state would tax every restart's MTTR even when the durable
        # tier wins); full validation runs in latest_valid() below once
        # the hot tier is actually chosen. Considered only for
        # auto-latest resumes (--global_step pins a durable step; hot
        # generations are latest-only by design)
        hot_meta = None
        if (self.hot is not None and want is None and self.config.resume):
            hot_meta = self.hot.latest_meta()
        use_hot = (hot_meta is not None
                   and (durable_latest is None
                        or hot_meta.step >= durable_latest))
        if not ((want is not None or self.config.resume)
                and (durable_latest is not None or hot_meta is not None)):
            return self.init_state(), 0
        if use_hot:
            saved = hot_meta.config or {}
        else:
            try:
                saved = self.ckpt.read_config(want) or {}
            except Exception:  # noqa: BLE001 - an unreadable newest config
                #               must not kill the resume: the restore
                #               fallback below walks to a complete step
                log.exception("checkpoint config unreadable; proceeding "
                              "to the restore fallback")
                saved = {}
        saved_opt = saved.get("optimizer")
        if saved_opt is not None and saved_opt != self.config.optimizer:
            # fail with intent, not an opaque orbax pytree mismatch: the
            # opt_state template cannot match a different optimizer, and
            # no restacking bridges adam moments to momentum — genuinely
            # lossy, so the named refusal stays (r18)
            raise ValueError(
                f"checkpoint at step "
                f"{want or (hot_meta.step if use_hot else durable_latest)} "
                f"was trained with --optimizer {saved_opt}, current run "
                f"uses {self.config.optimizer}; pass --no_resume or a "
                "fresh --output_dir to start over"
            )
        # layer-layout / mesh-shape changes are NO LONGER refusals: the
        # converter logic runs inside restore (reshard-on-restore, r18).
        # Checkpoints from before the scan_layers flag existed lack the
        # key and are necessarily unrolled.
        saved_scan = bool(saved.get("scan_layers", False))
        layout_changed = saved_scan != bool(self.config.scan_layers)
        mesh_changed = (saved.get("mesh") is not None
                        and saved.get("mesh") != self.config.mesh)
        if layout_changed or mesh_changed:
            log.info(
                "resuming across a config change "
                "(mesh %s -> %s, scan_layers %s -> %s): "
                "reshard-on-restore will convert in-restore",
                saved.get("mesh"), self.config.mesh,
                saved_scan, bool(self.config.scan_layers))
        state = self.init_state()
        if use_hot:
            try:
                # NOW pay the full read + CRC; an invalid newest
                # generation falls back to an older one inside
                # latest_valid(), which may land below the durable tier
                hot_rec = self.hot.latest_valid()
                if hot_rec is None:
                    raise RuntimeError("no hot generation passed "
                                       "validation")
                if (durable_latest is not None
                        and hot_rec.step < durable_latest):
                    raise RuntimeError(
                        f"newest VALID hot generation holds step "
                        f"{hot_rec.step} < durable step {durable_latest}")
                restored = self._restore_from_hot(hot_rec, state)
                return restored, int(restored.step)
            except Exception:  # noqa: BLE001 - the hot tier is an
                #               optimisation: a snapshot that will not
                #               restore degrades to the durable step
                log.exception(
                    "hot snapshot restore failed; falling back to the "
                    "durable checkpoint tier")
                if durable_latest is None:
                    # hot-only run, every generation invalid: nothing
                    # restorable exists. A raise here would crash-loop
                    # under a relauncher; the pre-hot posture for
                    # no-restorable-state is a fresh start, said loudly
                    log.error(
                        "no durable checkpoints and no hot generation "
                        "restores under %s — starting FRESH from step 0 "
                        "(the corrupt snapshots will be pruned by new "
                        "saves; pass --global_step to refuse instead)",
                        self.hot.base)
                    return state, 0
                hot_meta = None  # known-bad: no post-durable retry
        try:
            if layout_changed:
                # a doomed template restore is skipped outright: the
                # saved config already says the layouts differ
                state, _ = self.ckpt.restore_resharded(want, state)
            else:
                state, _ = self.ckpt.restore(want, state)
        except Exception as exc:
            if not layout_changed:
                # the direct restore failed with the SAME layout on
                # record: a pipe-degree change (mesh-only) or a stale
                # config still deserves the reshard attempt before the
                # named refusal
                try:
                    state, _ = self.ckpt.restore_resharded(want, state)
                    return state, int(state.step)
                except Exception:  # noqa: BLE001 - refuse below with the
                    pass           # original failure chained
            # an orbax tree/shape mismatch is opaque; name the likely
            # cause (model geometry changed between save and resume)
            raise ValueError(
                f"checkpoint at step {want or durable_latest} "
                f"does not match the current model {self.config.model!r} "
                "(architecture changed since it was saved? note: ResNet "
                "checkpoints from before the stageN_blockM module "
                "renaming use BasicBlock_N/BottleneckBlock_N keys and "
                "cannot be restored); reshard-on-restore handles layout/"
                "mesh changes, but not geometry changes — convert "
                "offline with tools/convert_checkpoint.py if possible, "
                "or pass --no_resume / a fresh --output_dir to start "
                "over"
            ) from exc
        if hot_meta is not None and int(state.step) < hot_meta.step:
            # the durable restore fell back past a torn newest step
            # (crash mid-save) and delivered LESS than the hot tier
            # holds — the one scenario the hot layer exists for;
            # prefer the newer snapshot (validated now), keep the
            # durable result if no generation survives validation
            log.info(
                "durable restore landed at step %d but a hot snapshot "
                "holds step %d (newest durable step torn?); restoring "
                "the hot snapshot instead",
                int(state.step), hot_meta.step)
            try:
                hot_rec = self.hot.latest_valid()
                if hot_rec is not None and hot_rec.step > int(state.step):
                    restored = self._restore_from_hot(hot_rec, state)
                    return restored, int(restored.step)
                log.warning(
                    "no hot generation newer than the durable step "
                    "validated; keeping the durable step %d",
                    int(state.step))
            except Exception:  # noqa: BLE001 - optimisation tier only
                log.exception(
                    "hot snapshot restore failed; keeping the durable "
                    "step %d", int(state.step))
        return state, int(state.step)

    def _restore_from_hot(self, hot_rec, template_state: TrainState
                          ) -> TrainState:
        """Restore from a validated hot snapshot through the SAME
        reshard/placement path durable checkpoints use
        (``checkpoint/reshard.place_state_onto_template`` — the
        snapshot is a raw host tree by construction, so every hot
        restore is a 'resharded' one, usually a no-op conversion +
        placement)."""
        from ..checkpoint.reshard import place_state_onto_template

        state = place_state_onto_template(template_state, hot_rec.body,
                                          hot_rec.residual,
                                          desc="hot snapshot")
        log.info("restored from hot snapshot",
                 {"step": hot_rec.step,
                  "generation": hot_rec.generation,
                  "dir": str(hot_rec.path)})
        return state

    # -- loops ------------------------------------------------------------
    def evaluate(self, state: TrainState) -> dict[str, float]:
        """Exactly-once eval: every held-out example contributes exactly
        once, globally. The loader pads the ragged tail and the shard
        wrap-around to SPMD-required shapes with weight-0 examples
        (``with_validity``); each batch metric is a weighted mean whose
        denominator the task reports as ``__denom__``, so the cross-batch
        aggregate ``sum(metric*denom)/sum(denom)`` is the exact whole-set
        statistic. (The reference's ``evaluate`` is a stub,
        ``/root/reference/ddp.py:123-124``.)"""
        if self.eval_dataset is None:
            return {}
        loader = ShardedLoader(
            self.eval_dataset, self.ctx.mesh, self.config.train_batch_size,
            seed=0, shuffle=False, with_validity=True,
            seq_dims=getattr(self.task, "seq_dims", None),
        )
        # accumulate on device: float() here would fence the dispatch
        # pipeline once per batch
        totals: dict[str, Any] = {}
        denom = None
        for batch in loader.epoch(0):
            m = dict(self.eval_step(state, batch))
            d = m.pop("__denom__")
            for k, v in m.items():
                totals[k] = totals.get(k, 0.0) + v * d
            denom = d if denom is None else denom + d
        den = max(float(denom), 1.0) if denom is not None else 1.0
        return {f"eval_{k}": float(v) / den for k, v in totals.items()}

    def train(self) -> TrainState:
        cfg = self.config
        t_restore = time.perf_counter()
        state, start_step = self.restore_or_init()
        # restore + init + placement: pre-training wall the goodput
        # ledger must not count as productive
        self.goodput.add("restore", time.perf_counter() - t_restore)
        from ..parallel.sharding import describe

        # mesh + active FSDP/TP execution modes (gspmd-default vs
        # decomposed) + per-leaf split-dim histogram + TP wire bytes:
        # the run log records WHICH layout/schedule produced its
        # numbers (model= supplies the geometry the TP wire accounting
        # needs). Computed once: the startup log, the unconditional
        # describe.json snapshot and the status endpoint all share it.
        desc = describe(self.ctx.mesh, cfg, state.params,
                        model=self.task.model)
        log.info(
            "***** running training *****",
            {
                "num_examples": len(self.dataset),
                "num_epochs": self.num_epochs,
                "per_device_batch": cfg.per_device_train_batch_size,
                "global_batch_with_accum": cfg.train_batch_size
                * cfg.gradient_accumulation_steps,
                "accum_steps": cfg.gradient_accumulation_steps,
                "total_optimizer_steps": self.total_steps,
                "resumed_at_step": start_step,
                **desc,
            },
        )
        # startup snapshot (config + mesh + overlap block), written
        # UNCONDITIONALLY to <output_dir>/describe.json — before r14 it
        # existed only inside flight bundles, but /status and humans
        # need it for every run, not only the sick ones
        snapshot = self._write_describe_snapshot(desc, start_step)
        if cfg.status_port:
            # opt-in live endpoint; binding failure disables it — the
            # watchtower must never cost the run it watches
            from ..obs.server import StatusServer

            try:
                # -1 = ephemeral: the server binds port 0 and the real
                # port is logged / exposed as self.status.port
                self.status = StatusServer(max(cfg.status_port, 0),
                                           host=cfg.status_host)
                self.status.set_static("describe", snapshot)
                self.status.sources["goodput"] = self.goodput.summary
                if self.sentry is not None:
                    self.status.sources["sentry"] = self.sentry.state
                if self.fleet is not None:
                    self.status.sources["fleet"] = self.fleet.state
                if self.memory is not None:
                    self.status.sources["memory"] = self.memory.state
                if self.supervisor is not None:
                    self.status.sources["supervisor"] = \
                        self.supervisor.state
                self.status.start()
            except Exception:  # noqa: BLE001
                log.exception("--status_port server failed to start; "
                              "continuing without it")
                self.status = None

        if cfg.hlo_report or cfg.perf_report or cfg.mem_report:
            # best-effort by design: a report/tripwire/attribution
            # failure must never cost the training run it exists to
            # protect. ONE shared AOT compile feeds all consumers.
            try:
                self._startup_reports(state)
            except Exception:  # noqa: BLE001
                log.exception("--hlo_report/--perf_report/--mem_report "
                              "startup analysis failed; continuing "
                              "without it")

        # graceful preemption (SLURM/TPU-VM maintenance send SIGTERM):
        # finish the in-flight step, checkpoint, exit cleanly — the next
        # run auto-resumes. The reference's pre-elastic launcher just dies
        # (SURVEY.md §5.3). Only the main thread may own signal handlers.
        stop_signal: dict[str, int | None] = {"sig": None}
        handler_registered = False
        prev_handler = None
        if threading.current_thread() is threading.main_thread():
            def _request_stop(signum, frame):  # noqa: ARG001
                stop_signal["sig"] = signum
            prev_handler = signal.signal(signal.SIGTERM, _request_stop)
            handler_registered = True

        try:
            return self._train_loop(state, start_step, stop_signal)
        finally:
            # telemetry first: flush every queued scalar (incl. the final
            # interval when the loop raised) before the writer closes
            self.telemetry.close()
            # the drain may deliver a verdict after the loop's last poll
            # (short runs): narrate a pending warn-mode decision so the
            # dry-run log is complete — act mode past the loop stays a
            # recorded decision, never a post-run action
            if self.supervisor is not None and self.supervisor.mode == "warn":
                try:
                    dec = self.supervisor.poll()
                    if dec is not None:
                        self._act_on_supervisor(dec, None, dec["step"])
                except Exception:  # noqa: BLE001 - narration only
                    log.exception("supervisor post-run narration failed")
            self.metrics_writer.close()
            # the ledger's durable heartbeat: a crash/preemption still
            # leaves goodput.json current, so the NEXT attempt's downtime
            # gap starts from the truth (pendings drained first — the
            # crash path never reached the loop-exit drain; idempotent
            # after a clean exit, which zeroed them)
            try:
                self._drain_pending_side_work()
            except Exception:  # noqa: BLE001
                pass
            self.goodput.flush()
            # the status endpoint dies WITH the run (crash included): a
            # dead job answering scrapes with frozen numbers is worse
            # than a connection refused the monitoring stack understands
            if self.status is not None:
                self.status.close()
            # restore only AFTER the preemption checkpoint is durably
            # written: schedulers re-deliver SIGTERM during the grace
            # window, and a default handler mid-save would defeat the
            # feature; also covers the loop raising
            if handler_registered:
                signal.signal(signal.SIGTERM,
                              prev_handler if prev_handler is not None
                              else signal.SIG_DFL)

    def _dispatch(self, state, batch, stop_signal=None):
        """Dispatch one jitted step; returns ``(state, metrics, fence)``.

        ``fence`` is the device scalar the bounded-depth barrier reads K
        iterations later: the cross-process stop agreement on multi-process
        runs, else the (already produced) loss. Shared with bench.py's e2e
        full-loop leg so the bench drives the exact production dispatch
        path."""
        if self._with_stop:
            # the anomaly-halt vote rides the same channel as SIGTERM: a
            # True from EITHER source reaches every host as one device-
            # side OR, so the fleet stops at the identical lagged step
            local = (stop_signal is not None
                     and stop_signal["sig"] is not None) or self._halt_vote
            votes = self._stop_votes.get(local)
            if votes is None:
                votes = self._stop_votes[local] = make_stop_flags(
                    self.ctx.mesh, local
                )
            args = (state, batch, votes)
        else:
            args = (state, batch)
        t0 = time.perf_counter()
        with annotate("train_step_dispatch"):
            state, metrics = self.train_step(*args)
        self._note_dispatch(time.perf_counter() - t0)
        if self._with_stop:
            return state, metrics, metrics.pop("stop_agreed")
        return state, metrics, metrics["loss"]

    def _note_dispatch(self, dt: float) -> None:
        """Post-dispatch bookkeeping: when the jit executable cache grew,
        this dispatch traced+compiled — record the duration for the
        goodput ``compile`` bucket (consumed by the next timer tick's
        split) and, mid-run, warn: a re-trace means the input
        shape/bucket or step structure changed, and without this record
        it masquerades as one mysteriously slow step."""
        size_fn = getattr(self.train_step, "_cache_size", None)
        if size_fn is None:  # wrapped step (tests/bench injectors)
            return
        try:
            size = int(size_fn())
        except Exception:  # noqa: BLE001 - accounting must never cost the run
            return
        if size <= self._jit_cache_size:
            return
        first = self._jit_cache_size == 0
        self._jit_cache_size = size
        self._pending["compile"] += dt
        if first:
            # the expected startup trace+compile (the --perf_report/
            # --hlo_report AOT compile does not populate the jit cache)
            log.info("train step compiled", {"compile_s": round(dt, 2)})
        else:
            log.warning(
                "train step re-traced mid-run (input shape/bucket or "
                "structure change) — this step paid a compile, recorded "
                "in the goodput `compile` bucket",
                {"compile_s": round(dt, 2), "executables_cached": size},
            )

    def _train_loop(self, state, start_step, stop_signal):
        cfg = self.config
        pbar = None
        if is_main_process():
            try:
                from tqdm import tqdm

                pbar = tqdm(total=self.total_steps, initial=start_step,
                            desc="train")
            except ImportError:
                pbar = None

        telemetry = self.telemetry

        def _on_write(kind, step, host):  # runs on the telemetry thread
            log.info(kind, {"step": step, **host})
            if self.status is not None:
                # latest-record feed for /status and /metrics — same
                # thread, already host floats, a dict copy under a lock
                self.status.note_record(kind, step, host)

        telemetry.on_write = _on_write

        window: list[jax.Array] = []
        side_work = False  # True when the last iteration ran eval/save/etc.
        trace = TraceWindow(cfg.output_dir, start_step=start_step + 10,
                            num_steps=cfg.profile_steps)
        timer = self.step_timer
        # Bounded dispatch depth: (step, fence) for the last K dispatches.
        # Reading the fence of step N-K each iteration is the loop's ONLY
        # host<->device sync — a scalar from a step that has already
        # retired in steady state, so it paces without stalling. In the
        # sync-telemetry before-mode on single-process runs the barrier is
        # off, reproducing the pre-async loop exactly.
        max_inflight = max(cfg.max_inflight_steps, 1)
        paced = self._with_stop or not isinstance(telemetry, SyncTelemetry)
        inflight: deque[tuple[int, jax.Array]] = deque()
        t_last = time.perf_counter()
        wait_last = self.loader.stats["consumer_wait_s"]
        idle_last = self.loader.stats["producer_idle_s"]
        examples_per_step = cfg.train_batch_size * cfg.gradient_accumulation_steps
        start_epoch = start_step // self.steps_per_epoch
        global_step = start_step
        done = False
        # perf/goodput cadence: --perf_every, falling back to the logging
        # cadence (perf fields then merge into the progress record)
        perf_every = cfg.perf_every or cfg.logging_steps
        # interval marks for the attribution deltas + the ledger's
        # per-iteration input split (separate from wait_last, which the
        # logging block owns)
        self._gp_wait_last = wait_last
        self._perf_marks = {
            "time": t_last, "step": global_step, "wait": wait_last,
            "idle": idle_last, "device_wait": self._device_wait_s,
        }
        # durable attempt marker BEFORE the first step: a hard kill
        # (SIGKILL/OOM — no finally runs) must still leave this attempt
        # and its inherited downtime on disk for the next attempt's
        # accounting; the in-loop heartbeat below keeps it fresh even
        # when --logging_steps 0 disables the perf cadence
        self.goodput.flush()
        # the loop proper runs under a crash guard: an exception mid-loop
        # must still stop any live profiler trace (losing the partially
        # captured profile of a crashed run loses the one you want most)
        # and give the flight recorder its chance to dump the ring buffer
        try:
            no_more = object()
            for epoch in range(start_epoch, self.num_epochs):
                # on resume mid-epoch, drop already-consumed batches in the
                # loader (before generation/transfer) so the data order matches
                # an uninterrupted run
                skip = start_step % self.steps_per_epoch if epoch == start_epoch else 0
                batches = self.loader.epoch(epoch, start_batch=skip)
                while True:
                    # explicit next() so the time blocked on the loader
                    # carries its phase name in captured traces (the
                    # loader's consumer_wait_s counter measures it)
                    with annotate("input_wait"):
                        batch = next(batches, no_more)
                    if batch is no_more:
                        break
                    # flight trace first: if its window ends exactly where
                    # the main --profile_steps window begins, it must stop
                    # before trace.step() starts the next capture (one
                    # live profiler trace per process)
                    if self._flight_trace is not None:
                        self._flight_trace.step(global_step)
                    trace.step(global_step)
                    state, metrics, fence = self._dispatch(state, batch, stop_signal)
                    # an interval that included eval/save/divergence work last
                    # iteration is not a step time — keep percentiles honest
                    dt = timer.tick(discard=side_work
                                    or self._hot_discard > 0)
                    side_work = False
                    if self._hot_discard:
                        self._hot_discard -= 1
                    # goodput: split this iteration's wall across buckets
                    # — measured parts (input stall, compile/save/eval
                    # durations recorded since the last tick) first,
                    # remainder productive. The pre-baseline first
                    # interval has no dt; ledger its measured parts only.
                    gp_wait = self.loader.stats["consumer_wait_s"]
                    pend = self._pending
                    if dt is None:
                        self.goodput.add("compile", pend["compile"])
                        self.goodput.add("input_stall",
                                         gp_wait - self._gp_wait_last)
                    else:
                        self.goodput.split_iteration(
                            dt, input_s=gp_wait - self._gp_wait_last,
                            compile_s=pend["compile"],
                            save_s=pend["checkpoint_save"],
                            hot_save_s=pend["hot_checkpoint_save"],
                            eval_s=pend["eval"], other_s=pend["other"])
                    self._gp_wait_last = gp_wait
                    for k in pend:
                        pend[k] = 0.0
                    # cadence-independent ledger heartbeat: one time.time()
                    # compare per iteration, one JSON write per minute at
                    # most — so a hard-killed --logging_steps 0 run still
                    # leaves a near-current goodput.json behind
                    self.goodput.flush(min_interval_s=60.0)
                    global_step += 1
                    inflight.append((global_step, fence))
                    if cfg.logging_steps:  # window only consumed when logging
                        window.append(metrics["loss"])
                    if self.sentry is not None:
                        # per-step health feed: device arrays into the
                        # telemetry queue (a dict build + queue put — the
                        # drain thread does the host conversion and hands
                        # the floats to the sentry; kind="health" records
                        # never hit the JSONL writer)
                        telemetry.emit(
                            global_step,
                            {k: metrics[k] for k in SENTRY_FEED_KEYS
                             if k in metrics},
                            kind="health")
                    if pbar is not None:
                        pbar.update(1)

                    stop_now = False
                    if paced:
                        t_fence = time.perf_counter()
                        with annotate("device_wait"):
                            while len(inflight) > max_inflight:
                                _, fval = inflight.popleft()
                                # the barrier: one scalar host read of a
                                # step K dispatches old — complete in
                                # steady state
                                fval = jax.device_get(fval)
                                if self._with_stop and int(fval):
                                    stop_now = True
                        # device-bound loops park HERE: the fence wait is
                        # the loop's observable device time, the quantity
                        # the perf attribution splits compute vs comm
                        self._device_wait_s += time.perf_counter() - t_fence
                    else:
                        while len(inflight) > max_inflight:
                            inflight.popleft()
                    if not self._with_stop and stop_signal["sig"] is not None:
                        # host-local decision; no device round-trip involved
                        stop_now = True
                    if (not self._with_stop and self._halt_at_step is not None
                            and global_step >= self._halt_at_step):
                        # single-process anomaly halt: stop once the
                        # post-trigger flight trace has its steps (the
                        # multi-process path stops via the vote agreement)
                        stop_now = True

                    if self.sentry is not None and self.sentry.triggered:
                        trig = self.sentry.poll_trigger()
                        if trig is not None:
                            self._on_anomaly_trigger(state, trig,
                                                     global_step, trace)

                    # perf/goodput cadence: attribution snapshot + ledger
                    # flush; merged into the progress record when the two
                    # cadences land on the same step, else its own record
                    perf_rec = None
                    if perf_every and global_step % perf_every == 0:
                        perf_rec = self._perf_snapshot(global_step)

                    if cfg.logging_steps and global_step % cfg.logging_steps == 0:
                        if isinstance(telemetry, SyncTelemetry):
                            # pre-async behaviour, kept bit-faithful for the
                            # host_overhead_pct before-leg: device mean, then
                            # the sink's inline float() blocks on the step
                            loss_val: Any = jnp.mean(jnp.stack(window))
                            timer_val: Any = timer.summary()
                        else:
                            # hand the raw per-step device scalars to the
                            # drain thread (it averages after device_get) and
                            # defer the percentile math over a snapshot taken
                            # NOW: zero extra dispatches, zero numpy on the
                            # hot loop, and the record stays tied to its step
                            # even if the drain lags
                            loss_val = window
                            timer_val = timer.deferred_summary()
                        window = []  # the sink owns the old list now
                        now = time.perf_counter()
                        steps_per_s = cfg.logging_steps / (now - t_last)
                        t_last = now
                        wait_now = self.loader.stats["consumer_wait_s"]
                        idle_now = self.loader.stats["producer_idle_s"]
                        scalars = {
                            "loss": loss_val,
                            "lr": metrics["lr"],
                            "grad_norm": metrics["grad_norm"],
                            "steps_per_sec": steps_per_s,
                            "examples_per_sec": steps_per_s * examples_per_step,
                            "input_wait_ms": 1e3 * (wait_now - wait_last)
                            / cfg.logging_steps,
                            # the prefetch thread's full-queue idle time:
                            # the input pipeline's SLACK (large values +
                            # ~zero input_wait_ms = headroom; both ~zero =
                            # the producer is the bottleneck). Counted by
                            # the loader since r8, surfaced here since r13
                            "producer_idle_ms": 1e3 * (idle_now - idle_last)
                            / cfg.logging_steps,
                            "timer": timer_val,
                        }
                        # the health pack rides the progress record at the
                        # logging cadence (point sample of the latest step,
                        # like lr/grad_norm) — the durable metrics.jsonl
                        # channel for the new fields
                        for k in HEALTH_KEYS:
                            if k in metrics:
                                scalars[k] = metrics[k]
                        wait_last = wait_now
                        idle_last = idle_now
                        if perf_rec:
                            scalars.update(perf_rec)
                            perf_rec = None
                        telemetry.emit(global_step, scalars, kind="progress")
                        # snapshot: the drain thread rebinds .latest (possibly
                        # to an eval record with no 'loss') between a check
                        # and an index
                        latest = telemetry.latest
                        if pbar is not None and "loss" in latest:
                            # lagged by design: the async contract trades a
                            # stale postfix for an unstalled dispatch pipeline
                            pbar.set_postfix(loss=f"{latest['loss']:.4f}")

                    if perf_rec:
                        # --perf_every off the logging cadence (or
                        # logging off): the snapshot is its own record
                        telemetry.emit(global_step, perf_rec, kind="perf")

                    if cfg.eval_steps and global_step % cfg.eval_steps == 0:
                        side_work = True
                        t_eval = time.perf_counter()
                        with annotate("eval"):
                            ev = self.evaluate(state)
                        self._pending["eval"] += time.perf_counter() - t_eval
                        if ev:
                            telemetry.emit(global_step, ev, kind="eval")

                    if (cfg.divergence_check_steps
                            and global_step % cfg.divergence_check_steps == 0):
                        # SPMD desync detector: dispatch the fingerprint now
                        # (async); the fetch+allgather completes via poll() once
                        # it is max_inflight steps old — off the critical path
                        self.divergence.submit(state.params, global_step)
                    t_div = time.perf_counter()
                    if self.divergence.poll(global_step) is not None:
                        side_work = True  # the DCN allgather ran this iteration
                        self._pending["other"] += time.perf_counter() - t_div

                    if cfg.save_steps and global_step % cfg.save_steps == 0:
                        # async orbax save: schedule-and-return. Only discard
                        # the next timer interval if scheduling actually
                        # stalled (e.g. waiting out the previous save) — an
                        # unconditional discard would blind the percentiles to
                        # every save-adjacent step
                        t_save = time.perf_counter()
                        with annotate("checkpoint_save"):
                            self.ckpt.save(global_step, state, cfg)
                        save_ms = 1e3 * (time.perf_counter() - t_save)
                        self._pending["checkpoint_save"] += save_ms / 1e3
                        p50 = timer.p50_ms() if self.ckpt.is_async else None
                        side_work = side_work or p50 is None or \
                            save_ms > max(0.25 * p50, 1.0)

                    if (cfg.hot_save_steps and self.hot is not None
                            and global_step % cfg.hot_save_steps == 0):
                        # hot tier: a blocking device_get + local write,
                        # booked to its OWN goodput bucket so the
                        # MTTR-vs-overhead trade is measurable
                        t_hot = time.perf_counter()
                        hot_path = None
                        with annotate("hot_checkpoint_save"):
                            try:
                                hot_path = self.hot.save(global_step,
                                                         state, cfg)
                            except Exception:  # noqa: BLE001 - the hot
                                #               tier is an optimisation:
                                #               a full/flaky local disk
                                #               must not kill a run the
                                #               durable tier still covers
                                log.exception(
                                    "hot snapshot save failed; disabling "
                                    "the hot tier for this attempt (the "
                                    "durable orbax saves continue)")
                                self.hot.disabled = True
                        if hot_path is not None:
                            hot_s = time.perf_counter() - t_hot
                            self._pending["hot_checkpoint_save"] += hot_s
                            # discard this interval AND the next (only
                            # when a snapshot actually happened — a
                            # disabled tier returns None in microseconds
                            # and must not starve the timer): the
                            # blocking device_get drains the bounded
                            # dispatch pipeline, and the disk write
                            # keeps competing with compute (OS
                            # writeback) for about one more interval —
                            # neither is a steady-state step time.
                            # Capped below the cadence so extreme
                            # cadences (the deterministic-test setting
                            # of 2) still record samples and the
                            # timer-gated consumers (perf baseline,
                            # restore-compare) keep working
                            side_work = True
                            self._hot_discard = min(
                                2, cfg.hot_save_steps - 1)

                    if self.fault is not None:
                        # deterministic fault injection, AFTER the save
                        # blocks: a crash at step N leaves step N's hot
                        # snapshot durable — the scenario the elastic
                        # stack exists to survive
                        self.fault.maybe_fire(global_step, hot=self.hot)

                    if self.supervisor is not None:
                        dec = self.supervisor.poll()
                        if dec is not None:
                            if self._act_on_supervisor(dec, state,
                                                       global_step):
                                stop_now = True

                    if stop_now:
                        # the drain thread may have delivered the sentry
                        # trigger AFTER this iteration's poll but before
                        # the supervisor's (same callback feeds both):
                        # drain it now so the triage bundle for the very
                        # verdict that stopped the run still lands
                        if self.sentry is not None and self.sentry.triggered:
                            trig = self.sentry.poll_trigger()
                            if trig is not None:
                                self._on_anomaly_trigger(state, trig,
                                                         global_step, trace)
                        if self._supervisor_stop:
                            log.warning(
                                "supervisor stop — checkpoint written, "
                                "exiting for resume on the healthy "
                                "subset (decision in supervisor.json; "
                                "downtime books to evict_resume)",
                                {"step": global_step},
                            )
                        elif self._halt_vote and stop_signal["sig"] is None:
                            # the sentry, not a scheduler, stopped this run
                            log.error(
                                "anomaly halt — checkpointing and exiting "
                                "(triage bundle in flight_records/)",
                                {"step": global_step},
                            )
                        else:
                            if stop_signal["sig"] is None:
                                # a peer was signalled; record it so the log
                                # is honest
                                stop_signal["sig"] = int(signal.SIGTERM)
                            log.warning(
                                "termination signal received — checkpointing "
                                "and exiting for clean resume",
                                {"signal": stop_signal["sig"],
                                 "step": global_step},
                            )
                        done = True
                        break

                    if global_step >= self.total_steps:
                        done = True
                        break
                if done:
                    break
        except BaseException as exc:
            # the crashed run's ring buffer IS the triage artifact: dump
            # it (best-effort — state may be poisoned or donated mid-step)
            # before the exception propagates to train()'s finally
            if self.recorder is not None:
                from ..obs.memory import looks_like_oom

                oom = looks_like_oom(exc)
                try:
                    self._dump_flight_record(state, {
                        "step": global_step,
                        "reasons": [f"exception: {exc!r}"],
                        "mode": "crash",
                        "oom": oom,
                        "time": time.time(),
                    }, fingerprint_ok=False,
                        # an allocation failure gets the memory
                        # forensics (live-buffer census + compile split
                        # + last K mem records) even without
                        # --mem_report — the live arrays exist anyway
                        mem_forensics=True if oom else None)
                except Exception:  # noqa: BLE001
                    log.exception("crash flight-record dump failed")
            raise
        finally:
            # crash or not: stop any live profiler capture so the partial
            # trace is written out (a crashed run's profile is the one you
            # want most), and release the progress bar
            if pbar is not None:
                pbar.close()
            trace.close()
            if self._flight_trace is not None:
                self._flight_trace.close()

        # side-work recorded in the FINAL iteration (a last-step eval or
        # save) has no next tick to consume it — drain it here so the
        # ledger never silently drops the run's closing minutes
        self._drain_pending_side_work()
        # completion marker: only a run that reached its step budget —
        # a SIGTERM/anomaly stop leaves it False, so the NEXT attempt
        # books the reschedule gap as `halted` downtime
        self.goodput.completed = (global_step >= self.total_steps
                                  and stop_signal["sig"] is None
                                  and not self._halt_vote)
        self.divergence.drain()  # identical pending set on every process
        t_final = time.perf_counter()
        with annotate("checkpoint_save"):
            if self.ckpt.latest_step() != global_step:  # no duplicate final save
                self.ckpt.save(global_step, state, cfg, force=True)
            self.ckpt.wait()  # the durability barrier IS checkpoint time
        self.goodput.add("checkpoint_save", time.perf_counter() - t_final)
        log.info("training complete", {"global_step": global_step})
        # the end-of-run goodput line: true end-to-end accounting, every
        # prior attempt of this output_dir included (obs/goodput.py)
        log.info("goodput summary", self.goodput.summary())
        self.goodput.flush()
        # this attempt's steady-state perf fingerprint, next to
        # goodput.json: the next attempt's regression yardstick
        # (obs/regression.py; clean exits only — the crash path must
        # not poison the baseline with partial numbers)
        self._write_perf_baseline()
        return state

    # -- observability ----------------------------------------------------
    def _drain_pending_side_work(self) -> None:
        """Move any unconsumed side-work durations into the ledger and
        zero them (idempotent). The per-iteration tick normally consumes
        them; the run's LAST iteration has no next tick."""
        for bucket, s in self._pending.items():
            self.goodput.add(bucket, s)
            self._pending[bucket] = 0.0

    def _perf_snapshot(self, global_step: int) -> dict[str, float]:
        """One perf-cadence tick: flush the goodput ledger and (when
        ``--perf_report`` built an attribution) compute the interval's
        MFU + compute/comm/host/input fractions from the deltas since
        the last snapshot. Returns flat float fields ready for a
        telemetry record."""
        now = time.perf_counter()
        stats = self.loader.stats
        marks = self._perf_marks
        wall_s = now - marks["time"]
        steps = global_step - marks["step"]
        input_s = stats["consumer_wait_s"] - marks["wait"]
        device_s = self._device_wait_s - marks["device_wait"]
        idle_s = stats["producer_idle_s"] - marks["idle"]
        rec: dict[str, float] = {}
        if self.perf is not None:
            rec = self.perf.interval(
                wall_s=wall_s,
                steps=steps,
                input_wait_s=input_s,
                device_wait_s=device_s,
                producer_idle_s=idle_s,
            )
            self._last_perf_rec = rec
        if self.fleet is not None:
            # this host's fleet window: pure host float math already in
            # hand — the DRAIN thread does the cross-host exchange
            self._emit_fleet_window(global_step, wall_s=wall_s,
                                    steps=steps, input_s=input_s,
                                    device_s=device_s, idle_s=idle_s)
        if self.memory is not None:
            # HBM watermark sample: a cadence marker only — the
            # device.memory_stats() poll happens on the DRAIN thread
            # (obs/memory.MemoryMonitor.observe), and the resolved
            # record writes as kind="mem"
            self.telemetry.emit(global_step, {}, kind="mem")
        # perf-regression tripwire: one comparison per attempt, once
        # the steady-state timer has enough honest samples
        self._maybe_check_baseline(global_step)
        # crash-survivable yardstick (r18): once the timer holds a
        # handful of honest samples, persist this attempt's fingerprint
        # at the perf cadence (rate-limited) — a hard-killed attempt
        # must still leave the next attempt a baseline, or the elastic
        # restart path flies blind (the restore-side COMPARE keeps its
        # stricter 16-sample gate; the fingerprint records `steps`)
        if self.step_timer.sample_count >= 8:
            if now - getattr(self, "_last_baseline_write", 0.0) > 30.0:
                self._last_baseline_write = now
                self._write_perf_baseline()
        self._perf_marks = {
            "time": now, "step": global_step,
            "wait": stats["consumer_wait_s"],
            "idle": stats["producer_idle_s"],
            "device_wait": self._device_wait_s,
        }
        gp = self.goodput.summary()
        if gp["goodput"] is not None:
            rec["goodput"] = gp["goodput"]
        rec["goodput_wall_s"] = gp["wall_s"]
        # heartbeat, rate-limited: the downtime gap the next attempt
        # computes only needs ~10s resolution, and an unconditional
        # write would tax sub-ms steps at tight logging cadences
        self.goodput.flush(min_interval_s=10.0)
        return rec

    def _write_describe_snapshot(self, desc: dict, start_step: int) -> dict:
        """Satellite (r14): the config + mesh + overlap-block snapshot,
        written UNCONDITIONALLY to ``<output_dir>/describe.json`` at
        engine start (host 0, best-effort) — previously it existed only
        inside flight bundles. Returns the dict (the status endpoint
        serves it)."""
        snapshot = {
            "schema": "describe/v1",
            "time": time.time(),
            "attempt": self.goodput.attempt,
            "resumed_at_step": start_step,
            "total_steps": self.total_steps,
            "mesh": {k: int(v) for k, v in self.ctx.mesh.shape.items()},
            "n_devices": int(self.ctx.mesh.devices.size),
            "process_count": jax.process_count(),
            "describe": desc,
            "config": json.loads(self.config.to_json()),
        }
        if is_main_process():
            try:
                from ..utils.serialization import json_sanitize

                path = Path(self.config.output_dir) / "describe.json"
                path.parent.mkdir(parents=True, exist_ok=True)
                path.write_text(json.dumps(json_sanitize(snapshot),
                                           indent=2, default=str,
                                           allow_nan=False))
            except Exception:  # noqa: BLE001 - the snapshot must never
                #               cost the run it documents
                log.exception("describe.json snapshot write failed")
        return snapshot

    def _emit_fleet_window(self, global_step: int, *, wall_s: float,
                           steps: int, input_s: float, device_s: float,
                           idle_s: float) -> None:
        """Queue this host's fleet window (``kind="fleet"``): interval
        deltas the loop already measured, as flat floats — the drain
        thread's FleetMonitor does the allgather + aggregation."""
        wall = max(wall_s, 1e-9)
        n = max(steps, 1)
        gp = self.goodput.totals()
        mark = self._fleet_gp_mark
        self._fleet_gp_mark = gp
        frac_input = min(max(input_s, 0.0) / wall, 1.0)
        frac_device = min(max(device_s, 0.0) / wall, 1.0 - frac_input)
        window = {
            "step": float(global_step),
            "step_wall_ms": 1e3 * wall / n,
            "frac_input": frac_input,
            "frac_device": frac_device,
            "frac_host": max(0.0, 1.0 - frac_input - frac_device),
            "input_wait_ms": 1e3 * max(input_s, 0.0) / n,
            "producer_idle_ms": 1e3 * max(idle_s, 0.0) / n,
            "gp_productive_s": gp["productive_step"]
            - mark.get("productive_step", 0.0),
            "gp_wall_s": sum(gp.values()) - sum(mark.values()),
            "anomaly": 1.0 if (self.sentry is not None
                               and self.sentry.triggered) else 0.0,
            # r16: pipeline-bubble share of the wall (0.0 without
            # --perf_report or a pipe axis) — a fleet whose bubble
            # fractions diverge has a desynchronised pipeline
            "bubble_frac": self._last_perf_rec.get(
                "perf_bubble_frac", 0.0),
        }
        if self.memory is not None:
            # the r15 memory columns (zero-filled by encode_window when
            # absent — this just supplies real values when they exist):
            # a host leaking memory is a straggler-to-be
            window.update(self.memory.wire_signals())
        self.telemetry.emit(global_step, window, kind="fleet")

    def _on_straggler(self, step: int, verdict: dict) -> None:
        """Fleet straggler verdict (drain thread): feed the sentry as a
        ``straggler`` trigger so the standard triage bundle lands with
        the offending host named — or, with no sentry configured, at
        least say it loudly. The supervisor (--supervise) receives the
        same confirmed verdict: this is the sentry→supervisor path that
        turns four rounds of detection into action."""
        reasons = [
            f"host {verdict['host']} step wall "
            f"{verdict['step_wall_ms']}ms > fleet median "
            f"{verdict['fleet_median_ms']}ms by {verdict['excess_pct']}% "
            f"(threshold {verdict['threshold_pct']}%) for "
            f"{verdict['consecutive_windows']} consecutive windows"]
        if self.sentry is not None:
            self.sentry.external_trigger(step, reasons, kind="straggler",
                                         scalars=verdict)
        else:
            log.warning(
                "fleet straggler detected (no --anomaly sentry active, "
                "so no triage bundle): " + reasons[0], verdict)
        if self.supervisor is not None:
            self.supervisor.on_verdict("straggler", step, verdict)

    def _act_on_supervisor(self, decision: dict, state,
                           global_step: int) -> bool:
        """Execute (act) or narrate (warn) a supervisor decision on the
        loop thread. Returns True when THIS host should stop this
        iteration (single-process act); multi-process runs stop through
        the device-side vote agreement instead, so every host exits at
        the identical lagged step — the r6 contract the eviction rides."""
        action = decision.get("action")
        host = decision.get("host")
        narrative = (
            f"checkpoint @ step {global_step} -> "
            + (f"evict host {host} -> " if action == "evict" else "")
            + "stop coherently -> resume on the "
            + ("healthy subset" if action == "evict" else "next attempt")
            + " (reshard-on-restore handles a smaller mesh)")
        if self.supervisor.mode == "warn":
            log.warning(
                "supervisor (warn mode) would act on the %s verdict: %s "
                "— logging only; pass --supervise act to execute",
                decision.get("kind"), narrative)
            return False
        log.warning("supervisor acting on the %s verdict: %s",
                    decision.get("kind"), narrative)
        from ..utils.dist import process_count

        if process_count() == 1:
            # immediate save, single-controller only: on a multi-process
            # fleet each host polls the verdict at its own iteration (or
            # not at all if its exchange degraded that window), so the
            # COLLECTIVE orbax save here could enter at different steps
            # and wedge on the commit barrier — there, the loop-exit
            # save at the vote-agreed stop step (identical on every
            # host) is the coordinated checkpoint
            t0 = time.perf_counter()
            with annotate("checkpoint_save"):
                if self.ckpt.latest_step() != global_step:
                    self.ckpt.save(global_step, state, self.config,
                                   force=True)
            self._pending["checkpoint_save"] += time.perf_counter() - t0
        if self.hot is not None:
            t1 = time.perf_counter()
            with annotate("hot_checkpoint_save"):
                try:
                    self.hot.save(global_step, state, self.config)
                except Exception:  # noqa: BLE001 - a dying local disk
                    #               (plausibly THE pathology on a sick
                    #               host) must not abort the eviction:
                    #               the durable save above already landed
                    log.exception(
                        "hot snapshot save failed during the supervisor "
                        "stop; continuing the eviction on the durable "
                        "checkpoint")
                    self.hot.disabled = True
            self._pending["hot_checkpoint_save"] += (time.perf_counter()
                                                     - t1)
        # the NEXT attempt books its restart gap to `evict_resume`,
        # not generic preemption downtime: this stop was chosen
        self.goodput.evicted = True
        self.supervisor.mark_acted(decision)
        # ride the same stop channel SIGTERM/anomaly-halt use: on
        # multi-process runs the device-side OR reaches every host
        # within K steps; single-process stops now
        self._halt_vote = True
        self._supervisor_stop = True
        return not self._with_stop

    def _current_fingerprint(self) -> dict | None:
        """This attempt's steady-state perf fingerprint from the honest
        StepTimer + whatever --perf_report produced (None before any
        step samples exist)."""
        from ..obs.regression import config_signature, make_fingerprint

        summ = self.step_timer.summary()
        if not summ:
            return None
        cm = self.perf.cost_model if self.perf is not None else {}
        return make_fingerprint(
            timer_summary=summ,
            mfu=self._last_perf_rec.get("perf_mfu"),
            wire_bytes_total=cm.get("wire_bytes_total"),
            frac_host=self._last_perf_rec.get("perf_frac_host"),
            steps=self.step_timer.sample_count,
            attempt=self.goodput.attempt,
            config_sig=config_signature(
                self.config, n_devices=int(self.ctx.mesh.devices.size)),
            # r15: peak HBM (measured watermark, else the static
            # projection, else absent) — restores catch memory
            # regressions the same way they catch step-wall ones
            peak_hbm_bytes=(self.memory.peak_hbm_bytes()
                            if self.memory is not None else None),
        )

    def _maybe_check_baseline(self, global_step: int = 0) -> None:
        """The restore-compare tripwire: ONCE per attempt, after the
        timer holds enough steady samples, compare against the prior
        attempt's ``perf_baseline.json`` and WARN per out-of-band
        signal. Best-effort by design."""
        if self._baseline_checked or self.baseline.prior is None:
            return
        if self.step_timer.sample_count < 16:
            return  # not steady state yet; a later snapshot will check
        self._baseline_checked = True
        try:
            current = self._current_fingerprint()
            if current is None:
                return
            warns = self.baseline.compare(
                current, threshold_pct=self.config.regression_pct)
            for w in warns:
                log.warning("perf regression vs prior attempt: " + w)
            if warns and self.supervisor is not None:
                # observe-only in the action table: recorded + surfaced,
                # never a restart loop chasing a slower-but-correct run
                self.supervisor.on_verdict(
                    "regression", global_step, {"warnings": warns})
        except Exception:  # noqa: BLE001 - tripwire must not cost the run
            log.exception("perf baseline comparison failed")

    def _write_perf_baseline(self) -> None:
        """Persist this attempt's fingerprint next to goodput.json —
        at clean shutdown AND (r18) at the perf cadence once the timer
        holds >= 8 honest samples, so a hard-killed attempt still
        leaves the next attempt a yardstick (side-work intervals are
        already discarded; the restore-side COMPARE keeps its stricter
        16-sample gate, and the fingerprint records `steps` so a reader
        can weigh an early-write estimate accordingly)."""
        try:
            current = self._current_fingerprint()
            if current is not None:
                self.baseline.write(current)
        except Exception:  # noqa: BLE001
            log.exception("perf_baseline.json write failed")

    def _on_anomaly_trigger(self, state, trig, global_step, main_trace):
        """Handle a sentry trigger on the loop thread: dump the triage
        bundle, arm a short profiler capture over the NEXT few steps into
        the bundle directory, and (halt mode) schedule the coherent stop."""
        from ..obs.sentry import FLIGHT_TRACE_STEPS
        from ..utils.dist import process_index

        # one live jax-profiler trace per process: skip the capture when
        # the --profile_steps window is mid-capture OR would OPEN inside
        # the flight window [global_step, global_step+N) — starting a
        # second trace raises, and the crash guard would then kill a run
        # that warn mode promises never to cost
        main_overlaps = (
            main_trace.enabled
            and main_trace.stop_at > global_step
            and main_trace.start < global_step + FLIGHT_TRACE_STEPS)
        # trigger.json names WHICH host dumped (every host runs its own
        # sentry) and which host will trace — decided before the dump so
        # the bundle's record is complete, not reconstructed. A
        # straggler verdict is fleet-replicated (every host saw the same
        # allgathered table), so only the NAMED host traces — N
        # simultaneous captures of N healthy hosts would be noise;
        # health-anomaly triggers trace wherever they fired (the r14
        # satellite fix for the r12 host-0 pin)
        named = ((trig.get("scalars") or {}).get("host")
                 if trig.get("kind") == "straggler" else None)
        my_turn = named is None or int(named) == process_index()
        will_trace = (self._flight_trace is None and not main_overlaps
                      and my_turn)
        trig = dict(trig)
        trig["host"] = process_index()
        if will_trace:
            trig["trace_host"] = process_index()
        elif named is not None and int(named) != process_index():
            # another host is expected to capture (it decides locally)
            trig["trace_host"] = int(named)
        else:
            # nobody will: this host was the one to trace but a live
            # window blocks it — the metadata must not point at a
            # trace that does not exist
            trig["trace_host"] = None
        flight_dir = None
        try:
            flight_dir = self._dump_flight_record(state, trig)
        except Exception:  # noqa: BLE001 - triage must not kill training
            log.exception("flight-record dump failed")
        if flight_dir is not None and will_trace:
            # start_step = the CURRENT counter: the next iteration's
            # loop-top step() call still carries this value (the counter
            # increments after dispatch), so capture starts immediately.
            # all_hosts: the triggering host captures its LOCAL trace —
            # the r12 host-0 pin silently lost every trace whose anomaly
            # fired on a non-zero host (r14 satellite fix)
            self._flight_trace = TraceWindow(
                flight_dir, start_step=global_step,
                num_steps=FLIGHT_TRACE_STEPS, all_hosts=True)
        elif flight_dir is not None and main_overlaps:
            log.info(
                "flight-record trace skipped: --profile_steps window "
                "overlaps the post-trigger capture",
                {"step": global_step, "profile_window":
                 [main_trace.start, main_trace.stop_at]})
        if self.sentry.mode == "halt":
            # vote now (multi-process: the device-side OR reaches every
            # host through the dispatch-depth barrier within K steps);
            # single-process: stop once the flight trace has its steps —
            # the +1 lets the window's own stop_at boundary close the
            # trace cleanly before the halt breaks the loop
            self._halt_vote = True
            self._halt_at_step = global_step + FLIGHT_TRACE_STEPS + 1

    def _dump_flight_record(self, state, trigger, *,
                            fingerprint_ok: bool = True,
                            mem_forensics: bool | None = None):
        """Write the triage bundle for ``trigger``; returns its directory
        (None when no recorder is configured). ``fingerprint_ok=False``
        skips the device fetch — crash dumps must not touch possibly
        donated/poisoned buffers. ``mem_forensics`` None = attach the
        memory forensics (census + compile split + mem-record ring)
        exactly when a ``--mem_report`` monitor exists; True forces a
        census-only payload (the OOM crash path on runs without the
        flag)."""
        if self.recorder is None:
            return None
        from ..parallel.sharding import describe
        from ..utils.divergence import fingerprint

        desc = None
        try:
            desc = describe(self.ctx.mesh, self.config, state.params,
                            model=self.task.model)
        except Exception:  # noqa: BLE001
            log.exception("describe() snapshot failed for flight record")
        fp = None
        if fingerprint_ok:
            try:
                # a device fetch, but a triggered run is past caring about
                # dispatch-depth discipline; NaNs in the digest serialise
                # as null+repr via the recorder's sanitiser
                fp = [float(x) for x in
                      np.asarray(jax.device_get(fingerprint(state.params)))]
            except Exception:  # noqa: BLE001
                log.exception("fingerprint failed for flight record")
        ring = self.sentry.records() if self.sentry is not None else []
        extra = None
        if mem_forensics or (mem_forensics is None
                             and self.memory is not None):
            from ..obs.memory import forensics_payload

            try:
                extra = {"memory.json": forensics_payload(self.memory)}
            except Exception:  # noqa: BLE001 - forensics must not cost
                #               the rest of the bundle
                log.exception("memory forensics failed for flight record")
        return self.recorder.dump(
            step=int(trigger.get("step", 0)), trigger=trigger, ring=ring,
            config=self.config, describe_snapshot=desc, fingerprint=fp,
            extra=extra)

    def _startup_reports(self, state):
        """``--hlo_report`` / ``--perf_report``: ONE ahead-of-time
        compile of the train step feeding both startup consumers — the
        HLO schedule report + overlap tripwire, and the perf
        attribution's static cost model. Costs one extra compilation
        (the loop's first call still compiles through the jit cache);
        both flags are opt-in for exactly that reason."""
        example = next(iter(self.loader.epoch(0)))
        args = [state, example]
        if self._with_stop:
            args.append(make_stop_flags(self.ctx.mesh, False))
        t0 = time.perf_counter()
        lowered = self.train_step.lower(*args)
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        # pre-loop compile wall is exactly what the goodput `compile`
        # bucket exists to expose
        self.goodput.add("compile", compile_s)
        hlo_text = compiled.as_text()
        if self.config.perf_report:
            try:
                self._init_perf(compiled, hlo_text)
            except Exception:  # noqa: BLE001 - attribution must not
                #               cost the run (nor the hlo report below)
                log.exception("--perf_report cost model failed; "
                              "continuing without attribution")
        if self.config.mem_report:
            try:
                self._init_memory_report(compiled, lowered)
            except Exception:  # noqa: BLE001 - same isolation contract
                log.exception("--mem_report compile-time analysis "
                              "failed; continuing without it")
        if self.config.hlo_report:
            self._emit_hlo_report(hlo_text, compile_s)

    def _init_perf(self, compiled, hlo_text: str) -> None:
        """Build the runtime attribution (obs/attribution.py) from the
        startup executable: static cost model (FLOPs + HBM bytes from
        cost analysis, wire bytes per mesh axis from the op census) +
        the device's peak-rate table (``--peak_tflops`` overrides)."""
        from ..obs.attribution import PerfAttribution, static_cost_model

        # r16: pipelined entries contribute their schedule's static
        # bubble fraction (task.bubble_fraction; zero when no pipe axis
        # or no pipelined task) so the runtime attribution can overlay
        # perf_bubble_frac on the measured device share
        pipe_bubble = 0.0
        bf = getattr(self.task, "bubble_fraction", None)
        if callable(bf):
            try:
                pipe_bubble = float(bf(self.config.train_batch_size))
            except Exception:  # noqa: BLE001 - attribution only
                pipe_bubble = 0.0
        # r22: on pipe×tp meshes the model-axis psums share the
        # all-reduce spelling with the data grad reduce — the task's
        # static ring-wire figure lets the cost model split the census
        # between the axes (zero everywhere else)
        model_wire = 0.0
        mw = getattr(self.task, "model_wire_bytes_per_step", None)
        if callable(mw):
            try:
                model_wire = float(mw(self.config.train_batch_size))
            except Exception:  # noqa: BLE001 - attribution only
                model_wire = 0.0
        cost_model = static_cost_model(
            compiled, dict(self.ctx.mesh.shape), hlo_text=hlo_text,
            pipe_bubble_frac=pipe_bubble,
            model_wire_bytes_per_step=model_wire)
        devices = self.ctx.mesh.devices
        self.perf = PerfAttribution(
            cost_model,
            device_kind=devices.flat[0].device_kind,
            n_devices=int(devices.size),
            peak_tflops_override=self.config.peak_tflops,
            # r17: --quant_compute selects the per-dtype peak row so the
            # startup log + perf records carry the narrow-peak headroom
            compute_dtype=(self.config.quant_compute
                           if self.config.quant_compute != "off"
                           else "bf16"),
        )
        log.info("perf attribution cost model", self.perf.describe())

    def _init_memory_report(self, compiled, lowered) -> None:
        """``--mem_report``'s compile-time half (obs/memory.py): the
        memory_analysis split + the donation audit off the shared
        startup executable, handed to the runtime monitor; donation
        gaps and a projected peak above the capacity budget WARN here,
        at startup — before the run walks into the cliff."""
        from ..obs.memory import (
            donation_warnings, static_memory_model,
        )

        args_info = getattr(lowered, "args_info", None)
        model = static_memory_model(compiled, args_info)
        self.memory.set_static_model(model)
        split = model.get("split") or {}
        audit = model.get("donation") or {}
        log.info("memory X-ray compile-time report", {
            "argument_mb": round(split.get("argument_bytes", 0) / 1e6, 2),
            "output_mb": round(split.get("output_bytes", 0) / 1e6, 2),
            "temp_mb": round(split.get("temp_bytes", 0) / 1e6, 2),
            "generated_code_mb": round(
                split.get("generated_code_bytes", 0) / 1e6, 2),
            "alias_mb": round(split.get("alias_bytes", 0) / 1e6, 2),
            "projected_peak_mb": round(
                split.get("projected_peak_bytes", 0) / 1e6, 2),
            "donated_leaves": audit.get("donated_leaves"),
            "undonated_leaves": audit.get("undonated_leaves"),
            "analysis_available": model.get("available"),
        } if split else {"analysis_available": False,
                         "donated_leaves": audit.get("donated_leaves"),
                         "undonated_leaves": audit.get("undonated_leaves")})
        for w in donation_warnings(model):
            log.warning(w)
        for w in self.memory.startup_warnings():
            log.warning(w)

    def _on_mem_pressure(self, step: int, verdict: dict) -> None:
        """Memory-pressure verdict (drain thread): feed the sentry as a
        ``mem_pressure`` trigger so the standard triage bundle lands
        with the numbers — and the memory forensics attached — or, with
        no sentry configured, at least say it loudly."""
        reasons = [
            f"HBM watermark {verdict['bytes_in_use'] / 1e9:.2f} GB is "
            f"{100 * verdict['frac_of_limit']:.1f}% of the "
            f"{verdict['bytes_limit'] / 1e9:.2f} GB device limit "
            f"(budget --mem_budget_frac="
            f"{verdict['budget_frac']:g}) on device "
            f"{verdict['device']} during phase {verdict['phase']!r}"]
        if self.sentry is not None:
            self.sentry.external_trigger(step, reasons,
                                         kind="mem_pressure",
                                         scalars=verdict)
        else:
            log.warning(
                "memory pressure detected (no --anomaly sentry active, "
                "so no triage bundle): " + reasons[0], verdict)
        if self.supervisor is not None:
            self.supervisor.on_verdict("mem_pressure", step, verdict)

    def _emit_hlo_report(self, hlo_text: str, compile_s: float):
        """Write the schedule report + tripwire warnings
        (obs/hlo_report.py) to ``<output_dir>/hlo_report.json``."""
        from ..obs.hlo_report import check_overlap_expectations, schedule_report

        report = schedule_report(hlo_text)
        report["compile_s"] = round(compile_s, 2)
        warnings = check_overlap_expectations(
            report, self.config, dict(self.ctx.mesh.shape))
        report["warnings"] = warnings
        if is_main_process():
            path = Path(self.config.output_dir) / "hlo_report.json"
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(json.dumps(report, indent=2))
        log.info("HLO schedule report", {
            "collective_ops": {k: v["count"]
                               for k, v in report["ops"].items()},
            "wire_mb_estimate": report["wire_mb_estimate"],
            "gather_independent_bodies":
                report["gather"]["independent_bodies"],
            "independent_ring_bodies":
                report["ring"]["independent_ring_bodies"],
            "composed_overlap_independent":
                report["composed"]["composed_overlap_independent"],
            "warnings": len(warnings),
            "report": str(Path(self.config.output_dir) / "hlo_report.json"),
        })
        for w in warnings:
            log.warning("schedule tripwire: " + w)
        return report
