"""Training engine: jitted SPMD step, schedules, metrics, orchestration."""

from .engine import (
    Trainer,
    TrainState,
    make_eval_step,
    make_optimizer,
    make_stop_flags,
    make_train_step,
)
from .metrics import (
    AsyncTelemetry,
    MetricsWriter,
    SyncTelemetry,
    make_telemetry,
)
from .schedule import (
    SCHEDULES,
    cosine_schedule_with_warmup,
    constant_schedule_with_warmup,
    linear_schedule_with_warmup,
)

__all__ = [
    "Trainer",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "make_optimizer",
    "make_stop_flags",
    "MetricsWriter",
    "AsyncTelemetry",
    "SyncTelemetry",
    "make_telemetry",
    "SCHEDULES",
    "cosine_schedule_with_warmup",
    "constant_schedule_with_warmup",
    "linear_schedule_with_warmup",
]
