"""Training engine: jitted SPMD step, schedules, metrics, orchestration."""

from .engine import (
    Trainer,
    TrainState,
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from .metrics import MetricsWriter
from .schedule import (
    SCHEDULES,
    cosine_schedule_with_warmup,
    constant_schedule_with_warmup,
    linear_schedule_with_warmup,
)

__all__ = [
    "Trainer",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "make_optimizer",
    "MetricsWriter",
    "SCHEDULES",
    "cosine_schedule_with_warmup",
    "constant_schedule_with_warmup",
    "linear_schedule_with_warmup",
]
