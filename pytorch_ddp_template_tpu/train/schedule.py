"""Learning-rate schedules.

Capability parity with ``get_linear_schedule_with_warmup``
(``/root/reference/ddp.py:52-61``): linear warmup from 0 over
``warmup_steps``, then linear decay to 0 at ``total_steps``. The reference
implements this as a ``LambdaLR`` multiplier; here it is a pure function of
the optimizer step — directly consumable by optax and traceable under jit.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def linear_schedule_with_warmup(
    base_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    """``lr(step)``: ``base_lr * step/warmup`` then linear decay to 0.

    Matches the reference multiplier exactly (``ddp.py:56-60``), including
    the ``max(0, ...)`` floor past ``total_steps``.
    """

    def schedule(step: jnp.ndarray) -> jnp.ndarray:
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.asarray(max(1.0, warmup_steps), jnp.float32)  # div guard only
        total = jnp.asarray(max(1.0, total_steps), jnp.float32)
        warmup_frac = step / warm
        decay_denom = jnp.maximum(1.0, total - float(warmup_steps))
        decay_frac = jnp.maximum(0.0, (total - step) / decay_denom)
        # note: condition uses the true warmup_steps, so warmup_steps == 0
        # never routes step 0 through the (zero-lr) warmup branch
        return base_lr * jnp.where(step < float(warmup_steps), warmup_frac, decay_frac)

    return schedule
