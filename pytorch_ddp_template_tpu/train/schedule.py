"""Learning-rate schedules.

Capability parity with ``get_linear_schedule_with_warmup``
(``/root/reference/ddp.py:52-61``): linear warmup from 0 over
``warmup_steps``, then linear decay to 0 at ``total_steps``. The reference
implements this as a ``LambdaLR`` multiplier; here it is a pure function of
the optimizer step — directly consumable by optax and traceable under jit.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def linear_schedule_with_warmup(
    base_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    """``lr(step)``: ``base_lr * step/warmup`` then linear decay to 0.

    Matches the reference multiplier exactly (``ddp.py:56-60``), including
    the ``max(0, ...)`` floor past ``total_steps``.
    """

    def schedule(step: jnp.ndarray) -> jnp.ndarray:
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.asarray(max(1.0, warmup_steps), jnp.float32)  # div guard only
        total = jnp.asarray(max(1.0, total_steps), jnp.float32)
        warmup_frac = step / warm
        decay_denom = jnp.maximum(1.0, total - float(warmup_steps))
        decay_frac = jnp.maximum(0.0, (total - step) / decay_denom)
        # note: condition uses the true warmup_steps, so warmup_steps == 0
        # never routes step 0 through the (zero-lr) warmup branch
        return base_lr * jnp.where(step < float(warmup_steps), warmup_frac, decay_frac)

    return schedule


def cosine_schedule_with_warmup(
    base_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    """Linear warmup, then cosine decay to 0 at ``total_steps`` — the
    standard large-batch/transformer recipe (no reference counterpart;
    the reference is linear-only, ``ddp.py:52-61``)."""

    def schedule(step: jnp.ndarray) -> jnp.ndarray:
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.asarray(max(1.0, warmup_steps), jnp.float32)
        warmup_frac = step / warm
        decay_denom = jnp.maximum(1.0, float(total_steps) - float(warmup_steps))
        progress = jnp.clip((step - float(warmup_steps)) / decay_denom, 0.0, 1.0)
        cosine = 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        return base_lr * jnp.where(step < float(warmup_steps), warmup_frac, cosine)

    return schedule


def constant_schedule_with_warmup(
    base_lr: float, warmup_steps: int, total_steps: int  # noqa: ARG001 - uniform factory signature
) -> optax.Schedule:
    """Linear warmup, then hold ``base_lr`` (debug/short-run recipe)."""

    def schedule(step: jnp.ndarray) -> jnp.ndarray:
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.asarray(max(1.0, warmup_steps), jnp.float32)
        warmup_frac = jnp.minimum(1.0, step / warm)
        return base_lr * jnp.where(step < float(warmup_steps), warmup_frac,
                                   jnp.asarray(1.0, jnp.float32))

    return schedule


SCHEDULES = {
    "linear": linear_schedule_with_warmup,
    "cosine": cosine_schedule_with_warmup,
    "constant": constant_schedule_with_warmup,
}
