"""The serving engine: bucketed prefill + ONE compiled decode program
over a model-sharded paged KV cache, with continuous batching.

Compile-count contract (the recompile-stall killer):

- **decode**: every step runs the SAME jitted program — fixed
  ``(max_slots,)`` token/position/length lanes, a fixed
  ``(max_slots, max_blocks)`` block table, the fixed-shape KV pool.
  Sequences of any length mix freely; growth across a block boundary
  is a free-list pop in the allocator, never a new shape. Pinned by
  test AND by the ``BENCH_MODE=serve`` committed record
  (``serve_decode_zero_recompile``).
- **prefill**: one compiled program per *bucketed* prompt length
  (prompts pad up to the bucket; the padded tail is written into the
  null block's scrap space and masked by the real context length), so
  the compile count is ``len(buckets)``, not ``len(distinct prompts)``.

Per engine step (:meth:`ServeEngine.step`): evictions happened at the
previous step's boundary, so first ADMIT (scheduler FCFS over free
slots + the committed-blocks budget), prefilling each admission and
emitting its first token (greedy, via the extracted
``ops/lm_head.greedy_decode`` — the ``(B, V)`` logits row never
exists); then ONE decode dispatch for every running slot; then book
finished sequences out. Prefill/decode wall-clock books to the goodput
ledger's ``serve_prefill``/``serve_decode`` buckets, and the flat
stats record feeds ``/status`` (kind ``serve``) and the
``tpuddp_serve_*`` gauges on ``/metrics``.

Params load through ``CheckpointManager.restore_raw`` + the r18
layout converter (:meth:`ServeEngine.from_checkpoint`): a training
checkpoint at ANY layer layout (scanned / unrolled / pipelined)
restores into the serving template directly.

``spec_k > 0`` (r20) swaps the decode phase for speculative decoding
(``serve/spec.py``): a shallow shared-embedding draft proposes k
tokens, the target verifies the window in ONE dispatch, and greedy
longest-prefix acceptance keeps the output token-for-token identical
to plain greedy decode.  The compile contract extends, it does not
bend: exactly TWO compiled decode programs (draft + verify), admission
reserves draft lanes too (worst case doubles), and the draft wall
books to the ``serve_draft`` goodput bucket.  Sampling goes through
the ``ops/lm_head.sample_tokens`` seam (``ServeConfig.sampling``,
greedy-only v1) so future policies never touch the engine.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import get_logger
from .kv_cache import NULL_BLOCK, PagedKVCache
from .model import decode_forward, prefill_forward, stacked_layers, \
    tp_decode_forward
from .scheduler import ContinuousScheduler, Request

log = get_logger(__name__)


def _default_buckets(block_size: int, max_model_len: int) -> tuple[int, ...]:
    """Power-of-two prompt buckets, block-aligned, up to the model
    limit — one compiled prefill program each."""
    buckets = []
    b = max(block_size, 16)
    while b < max_model_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_model_len)
    return tuple(sorted(set(buckets)))


@dataclasses.dataclass
class ServeConfig:
    """Engine geometry. Every field is a compile-shape or capacity
    knob; none of them changes with the traffic."""

    block_size: int = 16          # tokens per KV block
    num_blocks: int = 64          # physical pool size (incl. null block)
    max_slots: int = 4            # decode lanes (the decode batch shape)
    max_model_len: int = 128      # hard per-sequence length limit
    prefill_buckets: tuple[int, ...] | None = None  # None = powers of two
    kv_quant: str = "off"         # off | int8 (r17 primitives)
    eos_id: int | None = None     # early-stop token (None = length-only)
    vocab_block: int = 8192       # greedy-decode vocab tile
    static_batch: bool = False    # ablation: wave admission (the baseline)
    sampling: str = "greedy"      # ops/lm_head.sample_tokens policy seam
    spec_k: int = 0               # speculative decoding: max draft window
    #                               per round (0 = off; the verify
    #                               program's fixed lane count is
    #                               max_slots * spec_k)
    draft_depth: int = 0          # sliced-draft depth (first N target
    #                               layers); required when spec_k > 0
    #                               unless an external draft checkpoint
    #                               is passed
    spec_adaptive: bool = True    # per-request adaptive-k controller
    #                               (full accept grows the window,
    #                               rejection shrinks to evidence)

    def buckets(self) -> tuple[int, ...]:
        bks = self.prefill_buckets or _default_buckets(
            self.block_size, self.max_model_len)
        for b in bks:
            if b % self.block_size:
                raise ValueError(
                    f"prefill bucket {b} not a multiple of block_size "
                    f"{self.block_size} (bucket blocks insert whole)")
            if b > self.max_model_len:
                raise ValueError(
                    f"prefill bucket {b} exceeds max_model_len "
                    f"{self.max_model_len}")
        return tuple(sorted(bks))


def place_for_serving(params: dict, mesh, *, tp_head: bool = False) -> dict:
    """Model-shard the serving template over the mesh's ``model`` axis:
    attention heads (qkv kernel dim 2 / out kernel dim 1, with the
    leading stacked-layer axis) and the MLP hidden split; embeddings,
    norms and biases that span ``embed`` replicate. GSPMD partitions
    the jitted prefill/decode like any other program from these
    placements. The spec rule itself lives in
    ``serve/model.serving_param_spec`` — ONE source shared with the
    ``--tp_overlap`` ring decode's region specs, so placement and the
    explicit-collective program can never disagree. ``tp_head=True``
    (the TP ring engine) additionally shards the tied ``wte`` over
    vocab; the caller pads the table to ring granularity first."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..runtime.context import MODEL_AXIS
    from .model import serving_param_spec

    n = mesh.shape.get(MODEL_AXIS, 1)

    def spec(path) -> P:
        if n <= 1:
            return P()
        return serving_param_spec(path, tp_head=tp_head)

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: jax.device_put(
            leaf, NamedSharding(mesh, spec(path))), params)


class ServeEngine:
    """Prefill + per-token decode over the paged pool; see the module
    docstring for the step anatomy."""

    def __init__(self, model, params: dict, cfg: ServeConfig | None = None,
                 *, mesh=None, goodput=None, status=None,
                 draft_params: dict | None = None):
        self.cfg = cfg or ServeConfig()
        tp_live = self._validate_model(model, mesh)
        from ..ops.lm_head import SAMPLING_POLICIES

        if self.cfg.sampling not in SAMPLING_POLICIES:
            raise ValueError(
                f"unknown sampling policy {self.cfg.sampling!r}; v1 "
                f"serves {SAMPLING_POLICIES} (the ops/lm_head."
                "sample_tokens seam is where new policies land)")
        if self.cfg.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.cfg.spec_k}")
        if draft_params is not None and not self.cfg.spec_k:
            raise ValueError(
                "draft_params given but spec_k is 0: set spec_k > 0 to "
                "turn speculative decoding on")
        self.model = model
        self.mesh = mesh
        self.dtype = model.dtype
        self.attn_impl = model.attn_impl
        if self.cfg.max_model_len > model.max_len:
            raise ValueError(
                f"max_model_len {self.cfg.max_model_len} exceeds the "
                f"model's positional table ({model.max_len})")
        if self.cfg.max_model_len % self.cfg.block_size:
            raise ValueError(
                f"max_model_len {self.cfg.max_model_len} must be a "
                f"multiple of block_size {self.cfg.block_size} (the "
                "decode program's block table is sized max_model_len / "
                "block_size rows)")
        if self.cfg.kv_quant == "int8":
            import os

            if os.environ.get("PAGED_IMPL", "xla") == "pallas":
                raise ValueError(
                    "kv_quant=int8 serves through the xla gather path "
                    "only; unset PAGED_IMPL=pallas")
        # template: scanned stacked layers (the one-compiled-block form)
        import flax.linen as nn

        from ..parallel.stacking import convert_tree_layout

        params = nn.meta.unbox(params)  # fresh inits carry logical boxes
        params = convert_tree_layout(params, "scanned", strict=False)
        stacked_layers(params)  # validates the layout, refusal named
        #: TP ring decode degree (1 = the plain/GSPMD path)
        self._tp = 1
        self._vocab = model.vocab_size
        self._quant = "off"
        if mesh is not None:
            from ..runtime.context import MODEL_AXIS

            n_model = mesh.shape.get(MODEL_AXIS, 1)
            if model.num_heads % n_model:
                raise ValueError(
                    f"num_heads {model.num_heads} not divisible by the "
                    f"model axis ({n_model})")
            if tp_live:
                import os

                from ..ops.lm_head import tp_head_geometry

                if model.mlp_dim % n_model:
                    raise ValueError(
                        f"mlp_dim {model.mlp_dim} not divisible by the "
                        f"model axis ({n_model}) — the fc1/fc2 rings "
                        "shard the MLP hidden")
                if self.cfg.max_slots % n_model:
                    raise ValueError(
                        f"TP decode shards the {self.cfg.max_slots} slot "
                        f"lanes over the model axis ({n_model}); set "
                        "max_slots to a multiple of it (scrap slots are "
                        "cheap — they decode into the null block)")
                if os.environ.get("PAGED_IMPL", "xla") == "pallas":
                    raise ValueError(
                        "TP serving runs the xla gather decode path "
                        "only (the Pallas page walk is not validated "
                        "under the sharded region); unset "
                        "PAGED_IMPL=pallas")
                self._tp = n_model
                self._quant = getattr(model, "quant_compute", "off")
                # pad the tied table ONCE to ring granularity: the
                # vocab-parallel embed and the rotating-argmax head
                # both consume resident (V/n)-row shards of it
                _, vs, pad_v = tp_head_geometry(
                    self._vocab, n_model, self.cfg.vocab_block)
                if pad_v:
                    params = dict(params)
                    params["wte"] = dict(params["wte"])
                    params["wte"]["embedding"] = jnp.pad(
                        params["wte"]["embedding"], ((0, pad_v), (0, 0)))
            params = place_for_serving(params, mesh, tp_head=tp_live)
        self.params = params
        self.kv = PagedKVCache(
            num_layers=model.num_layers, num_heads=model.num_heads,
            head_dim=model.head_dim, num_blocks=self.cfg.num_blocks,
            block_size=self.cfg.block_size, dtype=self.dtype,
            kv_quant=self.cfg.kv_quant)
        if mesh is not None:
            from jax.sharding import NamedSharding

            kv_spec = NamedSharding(mesh, self.kv.head_sharding_spec())
            self.kv.pool = {
                k: jax.device_put(v, kv_spec)
                for k, v in self.kv.pool.items()}
        self.max_blocks = self.cfg.max_model_len // self.cfg.block_size
        self.scheduler = ContinuousScheduler(
            self.cfg.max_slots, static_batch=self.cfg.static_batch)
        self._buckets = self.cfg.buckets()
        #: worst-case blocks committed per running/admitted sequence —
        #: the no-preemption invariant (see scheduler module docstring)
        self._committed: dict[int, int] = {}
        self._goodput = goodput
        self._status = status
        if status is not None:
            status.sources["serve"] = self.serve_state
        # speculative decoding (serve/spec.py): build the draft AFTER
        # placement so a sliced draft shares the placed target arrays
        # by reference
        self._spec = None
        if self.cfg.spec_k:
            from .spec import SpecRunner, adopt_draft_checkpoint, \
                make_draft_params

            if draft_params is not None:
                draft, depth = adopt_draft_checkpoint(draft_params,
                                                      self.params)
                if self.cfg.draft_depth and self.cfg.draft_depth != depth:
                    raise ValueError(
                        f"draft checkpoint holds {depth} layers but "
                        f"draft_depth asks for {self.cfg.draft_depth}; "
                        "drop draft_depth (it is inferred from the "
                        "checkpoint) or fix the checkpoint")
            else:
                draft = make_draft_params(self.params, self.cfg.draft_depth)
                depth = self.cfg.draft_depth
            if mesh is not None:
                draft = place_for_serving(draft, mesh,
                                          tp_head=self._tp > 1)
            self._spec = SpecRunner(self, draft, depth)
            log.info("speculative decoding on", {
                "spec_k": self.cfg.spec_k, "draft_depth": depth,
                "adaptive": self.cfg.spec_adaptive,
                "draft_source": ("checkpoint" if draft_params is not None
                                 else "sliced")})
        # donation lets XLA update the pool in place; CPU ignores it
        # with a warning per program, so gate on backend
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._prefill_fn = jax.jit(
            functools.partial(self._prefill_math), donate_argnums=donate)
        self._decode_fn = jax.jit(
            functools.partial(self._decode_math), donate_argnums=donate)
        self.steps = 0
        self.tokens_out = 0
        self._t0 = time.perf_counter()
        self._prefill_s = 0.0
        self._decode_s = 0.0
        if self._tp > 1:
            log.info("serve_tp", self.describe_tp())

    @staticmethod
    def _validate_model(model, mesh) -> bool:
        """The refusal matrix, with intent per flag. Returns True when
        the ``--tp_overlap`` ring decode path is live: the model asks
        for it AND the mesh carries a model axis > 1. Every refused
        template names its own reason — "unsupported flag" tells an
        operator nothing about what to change."""
        from ..runtime.context import MODEL_AXIS

        n = (mesh.shape.get(MODEL_AXIS, 1) if mesh is not None else 1)
        tp = bool(getattr(model, "tp_overlap", False))
        refusals = {
            "moe_experts": (
                "expert-parallel FFNs have no serving path yet (the "
                "dispatch/combine all-to-alls would sit inside the "
                "decode scan); serve the dense twin of the checkpoint"),
            "fsdp_overlap": (
                "serving holds no gradients or optimizer state, so "
                "there is nothing to shard-and-overlap; params place "
                "whole (or model-sharded) via place_for_serving"),
            "ddp_overlap": (
                "decode has no gradient all-reduce to overlap; "
                "data-parallel serving is N engines behind one "
                "scheduler, not one engine on a data axis"),
            "pipe_stages": (
                "pipelined templates have no serving path (the slot "
                "loop's stage hand-offs assume a training microbatch "
                "stream); restack the checkpoint through the r18 "
                "layout converter and serve it flat"),
        }
        for flag, why in refusals.items():
            if getattr(model, flag, 0):
                raise ValueError(
                    f"serving template does not support {flag}: {why}")
        if tp and n <= 1:
            raise ValueError(
                "--tp_overlap serving needs a mesh with a live model "
                f"axis (got {'no mesh' if mesh is None else f'model axis {n}'}"
                "): the ring collective matmuls and the rotating-argmax "
                "head shard over it — pass a data×model mesh, or drop "
                "tp_overlap to serve single-replica")
        if getattr(model, "quant_compute", "off") != "off" and not tp:
            raise ValueError(
                "serving with --quant_compute weights rides the TP ring "
                "wire only (tp_overlap on a model-axis mesh quantizes "
                "the rotating chunks, r17 path); the plain template "
                "runs the master weights — kv_quant int8 covers the "
                "cache side")
        if getattr(model, "attn_impl", "auto") in ("ring", "ulysses"):
            raise ValueError(
                "context-parallel attention has no serving path yet; "
                "serve with attn_impl='auto'")
        return tp

    def describe_tp(self) -> dict[str, Any]:
        """The ``serve_tp`` startup/describe block: tp degree, per-step
        decode ring wire (wide vs the r17 quantized wire) and the KV
        pool's per-shard residency — what an operator needs to size the
        ICI budget and the HBM split before any traffic arrives. The
        same numbers export as ``tpuddp_serve_tp_*`` gauges via
        :meth:`stats`."""
        from ..parallel.collective_matmul import tp_decode_wire_bytes_per_step

        n = self._tp
        embed = self.model.num_heads * self.model.head_dim
        wide = tp_decode_wire_bytes_per_step(
            slots=self.cfg.max_slots, embed=embed,
            num_layers=self.model.num_layers, n=n)
        quant = tp_decode_wire_bytes_per_step(
            slots=self.cfg.max_slots, embed=embed,
            num_layers=self.model.num_layers, n=n,
            quant=self._quant if self._quant != "off" else "int8")
        return {
            "serve_tp_degree": n,
            "serve_tp_ring_wire_mb_per_step_wide": wide / 1e6,
            "serve_tp_ring_wire_mb_per_step_quant": quant / 1e6,
            "serve_tp_ring_wire_mb_per_step": (
                (quant if self._quant != "off" else wide) / 1e6),
            "serve_tp_kv_pool_bytes_per_shard": self.kv.pool_bytes(
                model_shards=n),
        }

    # -- jitted math -------------------------------------------------------
    def _prefill_math(self, params, pool, ids, length, block_ids):
        """One prompt: full forward, insert its KV blocks into the
        pool, greedy-decode the first token from the last real
        position. ``ids (1, T)`` bucket-padded; ``block_ids
        (T/block_size,)`` physical targets (null-padded past the
        prompt's blocks — scrap writes the mask never reads)."""
        hidden, k, v = prefill_forward(
            params, ids, dtype=self.dtype, attn_impl=self.attn_impl)
        lyr, _, t, h, d = k.shape
        nb = t // self.cfg.block_size
        k = k.reshape(lyr, nb, self.cfg.block_size, h, d)
        v = v.reshape(lyr, nb, self.cfg.block_size, h, d)
        pool = dict(pool)
        if self.cfg.kv_quant == "int8":
            from .kv_cache import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            pool["k"] = pool["k"].at[:, block_ids].set(kq)
            pool["v"] = pool["v"].at[:, block_ids].set(vq)
            pool["k_scale"] = pool["k_scale"].at[:, block_ids].set(ks)
            pool["v_scale"] = pool["v_scale"].at[:, block_ids].set(vs)
        else:
            pool["k"] = pool["k"].at[:, block_ids].set(
                k.astype(pool["k"].dtype))
            pool["v"] = pool["v"].at[:, block_ids].set(
                v.astype(pool["v"].dtype))
        from ..ops.lm_head import sample_tokens

        h_last = jnp.take(hidden[0], length - 1, axis=0)  # (E,)
        # vocab= masks the ring-granularity pad rows of a TP-placed
        # table (a no-op for the unpadded single-replica table)
        nxt = sample_tokens(h_last[None], params["wte"]["embedding"],
                            policy=self.cfg.sampling,
                            block=self.cfg.vocab_block,
                            vocab=self._vocab)[0]
        return nxt, pool

    def _decode_math(self, params, pool, tokens, positions, tables,
                     ctx_lens, write_blocks, write_offsets):
        if self._tp > 1:
            # the TP ring program samples inside its one shard_map
            # region (serve/model.tp_decode_forward) — hidden never
            # leaves the shards
            return tp_decode_forward(
                params, pool, tokens, positions, tables, ctx_lens,
                write_blocks, write_offsets, mesh=self.mesh,
                dtype=self.dtype, vocab=self._vocab,
                kv_quant=self.cfg.kv_quant, quant=self._quant,
                policy=self.cfg.sampling,
                vocab_block=self.cfg.vocab_block)
        hidden, pool = decode_forward(
            params, pool, tokens, positions, tables, ctx_lens,
            write_blocks, write_offsets, dtype=self.dtype,
            kv_quant=self.cfg.kv_quant)
        from ..ops.lm_head import sample_tokens

        nxt = sample_tokens(hidden, params["wte"]["embedding"],
                            policy=self.cfg.sampling,
                            block=self.cfg.vocab_block)
        return nxt, pool

    # -- intake ------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16) -> Request:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self._buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prefill bucket ({self._buckets[-1]})")
        if len(prompt) + max_new_tokens > self.cfg.max_model_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_model_len {self.cfg.max_model_len}")
        need = self._blocks_reserved(len(prompt), max_new_tokens)
        if need > self.kv.num_blocks - 1:
            # refuse at submit: an unadmittable request would sit at the
            # queue head forever (FCFS) starving everything behind it
            raise ValueError(
                f"request needs {need} KV blocks but the pool holds "
                f"{self.kv.num_blocks - 1}; raise num_blocks or lower "
                "max_new_tokens"
                + (" (speculative decoding doubles the reservation: "
                   "the draft twin mirrors the target's lanes)"
                   if self._spec is not None else ""))
        return self.scheduler.submit(prompt, max_new_tokens)

    def _blocks_reserved(self, prompt_len: int, max_new: int) -> int:
        """Worst-case blocks one request commits.  Spec mode doubles
        it: the draft twin writes the SAME position range (k clamps to
        the remaining budget, so neither sequence ever exceeds
        ``prompt + max_new`` positions)."""
        need = self.kv.blocks_needed(prompt_len + max_new)
        return 2 * need if self._spec is not None else need

    def _can_admit(self, req: Request) -> bool:
        """Admission = reservation: the worst-case block count is
        committed HERE, not at prefill — the scheduler approves a whole
        wave before any prefill runs, and each member must see the
        members admitted before it (the no-OOM invariant)."""
        need = self._blocks_reserved(len(req.prompt), req.max_new_tokens)
        budget = self.kv.num_blocks - 1  # null block excluded
        if sum(self._committed.values()) + need > budget:
            return False
        self._committed[req.id] = need
        return True

    # -- the engine step ---------------------------------------------------
    def step(self) -> dict[str, Any]:
        """One iteration of the serving loop: admit (+prefill), decode,
        evict finished. Returns the flat stats record it published."""
        admitted = self.scheduler.admit(self._can_admit)
        spec_d0 = self._spec.draft_s if self._spec is not None else 0.0
        t0 = time.perf_counter()
        for req in admitted:
            self._prefill_request(req)
        prefill_dt = time.perf_counter() - t0 if admitted else 0.0
        spec_d1 = self._spec.draft_s if self._spec is not None else 0.0
        prefill_dt = max(0.0, prefill_dt - (spec_d1 - spec_d0))
        self._prefill_s += prefill_dt
        t1 = time.perf_counter()
        decode_dt = 0.0
        if self.scheduler.running:
            if self._spec is not None:
                self._spec.decode_step(dict(self.scheduler.running))
            else:
                self._decode_step()
            decode_dt = time.perf_counter() - t1
        spec_d2 = self._spec.draft_s if self._spec is not None else 0.0
        decode_dt = max(0.0, decode_dt - (spec_d2 - spec_d1))
        self._decode_s += decode_dt
        draft_dt = spec_d2 - spec_d0
        self.steps += 1
        if self._goodput is not None:
            if prefill_dt:
                self._goodput.add("serve_prefill", prefill_dt)
            if decode_dt:
                self._goodput.add("serve_decode", decode_dt)
            if draft_dt:
                # the speculative wager's cost side, metered apart
                self._goodput.add("serve_draft", draft_dt)
        if self._status is None:
            return {}  # no sink: don't assemble gauges in the token path
        rec = self.stats()
        self._status.note_record("serve", self.steps, rec)
        return rec

    def _prefill_request(self, req: Request) -> None:
        plen = len(req.prompt)
        bucket = next(b for b in self._buckets if b >= plen)
        self.kv.alloc(req.id, plen)  # worst case reserved at admission
        nb_bucket = bucket // self.cfg.block_size
        blocks = self.kv.table(req.id)
        block_ids = np.full((nb_bucket,), NULL_BLOCK, np.int32)
        block_ids[: len(blocks)] = blocks
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :plen] = req.prompt
        nxt, self.kv.pool = self._prefill_fn(
            self.params, self.kv.pool, jnp.asarray(ids),
            jnp.int32(plen), jnp.asarray(block_ids))
        tok = int(nxt)  # sync: TTFT is honest wall-clock
        req.tokens.append(tok)
        req.t_first_token = time.time()
        self.tokens_out += 1
        self._maybe_finish(req, tok)
        if self._spec is not None and req.state != "finished":
            # draft twin prefills AFTER the first token is out (TTFT
            # stays the target's prefill alone); skipped when the first
            # token already finished the request
            self._spec.prefill(req)

    def _decode_step(self) -> None:
        s = self.cfg.max_slots
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        ctx = np.zeros((s,), np.int32)
        wb = np.full((s,), NULL_BLOCK, np.int32)
        wo = np.zeros((s,), np.int32)
        tables = np.full((s, self.max_blocks), NULL_BLOCK, np.int32)
        running = dict(self.scheduler.running)
        for slot, req in running.items():
            pos = self.kv.seq_len(req.id)
            blk, off = self.kv.append_slot(req.id)
            tokens[slot] = req.tokens[-1]
            positions[slot] = pos
            ctx[slot] = pos + 1  # the token attends to itself
            wb[slot], wo[slot] = blk, off
            tables[slot] = self.kv.padded_table(req.id, self.max_blocks)
        nxt, self.kv.pool = self._decode_fn(
            self.params, self.kv.pool, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(tables),
            jnp.asarray(ctx), jnp.asarray(wb), jnp.asarray(wo))
        nxt = np.asarray(nxt)  # ONE host sync for the whole step
        for slot, req in running.items():
            tok = int(nxt[slot])
            req.tokens.append(tok)
            self.tokens_out += 1
            self._maybe_finish(req, tok)

    def _maybe_finish(self, req: Request, tok: int) -> None:
        done = len(req.tokens) >= req.max_new_tokens
        if self.cfg.eos_id is not None and tok == self.cfg.eos_id:
            done = True
        if done:
            self.scheduler.finish(req)
            self.kv.free(req.id)
            if self._spec is not None:
                self._spec.release(req)
            self._committed.pop(req.id, None)

    def run(self, max_steps: int = 100_000) -> dict[int, list[int]]:
        """Drive :meth:`step` until idle; ``{request_id: tokens}``."""
        for _ in range(max_steps):
            if self.scheduler.idle():
                break
            self.step()
        return {rid: list(r.tokens)
                for rid, r in self.scheduler.finished.items()}

    # -- reporting ---------------------------------------------------------
    def decode_programs(self) -> int:
        """Compiled decode-program count — the zero-recompile pin:
        1 plain, 2 speculative (draft + verify; the plain decode
        program never traces in spec mode), however sequences grow or
        k adapts."""
        n = self._decode_fn._cache_size()
        if self._spec is not None:
            n += self._spec.decode_program_count()
        return n

    def prefill_programs(self) -> int:
        n = self._prefill_fn._cache_size()
        if self._spec is not None:
            n += self._spec.prefill_program_count()
        return n

    def stats(self) -> dict[str, Any]:
        """Flat SLO/capacity gauges, ``serve_``-prefixed — the record
        published to ``/status`` (kind ``serve``) and exported as
        ``tpuddp_serve_*`` on ``/metrics``."""
        wall = max(time.perf_counter() - self._t0, 1e-9)
        kv = self.kv.stats()
        slo = self.scheduler.slo_summary()
        n_dev = jax.device_count()
        rec: dict[str, Any] = {
            "serve_queue_depth": self.scheduler.queue_depth(),
            "serve_active": self.scheduler.active(),
            "serve_finished_total": slo["finished"],
            "serve_tokens_total": self.tokens_out,
            "serve_tokens_per_sec": self.tokens_out / wall,
            "serve_tokens_per_sec_per_chip": self.tokens_out / wall / n_dev,
            "serve_blocks_used": kv["blocks_used"],
            "serve_blocks_free": kv["blocks_free"],
            "serve_frag_slots": kv["frag_slots"],
            "serve_kv_high_water_blocks": kv["high_water_blocks"],
            "serve_kv_bytes_per_token": kv["bytes_per_token"],
            "serve_prefill_s_total": self._prefill_s,
            "serve_decode_s_total": self._decode_s,
            "serve_decode_programs": self.decode_programs(),
            "serve_prefill_programs": self.prefill_programs(),
            "serve_steps": self.steps,
        }
        if slo["ttft_s_mean"] is not None:
            rec["serve_ttft_ms_mean"] = slo["ttft_s_mean"] * 1e3
        if slo["ttft_s_max"] is not None:
            rec["serve_ttft_ms_max"] = slo["ttft_s_max"] * 1e3
        if slo["per_token_s_mean"] is not None:
            rec["serve_per_token_ms_mean"] = slo["per_token_s_mean"] * 1e3
        if self._spec is not None:
            rec.update(self._spec.stats_fields(self.scheduler.running))
        if self._tp > 1:
            # flat numeric fields → tpuddp_serve_tp_* gauges for free
            # (the /metrics sweep exports every number on kind "serve")
            rec.update(self.describe_tp())
        return rec

    def serve_state(self) -> dict[str, Any]:
        """The ``/status`` source: gauges + engine geometry."""
        return {
            **self.stats(),
            "config": dataclasses.asdict(self.cfg),
            "buckets": list(self._buckets),
        }

    # -- the checkpoint seam -----------------------------------------------
    @staticmethod
    def _restore_params(directory, step):
        from ..checkpoint.manager import CheckpointManager

        mngr = CheckpointManager(directory)
        try:
            step_n, state, _cfg = mngr.restore_raw(step)
        finally:
            mngr.close()
        params = state.get("params") if isinstance(state, dict) else None
        if params is None:
            raise ValueError(
                f"checkpoint at {directory} holds no 'params' item — "
                "not a training-state checkpoint this engine can serve")
        return step_n, params

    @classmethod
    def from_checkpoint(cls, directory, model,
                        cfg: ServeConfig | None = None, *, step=None,
                        draft_dir=None, draft_step=None,
                        mesh=None, goodput=None, status=None
                        ) -> "ServeEngine":
        """Serve a TRAINING checkpoint directly: template-free read
        (``restore_raw`` — falls back past torn steps), the r18 layout
        converter restacks scanned/unrolled/pipelined into the serving
        template, and the params place onto ``mesh``. The optimizer
        state rides along in the raw read and is dropped here — serving
        wants the params leaf only.

        ``draft_dir`` (with ``cfg.spec_k > 0``) loads an independently
        trained shallow draft through the SAME seam — the
        ``--num_layers`` workflow: train a depth-d twin of the target
        config, point draft_dir at its checkpoints, and the engine
        adopts its stack while sharing the target's embedding table
        (see ``serve/spec.py``)."""
        step_n, params = cls._restore_params(directory, step)
        log.info("serving checkpoint", {"dir": str(directory),
                                        "step": step_n})
        draft_params = None
        if draft_dir is not None:
            d_step, draft_params = cls._restore_params(draft_dir,
                                                       draft_step)
            log.info("draft checkpoint", {"dir": str(draft_dir),
                                          "step": d_step})
        return cls(model, params, cfg, mesh=mesh, goodput=goodput,
                   status=status, draft_params=draft_params)
