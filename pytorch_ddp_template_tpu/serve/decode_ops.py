"""Decode-specialized attention: one query token per sequence reading
scattered KV blocks through a block table.

The training flash kernel (``ops/flash.py``) is the wrong shape for
decode: its grid tiles a (seq x seq) logit square, but a decode step
has ONE query row per sequence attending over a context that lives in
non-contiguous physical blocks (``serve/kv_cache.py``). This module is
the gather-KV path:

- :func:`paged_attention` — the public op. ``q (S, H, D)`` against the
  pooled ``(N, B, H, D)`` K/V of one layer, routed per ``PAGED_IMPL``.
- ``xla`` (default) — gather-by-table (``k_pool[tables]``), mask
  positions ``>= context_len``, f32 softmax. XLA lowers the gather to a
  dynamic-slice loop; at serving batch sizes the whole gathered context
  is tiny next to the weights, and this formulation is exactly
  re-orderable against the dense reference (the parity test's anchor).
- ``pallas`` — the real gather kernel: grid ``(S, max_blocks)`` with
  the block table and context lengths as **scalar-prefetch** operands,
  so each kv BlockSpec's ``index_map`` reads the table and DMAs the
  right physical block — the kernel never touches a gathered copy.
  Online-softmax state (m/l lane-replicated, acc) lives in VMEM scratch
  across the sequential block dimension, the ``ops/flash.py``
  recurrence re-shaped for a single query row per sequence.

The Pallas path follows the FLASH_BWD/QUANT_IMPL convention: validated
in interpret mode on CPU (the parity test), default ``xla`` everywhere
until a real-Mosaic parity record lands (``tools/tpu_followup.sh
legs_r19``). ``PAGED_IMPL=pallas`` opts in; int8 KV (quantized pool)
is served by the xla path only — the kernel takes the f32 pool.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import get_logger

log = get_logger(__name__)

#: renamed TPUCompilerParams → CompilerParams across jax versions
CompilerParams = (getattr(pltpu, "CompilerParams", None)
                  or pltpu.TPUCompilerParams)

NEG_INF = -1e30
LANES = 128

_impl_logged: set[str] = set()


def paged_impl() -> str:
    """Active lowering for the paged decode attention, read at TRACE
    time (the FLASH_BWD/QUANT_IMPL convention): ``PAGED_IMPL=pallas``
    opts into the gather kernel (interpret mode off-TPU — how CPU CI
    validates it); default ``xla`` until the real-Mosaic parity record
    (legs_r19). A typo'd override fails loudly."""
    impl = os.environ.get("PAGED_IMPL", "xla")
    if impl not in ("xla", "pallas"):
        raise ValueError(f"PAGED_IMPL={impl!r}: expected 'xla' or 'pallas'")
    if impl not in _impl_logged:
        _impl_logged.add(impl)
        log.info(
            "paged decode attention lowering selected (trace-time; set "
            "PAGED_IMPL before first use or jax.clear_caches() to change)",
            {"impl": impl})
    return impl


def gather_kv(pool_leaf: jax.Array, tables: jax.Array) -> jax.Array:
    """``(N, B, H, ...)[tables (S, M)]`` -> ``(S, M*B, H, ...)``: one
    sequence's logical context, materialised in table order."""
    g = pool_leaf[tables]  # (S, M, B, H, ...)
    s, m, b = g.shape[:3]
    return g.reshape(s, m * b, *g.shape[3:])


def _paged_attention_xla(q, k_pool, v_pool, tables, context_lens,
                         k_scale=None, v_scale=None):
    dtype = q.dtype
    d = q.shape[-1]
    k = gather_kv(k_pool, tables)          # (S, T, H, D)
    v = gather_kv(v_pool, tables)
    if k_scale is not None:
        from .kv_cache import dequantize_kv

        k = dequantize_kv(k, gather_kv(k_scale, tables))
        v = dequantize_kv(v, gather_kv(v_scale, tables))
    qf = q.astype(jnp.float32) * (d ** -0.5)
    logits = jnp.einsum("shd,sthd->sht", qf, k.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    t = logits.shape[-1]
    valid = lax.broadcasted_iota(jnp.int32, (1, 1, t), 2) \
        < context_lens[:, None, None]
    logits = jnp.where(valid, logits, NEG_INF)
    # fully-masked rows (inactive slots, context_len 0) must yield 0,
    # not NaN — the engine discards them but the program must stay finite
    weights = jax.nn.softmax(logits, axis=-1)
    weights = jnp.where(valid.any(-1, keepdims=True), weights, 0.0)
    out = jnp.einsum("sht,sthd->shd", weights, v.astype(jnp.float32))
    return out.astype(dtype)


def _paged_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, block_size: int,
                  max_blocks: int, scale: float):
    s = pl.program_id(0)   # sequence slot
    j = pl.program_id(1)   # logical block (sequential)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = lens_ref[s]
    # a block whose first slot is past the context holds nothing valid;
    # skip its compute entirely (the tail of a short sequence)
    @pl.when(j * block_size < ctx)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale        # (H, D)
        k = k_ref[0].astype(jnp.float32)                # (B, H, D)
        v = v_ref[0].astype(jnp.float32)
        # (H, B): contract D, batch H
        logits = lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        pos = j * block_size + lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(pos < ctx, logits, NEG_INF)
        m_prev = m_ref[...]                              # (H, LANES)
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
        p = jnp.exp(logits - m_new[:, :1])               # (H, B)
        correction = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * correction + jnp.sum(p, axis=1,
                                                       keepdims=True)
        m_ref[...] = m_new
        # (H, D): p (H, B) x v (B, H, D), batch H
        pv = lax.dot_general(p, v, (((1,), (0,)), ((0,), (1,))),
                             preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * correction[:, :1] + pv

    @pl.when(j == max_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        out = acc_ref[...] / l
        # fully-masked slot (ctx 0): emit zeros, not NaN
        out = jnp.where(m_ref[:, :1] <= NEG_INF / 2, 0.0, out)
        o_ref[0] = out.astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pool, v_pool, tables, context_lens):
    s, h, d = q.shape
    _, block_size = k_pool.shape[0], k_pool.shape[1]
    max_blocks = tables.shape[1]
    interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(
        _paged_kernel, block_size=block_size, max_blocks=max_blocks,
        scale=d ** -0.5)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # tables, context_lens
        grid=(s, max_blocks),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, j, tb, ln: (i, 0, 0)),
            # the gather: the kv BlockSpec reads the PHYSICAL block id
            # from the prefetched table — the DMA itself is the page walk
            pl.BlockSpec((1, block_size, h, d),
                         lambda i, j, tb, ln: (tb[i, j], 0, 0, 0)),
            pl.BlockSpec((1, block_size, h, d),
                         lambda i, j, tb, ln: (tb[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, tb, ln: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, LANES), jnp.float32),   # m (lane-replicated)
            pltpu.VMEM((h, LANES), jnp.float32),   # l
            pltpu.VMEM((h, d), jnp.float32),       # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      q, k_pool, v_pool)


def paged_attention(q, k_pool, v_pool, tables, context_lens, *,
                    k_scale=None, v_scale=None):
    """Single-token attention over a paged KV pool.

    Args:
      q: ``(S, H, D)`` — one query token per decode slot.
      k_pool, v_pool: ``(N, B, H, D)`` — ONE layer's physical blocks
        (``PagedKVCache.pool`` leaf, layer axis already sliced).
      tables: ``(S, max_blocks)`` int32 physical-block ids, padded with
        the null block.
      context_lens: ``(S,)`` int32 valid context per slot (0 = inactive
        slot; its output row is zeros).
      k_scale, v_scale: int8-pool dequant scales ``(N, B, H, 1)``
        (``kv_quant="int8"``; xla path only).

    Returns ``(S, H, D)`` in ``q.dtype``.
    """
    impl = paged_impl()
    if impl == "pallas":
        if k_scale is not None:
            raise ValueError(
                "PAGED_IMPL=pallas does not serve the int8 KV pool yet "
                "(the gather kernel takes the f32 pool); drop one of "
                "--kv_quant int8 / PAGED_IMPL=pallas")
        return _paged_attention_pallas(q, k_pool, v_pool, tables,
                                       context_lens)
    return _paged_attention_xla(q, k_pool, v_pool, tables, context_lens,
                                k_scale=k_scale, v_scale=v_scale)
