"""Continuous-batching scheduler: iteration-level admission and
eviction (Orca, Yu et al. OSDI'22).

Static batching forms a batch, decodes until EVERY member finishes,
and only then admits again — the batch runs at the speed of its
longest member while finished slots burn idle decode lanes. Continuous
batching re-decides membership every step: finished sequences leave at
the step they finish, queued sequences join the moment a slot AND the
KV blocks are free. The scheduler owns the host-side bookkeeping
(queue, slot map, per-request timing); the capacity question is
delegated to the engine's block accounting (``can_admit`` callback),
so admission is joint over the two real resources — decode slots and
KV blocks — and never over tensor shapes.

Admission commits worst-case KV blocks (prompt + max_new_tokens): a
running sequence can always grow to its limit without preemption.
That is deliberately conservative next to vLLM's optimistic
admission + preempt-on-OOM — preemption needs KV swap/recompute
machinery this engine doesn't carry yet; the committed-blocks ledger
makes the no-OOM guarantee a one-line invariant instead.

A decode step may emit SEVERAL tokens per request at once (the
speculative verify step commits an accepted run, ``serve/spec.py``):
``Request.tokens`` grows by the whole run, so the SLO math needs no
special case — ``per_token_s`` divides the decode wall by tokens
actually emitted, TTFT is still the prefill's single first token, and
admission already reserved the draft twin's lanes through the engine's
``can_admit`` callback.  Continuous join/evict is untouched: a
finished member leaves at the round it finishes, whatever the round's
emission width.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable


@dataclasses.dataclass
class Request:
    """One generation request through its life: queued → running →
    finished. ``tokens`` accumulates the generated ids; timing fields
    feed the SLO metrics (TTFT = first token - submit)."""

    id: int
    prompt: list[int]
    max_new_tokens: int
    state: str = "queued"
    tokens: list[int] = dataclasses.field(default_factory=list)
    slot: int | None = None
    t_submit: float = 0.0
    t_first_token: float | None = None
    t_finished: float | None = None
    # -- speculative decoding (serve/spec.py): the per-request
    #    controller state rides the request so it joins/evicts with it
    draft_k: int = 0       # current adaptive draft window (0 = unset)
    spec_drafted: int = 0  # lifetime draft tokens proposed for this req
    spec_accepted: int = 0  # lifetime draft tokens accepted

    @property
    def ttft_s(self) -> float | None:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def per_token_s(self) -> float | None:
        """Mean inter-token latency over the decode phase (excludes
        TTFT — prefill is its own SLO)."""
        if self.t_finished is None or self.t_first_token is None:
            return None
        n = len(self.tokens) - 1
        if n <= 0:
            return None
        return (self.t_finished - self.t_first_token) / n


class ContinuousScheduler:
    """Admission queue + slot map for ``max_slots`` decode lanes.

    ``static_batch=True`` degrades to wave admission (admit only into
    an EMPTY engine, drain fully) — the ablation baseline the
    ``BENCH_MODE=serve`` continuous-vs-static leg measures against.
    """

    def __init__(self, max_slots: int, *, static_batch: bool = False):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = max_slots
        self.static_batch = static_batch
        self.queue: deque[Request] = deque()
        self.running: dict[int, Request] = {}  # slot -> request
        self.finished: dict[int, Request] = {}  # id -> request
        self._next_id = 0
        # running SLO aggregates — slo_summary() must stay O(1): the
        # engine publishes it every decode step, and rescanning
        # `finished` would grow the per-token host cost with lifetime
        # requests served
        self._ttft_sum = 0.0
        self._ttft_max = 0.0
        self._ttft_n = 0
        self._pt_sum = 0.0
        self._pt_n = 0

    # -- intake ------------------------------------------------------------
    def submit(self, prompt: list[int], max_new_tokens: int,
               *, now: float | None = None) -> Request:
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        req = Request(id=self._next_id, prompt=list(prompt),
                      max_new_tokens=int(max_new_tokens),
                      t_submit=time.time() if now is None else now)
        self._next_id += 1
        self.queue.append(req)
        return req

    # -- membership --------------------------------------------------------
    def free_slots(self) -> list[int]:
        return [s for s in range(self.max_slots) if s not in self.running]

    def admit(self, can_admit: Callable[[Request], bool]) -> list[Request]:
        """Move queue heads into free slots while ``can_admit`` (the
        engine's block-budget check) holds — FCFS, no reordering (a
        blocked head blocks the queue: cheap head-of-line fairness;
        size-aware reordering is a policy for later). Static mode only
        admits into an empty engine (the wave)."""
        if self.static_batch and self.running:
            return []
        admitted = []
        slots = self.free_slots()
        while self.queue and slots:
            req = self.queue[0]
            if not can_admit(req):
                break
            self.queue.popleft()
            req.slot = slots.pop(0)
            req.state = "running"
            self.running[req.slot] = req
            admitted.append(req)
        return admitted

    def finish(self, req: Request, *, now: float | None = None) -> None:
        """Per-step eviction of a finished sequence: the slot frees at
        THIS step's boundary (the continuous-batching move)."""
        req.state = "finished"
        req.t_finished = time.time() if now is None else now
        if req.slot is not None:
            self.running.pop(req.slot, None)
            req.slot = None
        self.finished[req.id] = req
        if req.ttft_s is not None:
            self._ttft_sum += req.ttft_s
            self._ttft_max = max(self._ttft_max, req.ttft_s)
            self._ttft_n += 1
        if req.per_token_s is not None:
            self._pt_sum += req.per_token_s
            self._pt_n += 1

    # -- reporting ---------------------------------------------------------
    def queue_depth(self) -> int:
        return len(self.queue)

    def active(self) -> int:
        return len(self.running)

    def idle(self) -> bool:
        return not self.queue and not self.running

    def slo_summary(self) -> dict[str, Any]:
        """TTFT / per-token latency over everything finished so far —
        O(1) from the running aggregates (published every step)."""
        return {
            "ttft_s_mean": (self._ttft_sum / self._ttft_n
                            if self._ttft_n else None),
            "ttft_s_max": self._ttft_max if self._ttft_n else None,
            "per_token_s_mean": (self._pt_sum / self._pt_n
                                 if self._pt_n else None),
            "finished": len(self.finished),
        }
