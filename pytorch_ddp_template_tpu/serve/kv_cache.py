"""Paged KV cache: a block-table slot allocator over fixed-size KV
blocks, so sequences grow without recompiles.

The naive serving cache is a dense ``(B, max_len, H, D)`` tensor per
layer: every admitted sequence reserves its worst-case length up front
(internal fragmentation ~= 1 - mean_len/max_len), and any change to the
resident batch's length profile is a new XLA program. PagedAttention
(Kwon et al., SOSP'23) fixes both with virtual memory's oldest trick:
the cache is a pool of fixed-size physical blocks, each sequence holds
a *block table* (its logical-to-physical page map), and the attention
kernel gathers through the table. Consequences this module exists for:

- **Zero recompiles on growth** — the device arrays
  ``(L, num_blocks, block_size, H, D)`` never change shape; a sequence
  crossing a block boundary costs one free-list pop, not a compile
  (pinned by test: ONE compiled decode program, ever).
- **No length fragmentation** — a sequence holds ceil(len/block_size)
  blocks; waste is bounded by one partial block per sequence
  (``stats()["frag_slots"]`` meters it).
- **Admission = arithmetic** — the scheduler admits while
  ``can_alloc(prompt_len)`` holds; there is no "fits in the batch
  tensor?" shape question, only a block budget.

Physical block 0 is reserved as the **null block**: padded block-table
entries and masked decode slots point at it, so gathers and scatter
writes for inactive lanes have a harmless, always-valid target (the
attention mask discards whatever lands there).

Host-side state (free list, tables, lengths) is plain Python — the
allocator runs between device steps, never inside them; the device
arrays are functional values threaded through the engine's jitted
programs (donated, so XLA updates the pool in place).

``kv_quant="int8"`` (the r17 stretch): blocks store int8 with one f32
scale per (token, head) — per-``head_dim``-channel symmetric absmax,
``ops/quant.py``'s granularity — cutting resident KV bytes ~3.8x at
D=64 (the "roughly doubles concurrent sequences" lever, conservatively
stated). Dequantize happens inside the gather path
(``serve/decode_ops.py``); the write path quantizes in the same jitted
program that produced the KV.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import get_logger

log = get_logger(__name__)

#: physical block reserved for padded table entries / inactive slots
NULL_BLOCK = 0

KV_QUANT_MODES = ("off", "int8")


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(…, head)-channel symmetric int8 over the trailing head_dim
    (``ops/quant.py`` granularity): ``(q, scale)`` with scale f32
    keepdims. Zero vectors pin scale 1.0 (dequant stays exact zeros)."""
    from ..ops.quant import quantize_channel

    return quantize_channel(x, "int8", axes=-1)


def dequantize_kv(q: jax.Array, scale: jax.Array) -> jax.Array:
    from ..ops.quant import dequantize

    return dequantize(q, scale)


class PagedKVCache:
    """Block-table slot allocator + the pooled device arrays.

    The device pool is a dict (a pytree the jitted programs thread):
    ``{"k": (L, N, B, H, D), "v": ...}`` plus ``k_scale``/``v_scale``
    ``(L, N, B, H, 1)`` f32 leaves under ``kv_quant="int8"``.
    """

    def __init__(self, *, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int,
                 dtype: Any = jnp.float32, kv_quant: str = "off"):
        if kv_quant not in KV_QUANT_MODES:
            raise ValueError(f"unknown kv_quant {kv_quant!r}; expected one "
                             f"of {KV_QUANT_MODES}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {NULL_BLOCK} is the "
                f"reserved null block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.kv_quant = kv_quant
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        store_dtype = jnp.int8 if kv_quant == "int8" else dtype
        self.pool: dict[str, jax.Array] = {
            "k": jnp.zeros(shape, store_dtype),
            "v": jnp.zeros(shape, store_dtype),
        }
        if kv_quant == "int8":
            s_shape = shape[:-1] + (1,)
            self.pool["k_scale"] = jnp.ones(s_shape, jnp.float32)
            self.pool["v_scale"] = jnp.ones(s_shape, jnp.float32)
        # host-side allocator state: block NULL_BLOCK never enters the
        # free list — it is the dump target for masked lanes
        self._free: list[int] = list(range(num_blocks - 1, NULL_BLOCK, -1))
        self._tables: dict[int, list[int]] = {}
        self._lens: dict[int, int] = {}
        # accounting (the "alloc/free/defrag" ledger): lifetime counters
        # plus the high-water mark — what capacity planning reads
        self.alloc_count = 0
        self.free_count = 0
        self.high_water_blocks = 0

    # -- placement ---------------------------------------------------------
    @staticmethod
    def head_sharding_spec():
        """``PartitionSpec`` sharding the pool's HEAD axis over the
        ``model`` mesh axis — ``(L, N, B, H, D)`` dim 3, and dim 3 of
        the ``(L, N, B, H, 1)`` scale leaves alike (int8 scales are
        per-(token, head), so they shard with their heads). The one
        pool-placement rule: the engine's GSPMD path device_puts with
        it, and the TP ring decode's region in_specs reuse it — block
        tables and the free list stay host-side and replicated, so the
        allocator never learns the mesh exists."""
        from jax.sharding import PartitionSpec as P

        from ..runtime.context import MODEL_AXIS

        return P(None, None, None, MODEL_AXIS, None)

    # -- byte accounting ---------------------------------------------------
    def bytes_per_token(self) -> float:
        """Resident KV bytes one token costs across all layers — the
        capacity denominator (int8 ≈ itemsize 1 + 4/D scale overhead
        per K and V)."""
        per = 2 * self.num_heads * self.head_dim  # K and V elements
        if self.kv_quant == "int8":
            return self.num_layers * (per * 1 + 2 * self.num_heads * 4)
        return self.num_layers * per * float(
            jnp.dtype(self.pool["k"].dtype).itemsize)

    def pool_bytes(self, *, model_shards: int = 1) -> int:
        """Resident pool bytes per model shard: the whole pool at
        ``model_shards=1``; under :meth:`head_sharding_spec` each shard
        holds ``H / model_shards`` heads of every leaf."""
        total = sum(int(v.size) * jnp.dtype(v.dtype).itemsize
                    for v in self.pool.values())
        return total // max(model_shards, 1)

    # -- allocation --------------------------------------------------------
    def blocks_needed(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 0) // self.block_size)

    def free_blocks(self) -> int:
        return len(self._free)

    def can_alloc(self, n_tokens: int) -> bool:
        return self.blocks_needed(n_tokens) <= len(self._free)

    def alloc(self, seq_id: int, n_tokens: int) -> list[int]:
        """Allocate the block list for a new ``seq_id`` holding
        ``n_tokens``; refuses (ValueError) when the pool cannot cover
        it — the scheduler must check :meth:`can_alloc` first."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id} already holds an allocation")
        need = self.blocks_needed(n_tokens)
        if need > len(self._free):
            raise ValueError(
                f"KV pool exhausted: seq {seq_id} needs {need} blocks, "
                f"{len(self._free)} free of {self.num_blocks - 1} usable")
        blocks = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = blocks
        self._lens[seq_id] = n_tokens
        self.alloc_count += need
        self.high_water_blocks = max(self.high_water_blocks,
                                     self.blocks_used())
        return list(blocks)

    def append_slot(self, seq_id: int) -> tuple[int, int]:
        """Advance ``seq_id`` by one token: ``(physical_block, offset)``
        of the slot the next KV write lands in, allocating a fresh
        block exactly when the length crosses a block boundary — the
        no-recompile growth path."""
        if seq_id not in self._tables:
            raise KeyError(f"seq {seq_id} holds no allocation")
        pos = self._lens[seq_id]
        blk_idx, off = divmod(pos, self.block_size)
        if blk_idx == len(self._tables[seq_id]):
            if not self._free:
                raise ValueError(
                    f"KV pool exhausted growing seq {seq_id} past "
                    f"{pos} tokens")
            self._tables[seq_id].append(self._free.pop())
            self.alloc_count += 1
            self.high_water_blocks = max(self.high_water_blocks,
                                         self.blocks_used())
        self._lens[seq_id] = pos + 1
        return self._tables[seq_id][blk_idx], off

    def truncate(self, seq_id: int, n_tokens: int) -> int:
        """Roll ``seq_id`` back to ``n_tokens``: blocks past
        ``ceil(n/block_size)`` return to the free list (LIFO, like
        :meth:`free`) and the logical length clamps. The speculative-
        decode rejection path — a rejected draft tail is popped here,
        never copied or recompiled. Returns blocks released. Growing
        through truncate is refused (that is :meth:`append_slot`'s
        job)."""
        if seq_id not in self._tables:
            raise KeyError(f"seq {seq_id} holds no allocation")
        if n_tokens > self._lens[seq_id]:
            raise ValueError(
                f"truncate(seq {seq_id}, {n_tokens}) would GROW the "
                f"sequence (length {self._lens[seq_id]}); use "
                "append_slot to extend")
        keep = self.blocks_needed(n_tokens)
        blocks = self._tables[seq_id]
        released = 0
        while len(blocks) > keep:
            self._free.append(blocks.pop())
            released += 1
        self.free_count += released
        self._lens[seq_id] = n_tokens
        return released

    def free(self, seq_id: int) -> int:
        """Return ``seq_id``'s blocks to the pool; count released."""
        blocks = self._tables.pop(seq_id, None)
        if blocks is None:
            return 0
        self._lens.pop(seq_id, None)
        self._free.extend(reversed(blocks))
        self.free_count += len(blocks)
        return len(blocks)

    # -- lookups -----------------------------------------------------------
    def table(self, seq_id: int) -> list[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id: int) -> int:
        return self._lens[seq_id]

    def set_seq_len(self, seq_id: int, n: int) -> None:
        """Clamp the logical length (prefill writes padded bucket
        blocks; the real length is what attention must see)."""
        if self.blocks_needed(n) > len(self._tables[seq_id]):
            raise ValueError(
                f"seq {seq_id}: length {n} exceeds its "
                f"{len(self._tables[seq_id])}-block allocation")
        self._lens[seq_id] = n

    def padded_table(self, seq_id: int, max_blocks: int) -> np.ndarray:
        """``(max_blocks,)`` int32 physical-block vector, padded with
        the null block — one row of the decode program's block table."""
        blocks = self._tables[seq_id]
        if len(blocks) > max_blocks:
            raise ValueError(
                f"seq {seq_id} holds {len(blocks)} blocks > decode "
                f"program's max_blocks {max_blocks}")
        row = np.full((max_blocks,), NULL_BLOCK, np.int32)
        row[: len(blocks)] = blocks
        return row

    # -- accounting --------------------------------------------------------
    def blocks_used(self) -> int:
        return sum(len(b) for b in self._tables.values())

    def stats(self) -> dict[str, Any]:
        """The allocator ledger: occupancy, internal fragmentation
        (allocated slots minus resident tokens — bounded by one partial
        block per sequence; the number a dense cache cannot bound), and
        the lifetime alloc/free counters."""
        used = self.blocks_used()
        tokens = sum(self._lens.values())
        return {
            "blocks_total": self.num_blocks - 1,  # null block excluded
            "blocks_used": used,
            "blocks_free": len(self._free),
            "tokens_resident": tokens,
            "frag_slots": used * self.block_size - tokens,
            "high_water_blocks": self.high_water_blocks,
            "alloc_count": self.alloc_count,
            "free_count": self.free_count,
            "bytes_per_token": self.bytes_per_token(),
            "kv_quant": self.kv_quant,
        }
