"""Serving engine (r19): prefill + per-token decode over a paged KV
cache, with continuous batching — the inference story for the
ROADMAP's "millions of users".

The expensive training primitives were idle outside the train loop;
here they serve: ``ops/flash.py``/``ops/attention.py`` run the bucketed
prefill, ``ops/lm_head.greedy_decode`` (the online-argmax bundle,
extracted) samples without materialising logits, and
``CheckpointManager.restore_raw`` + the r18 reshard converter load a
training checkpoint at ANY layer layout straight into the serving
template. See ``serve/engine.py`` for the architecture note.

r20 adds speculative decoding (``serve/spec.py``): a shallow
shared-embedding draft proposes k tokens, the target verifies the
window in one dispatch, greedy longest-prefix acceptance keeps the
output token-for-token identical to plain greedy decode —
``ServeConfig(spec_k=..., draft_depth=...)`` turns it on.
"""

from .engine import ServeConfig, ServeEngine  # noqa: F401
from .kv_cache import PagedKVCache  # noqa: F401
from .scheduler import ContinuousScheduler, Request  # noqa: F401
from .spec import (AdaptiveK, SpecRunner, adopt_draft_checkpoint,  # noqa: F401
                   draft_seq_id, make_draft_params)
