"""Serving engine (r19): prefill + per-token decode over a paged KV
cache, with continuous batching — the inference story for the
ROADMAP's "millions of users".

The expensive training primitives were idle outside the train loop;
here they serve: ``ops/flash.py``/``ops/attention.py`` run the bucketed
prefill, ``ops/lm_head.greedy_decode`` (the online-argmax bundle,
extracted) samples without materialising logits, and
``CheckpointManager.restore_raw`` + the r18 reshard converter load a
training checkpoint at ANY layer layout straight into the serving
template. See ``serve/engine.py`` for the architecture note.

r20 adds speculative decoding (``serve/spec.py``): a shallow
shared-embedding draft proposes k tokens, the target verifies the
window in one dispatch, greedy longest-prefix acceptance keeps the
output token-for-token identical to plain greedy decode —
``ServeConfig(spec_k=..., draft_depth=...)`` turns it on.

r21 adds tensor-parallel decode (``serve/model.py``): with
``tp_overlap=True`` and a mesh carrying a live model axis, the decode
step runs model-sharded end to end — fc1/fused-qkv as all-gather-matmul
rings, fc2/out-proj as matmul-reduce-scatter rings (the r14 collective
matmuls, forward-only), attention heads and the paged KV pool split over
the model axis, and ``ops/lm_head.tp_greedy_decode`` sampling over
resident vocab shards with the r17 quantized ring wire. Output stays
token-for-token identical to single-replica greedy; ``describe_tp()``
reports degree, per-step ring wire and per-shard KV residency.
"""

from .engine import ServeConfig, ServeEngine  # noqa: F401
from .kv_cache import PagedKVCache  # noqa: F401
from .scheduler import ContinuousScheduler, Request  # noqa: F401
from .spec import (AdaptiveK, SpecRunner, adopt_draft_checkpoint,  # noqa: F401
                   draft_seq_id, make_draft_params)
