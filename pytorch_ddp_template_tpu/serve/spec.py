"""Speculative decoding: a shallow draft model proposes k tokens, the
target scores the whole window in ONE step (Leviathan et al.).

Decode is memory-bound — each target step reads every weight to emit
one token per slot.  Speculative decoding spends a shallow draft's
FLOPs to turn k sequential target steps into one batched verification:
the draft autoregressively proposes ``d_1..d_k``; the target then
scores the window ``[t_last, d_1..d_{k-1}]`` as k staggered decode
lanes in a single compiled program (:func:`serve.model.verify_forward`)
and greedy longest-prefix acceptance keeps the longest draft prefix
matching the target argmax plus ONE free correction token.

**Lossless by construction.** Let ``m`` be the longest prefix with
``d_i == y_i`` where ``y_i`` is the target argmax after consuming the
window input at position ``n+i-1``.  The round commits
``d_1..d_m + y_{m+1}`` (or ``d_1..d_k`` on full acceptance) — every
committed token is, by induction, exactly the token target-only greedy
decode would have produced from the same context, so speculative
output is token-for-token identical to the baseline (pinned as an
engine-level equality test, ``tests/test_spec.py``).

**KV lockstep + free-list rollback.** Draft and target write the SAME
positions ``n..n+k-1`` each round (the draft through its own lanes in
the shared paged pool — distinct ``seq_id``s via :func:`draft_seq_id`,
occupying layers ``0..depth-1`` of draft-owned blocks; layers past the
draft's depth in those blocks are idle, the documented cost of sharing
one pool).  Rejection truncates BOTH sequences to ``n + min(m+1, k)``
— :meth:`serve.kv_cache.PagedKVCache.truncate`, a free-list pop, never
a copy or a recompile.

**Compile-count contract.** Exactly two compiled decode programs ever:
the draft step (fixed ``(max_slots,)`` lanes over the depth-sliced
pool) and the verify step (fixed ``(max_slots, spec_k)`` window —
short rounds pad into null-block scrap lanes exactly like bucketed
prefill).  Extends the r19 zero-recompile pin;
``ServeEngine.decode_programs()`` must report 2 in spec mode, however
sequences grow or k adapts.

**The draft.** Default: the target's first ``draft_depth`` scanned
layers plus its embedding table, positional table and final LayerNorm,
shared BY REFERENCE (no extra copies resident; the tied LM head is the
same shared table).  Or an independently trained shallow checkpoint
(``--num_layers`` makes training one a one-flag job) restored through
the same ``convert_tree_layout`` seam — its decoder stack and final
LayerNorm serve, the embedding/positional tables and tied head still
come from the target (train the draft against the target's frozen
embeddings for best acceptance; acceptance only affects SPEED, never
output).

**Adaptive k** (:class:`AdaptiveK`): per-request TCP-style control —
full acceptance grows the next window by one (up to ``spec_k``), a
rejection shrinks it to what the round proved (``accepted + 1``), and
a rolling EWMA acceptance rate feeds the ``tpuddp_serve_spec_*``
gauges.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import get_logger
from .kv_cache import NULL_BLOCK, quantize_kv
from .model import decode_forward, prefill_forward, stacked_layers, \
    tp_decode_forward, tp_verify_forward, verify_forward
from .scheduler import Request

log = get_logger(__name__)


def draft_seq_id(request_id: int) -> int:
    """The draft twin's allocator key: request ids are non-negative, so
    the negative mirror never collides."""
    return -request_id - 1


def _stack_depth(layers: dict) -> int:
    return jax.tree_util.tree_leaves(layers)[0].shape[0]


def make_draft_params(target_params: dict, depth: int) -> dict:
    """The default draft: the target's first ``depth`` scanned layers.

    Embedding table, positional table and final LayerNorm are shared BY
    REFERENCE (the same arrays — zero extra HBM beyond the sliced
    stack); truncated-depth transformers keep a usable next-token
    distribution because the residual stream feeds the tied head at
    every depth.  Acceptance rate is the draft's only quality metric —
    output is lossless regardless.
    """
    layers = stacked_layers(target_params)
    n = _stack_depth(layers)
    if not 1 <= depth <= n:
        raise ValueError(
            f"draft_depth {depth} out of range: the sliced draft takes "
            f"1..{n} of the target's layers (draft_depth == num_layers "
            "is the always-accept degenerate draft — valid, but all "
            "FLOPs and no win)")
    sliced = jax.tree_util.tree_map(lambda x: x[:depth], layers)
    return {"wte": target_params["wte"], "wpe": target_params["wpe"],
            "decoder": {"layers": sliced},
            "final_ln": target_params["final_ln"]}


def adopt_draft_checkpoint(raw_params: dict, target_params: dict
                           ) -> tuple[dict, int]:
    """An independently trained shallow draft, through the SAME seam a
    target checkpoint loads by: unbox, ``convert_tree_layout`` to the
    scanned template, validate geometry.  Its decoder stack and final
    LayerNorm serve; the embedding/positional tables (and therefore the
    tied head) are the TARGET's — one table resident, and the
    ``--num_layers`` draft-training workflow is told to train against
    frozen target embeddings for acceptance.  Returns
    ``(draft_params, depth)`` with depth inferred from the stack."""
    import flax.linen as nn

    from ..parallel.stacking import convert_tree_layout

    p = nn.meta.unbox(raw_params)
    p = convert_tree_layout(p, "scanned", strict=False)
    layers = stacked_layers(p)
    depth = _stack_depth(layers)
    target_depth = _stack_depth(stacked_layers(target_params))
    if depth > target_depth:
        raise ValueError(
            f"draft checkpoint is DEEPER than the target ({depth} > "
            f"{target_depth} layers): the draft shares the target's "
            "paged pool and can only occupy a layer-prefix of it")
    e_t = target_params["wte"]["embedding"].shape[-1]
    e_d = layers["ln_attn"]["scale"].shape[-1]
    if e_d != e_t:
        raise ValueError(
            f"draft embed width {e_d} != target {e_t}: the draft reads "
            "the target's shared embedding table — train it at the "
            "target's width (--num_layers changes depth only)")
    draft = {"wte": target_params["wte"], "wpe": target_params["wpe"],
             "decoder": {"layers": layers}, "final_ln": p["final_ln"]}
    return draft, depth


class AdaptiveK:
    """Per-request draft-window controller + rolling acceptance.

    TCP-shaped and deterministic (unit-tested as pure bookkeeping):
    full acceptance grows the request's next window by 1 up to
    ``k_max``; any rejection shrinks it to ``accepted + 1`` — the
    length the round just proved profitable.  State lives ON the
    :class:`~.scheduler.Request` (``draft_k``/``spec_drafted``/
    ``spec_accepted``), so it joins and evicts with the request;
    the controller itself holds only the global EWMA.
    """

    def __init__(self, k_max: int, *, enabled: bool = True,
                 ema: float = 0.3):
        if k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {k_max}")
        self.k_max = k_max
        self.enabled = enabled
        self.ema = ema
        self.accept_rate = 1.0  # rolling EWMA of accepted/drafted
        self._rounds = 0

    def k_for(self, req: Request) -> int:
        """The window to draft for this request's next round."""
        if not self.enabled:
            return self.k_max
        if req.draft_k < 1:
            req.draft_k = self.k_max  # start optimistic; one bad round
            #                           shrinks it to evidence
        return req.draft_k

    def update(self, req: Request, *, drafted: int, accepted: int) -> None:
        req.spec_drafted += drafted
        req.spec_accepted += accepted
        rate = accepted / drafted if drafted else 0.0
        if self._rounds == 0:
            self.accept_rate = rate
        else:
            self.accept_rate = (self.ema * rate
                                + (1.0 - self.ema) * self.accept_rate)
        self._rounds += 1
        if not self.enabled:
            return
        if accepted >= drafted:
            req.draft_k = min(req.draft_k + 1, self.k_max)
        else:
            req.draft_k = max(1, accepted + 1)


class SpecRunner:
    """The engine's speculative-decode path: draft loop → one verify
    dispatch → longest-prefix accept → symmetric KV rollback.

    Owns the two spec-mode compiled decode programs (draft step,
    verify step) and the draft's bucketed prefill; the engine delegates
    its decode phase here when ``ServeConfig.spec_k > 0`` and keeps
    everything else (admission, scheduling, eviction, checkpoints).
    """

    def __init__(self, engine, draft_params: dict, depth: int):
        self.engine = engine
        self.depth = depth
        self.draft_params = draft_params
        cfg = engine.cfg
        self.ctrl = AdaptiveK(cfg.spec_k, enabled=cfg.spec_adaptive)
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._draft_prefill_fn = jax.jit(self._draft_prefill_math,
                                         donate_argnums=donate)
        self._draft_decode_fn = jax.jit(self._draft_decode_math,
                                        donate_argnums=donate)
        self._verify_fn = jax.jit(self._verify_math, donate_argnums=donate)
        # the acceptance ledger (stats()/gauges read these)
        self.draft_s = 0.0       # draft wall (prefill + decode loop)
        self.verify_s = 0.0      # verify dispatch + acceptance sync
        self.draft_steps = 0     # draft decode dispatches
        self.verify_steps = 0    # verify dispatches
        self.slot_rounds = 0     # (active slot, round) pairs
        self.drafted_total = 0   # draft tokens proposed
        self.accepted_total = 0  # draft tokens accepted
        self.committed_total = 0  # tokens emitted through verify rounds

    # -- jitted math -------------------------------------------------------
    def _sub_pool(self, pool: dict) -> dict:
        """The draft's view: layers ``0..depth-1`` of every pool leaf
        (matches the scan length of its stacked params)."""
        return {k: v[: self.depth] for k, v in pool.items()}

    def _merge_pool(self, pool: dict, sub: dict) -> dict:
        return {k: pool[k].at[: self.depth].set(sub[k]) for k in pool}

    def _draft_prefill_math(self, params, pool, ids, block_ids):
        """Insert the prompt's DRAFT KV (depth-sliced layer prefix of
        the shared pool); the draft's prefill output is discarded — the
        first token is the target prefill's, for losslessness."""
        eng = self.engine
        _, k, v = prefill_forward(params, ids, dtype=eng.dtype,
                                  attn_impl=eng.attn_impl)
        lyr, _, t, h, d = k.shape
        nb = t // eng.cfg.block_size
        k = k.reshape(lyr, nb, eng.cfg.block_size, h, d)
        v = v.reshape(lyr, nb, eng.cfg.block_size, h, d)
        pool = dict(pool)
        if eng.cfg.kv_quant == "int8":
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            pool["k"] = pool["k"].at[: self.depth, block_ids].set(kq)
            pool["v"] = pool["v"].at[: self.depth, block_ids].set(vq)
            pool["k_scale"] = pool["k_scale"].at[
                : self.depth, block_ids].set(ks)
            pool["v_scale"] = pool["v_scale"].at[
                : self.depth, block_ids].set(vs)
        else:
            pool["k"] = pool["k"].at[: self.depth, block_ids].set(
                k.astype(pool["k"].dtype))
            pool["v"] = pool["v"].at[: self.depth, block_ids].set(
                v.astype(pool["v"].dtype))
        return pool

    def _draft_decode_math(self, params, pool, tokens, positions, tables,
                           ctx_lens, write_blocks, write_offsets):
        from ..ops.lm_head import sample_tokens

        eng = self.engine
        sub = self._sub_pool(pool)
        if eng._tp > 1:
            # TP engine (r21): the draft rides the SAME ring-sharded
            # decode program shape as the target — depth-sliced pool,
            # identical per-shard head/vocab geometry (the draft shares
            # the target's padded table by reference)
            nxt, sub = tp_decode_forward(
                params, sub, tokens, positions, tables, ctx_lens,
                write_blocks, write_offsets, mesh=eng.mesh,
                dtype=eng.dtype, vocab=eng._vocab,
                kv_quant=eng.cfg.kv_quant, quant=eng._quant,
                policy=eng.cfg.sampling, vocab_block=eng.cfg.vocab_block)
            return nxt, self._merge_pool(pool, sub)
        hidden, sub = decode_forward(
            params, sub, tokens, positions, tables, ctx_lens,
            write_blocks, write_offsets, dtype=eng.dtype,
            kv_quant=eng.cfg.kv_quant)
        nxt = sample_tokens(hidden, params["wte"]["embedding"],
                            policy=eng.cfg.sampling,
                            block=eng.cfg.vocab_block)
        return nxt, self._merge_pool(pool, sub)

    def _verify_math(self, params, pool, tokens, positions, tables,
                     ctx_lens, write_blocks, write_offsets):
        from ..ops.lm_head import sample_tokens

        eng = self.engine
        if eng._tp > 1:
            # verify lanes ride the sharded program too (the lossless
            # pin is against TP greedy, so draft/verify/plain must all
            # share one math path)
            return tp_verify_forward(
                params, pool, tokens, positions, tables, ctx_lens,
                write_blocks, write_offsets, mesh=eng.mesh,
                dtype=eng.dtype, vocab=eng._vocab,
                kv_quant=eng.cfg.kv_quant, quant=eng._quant,
                policy=eng.cfg.sampling, vocab_block=eng.cfg.vocab_block)
        hidden, pool = verify_forward(
            params, pool, tokens, positions, tables, ctx_lens,
            write_blocks, write_offsets, dtype=eng.dtype,
            kv_quant=eng.cfg.kv_quant)
        y = sample_tokens(hidden, params["wte"]["embedding"],
                          policy=eng.cfg.sampling,
                          block=eng.cfg.vocab_block)
        return y, pool

    # -- per-request lifecycle ---------------------------------------------
    def prefill(self, req: Request) -> None:
        """Prefill the prompt into the DRAFT's paged lanes (same bucket,
        same null-block scrap convention as the target's prefill)."""
        eng = self.engine
        t0 = time.perf_counter()
        plen = len(req.prompt)
        did = draft_seq_id(req.id)
        eng.kv.alloc(did, plen)
        bucket = next(b for b in eng._buckets if b >= plen)
        nb_bucket = bucket // eng.cfg.block_size
        blocks = eng.kv.table(did)
        block_ids = np.full((nb_bucket,), NULL_BLOCK, np.int32)
        block_ids[: len(blocks)] = blocks
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :plen] = req.prompt
        eng.kv.pool = self._draft_prefill_fn(
            self.draft_params, eng.kv.pool, jnp.asarray(ids),
            jnp.asarray(block_ids))
        self.draft_s += time.perf_counter() - t0

    def release(self, req: Request) -> None:
        """Return the draft twin's blocks (no-op if never prefilled —
        e.g. the request finished at its own prefill)."""
        self.engine.kv.free(draft_seq_id(req.id))

    # -- the spec decode round ---------------------------------------------
    def decode_step(self, running: dict[int, Request]) -> None:
        """One speculative round for every running slot: k draft
        dispatches (device-resident token chain, no host sync), ONE
        verify dispatch, one host sync for acceptance, symmetric
        truncate of both KV sequences to the accepted length."""
        eng = self.engine
        cfg = eng.cfg
        s_lanes = cfg.max_slots
        k_cap = cfg.spec_k
        m_blocks = eng.max_blocks

        plan: dict[int, tuple[Request, int]] = {}
        base_len: dict[int, int] = {}
        feed = np.zeros((s_lanes,), np.int32)
        for slot, req in running.items():
            remaining = req.max_new_tokens - len(req.tokens)
            k_i = max(1, min(self.ctrl.k_for(req), remaining))
            plan[slot] = (req, k_i)
            base_len[slot] = eng.kv.seq_len(req.id)
            feed[slot] = req.tokens[-1]
        k_round = max(k_i for _, k_i in plan.values())

        # -- draft: k_round dispatches, token chain stays on device
        t0 = time.perf_counter()
        cur = jnp.asarray(feed)
        if eng._tp > 1:
            # the TP draft program emits REPLICATED tokens; the chain's
            # first feed must carry the same sharding or the second
            # dispatch hashes as a new program (breaking the 2-program
            # pin)
            from jax.sharding import NamedSharding, PartitionSpec
            cur = jax.device_put(
                cur, NamedSharding(eng.mesh, PartitionSpec()))
        drafts = []
        for t in range(k_round):
            positions = np.zeros((s_lanes,), np.int32)
            ctx = np.zeros((s_lanes,), np.int32)
            wb = np.full((s_lanes,), NULL_BLOCK, np.int32)
            wo = np.zeros((s_lanes,), np.int32)
            tables = np.full((s_lanes, m_blocks), NULL_BLOCK, np.int32)
            for slot, (req, k_i) in plan.items():
                if t >= k_i:
                    continue  # this slot's window is shorter: its lane
                    #           degrades to a ctx-0 null-block scrap lane
                did = draft_seq_id(req.id)
                pos = eng.kv.seq_len(did)
                blk, off = eng.kv.append_slot(did)
                positions[slot] = pos
                ctx[slot] = pos + 1
                wb[slot], wo[slot] = blk, off
                tables[slot] = eng.kv.padded_table(did, m_blocks)
            cur, eng.kv.pool = self._draft_decode_fn(
                self.draft_params, eng.kv.pool, cur,
                jnp.asarray(positions), jnp.asarray(tables),
                jnp.asarray(ctx), jnp.asarray(wb), jnp.asarray(wo))
            drafts.append(cur)
            self.draft_steps += 1
        draft_stack = jnp.stack(drafts, axis=1)  # (S, k_round): d_1..d_k
        jax.block_until_ready(draft_stack)  # honest draft/verify split
        self.draft_s += time.perf_counter() - t0

        # -- verify: the whole window in ONE target dispatch
        t1 = time.perf_counter()
        positions = np.zeros((s_lanes, k_cap), np.int32)
        ctx = np.zeros((s_lanes, k_cap), np.int32)
        wb = np.full((s_lanes, k_cap), NULL_BLOCK, np.int32)
        wo = np.zeros((s_lanes, k_cap), np.int32)
        tables = np.full((s_lanes, k_cap, m_blocks), NULL_BLOCK, np.int32)
        for slot, (req, k_i) in plan.items():
            for j in range(k_i):
                pos = eng.kv.seq_len(req.id)
                blk, off = eng.kv.append_slot(req.id)
                positions[slot, j] = pos
                ctx[slot, j] = pos + 1  # lane j attends to lanes < j of
                #                         its own window (write-then-
                #                         gather inside the layer scan)
                wb[slot, j], wo[slot, j] = blk, off
            # one table snapshot AFTER the window's appends covers every
            # lane: trailing blocks a short lane hasn't reached are
            # masked by its context length
            tables[slot, :k_i] = eng.kv.padded_table(req.id, m_blocks)
        # window inputs [t_last, d_1..d_{k-1}]; the tail past k_round+1
        # pads with null-lane zeros
        window = jnp.concatenate([jnp.asarray(feed)[:, None], draft_stack],
                                 axis=1)
        if window.shape[1] < k_cap:
            window = jnp.pad(window,
                             ((0, 0), (0, k_cap - window.shape[1])))
        y_dev, eng.kv.pool = self._verify_fn(
            eng.params, eng.kv.pool, window[:, :k_cap],
            jnp.asarray(positions), jnp.asarray(tables), jnp.asarray(ctx),
            jnp.asarray(wb), jnp.asarray(wo))
        y = np.asarray(y_dev)           # (S, k_cap): y[s, j] = y_{j+1}
        d = np.asarray(draft_stack)     # (S, k_round): d[s, j] = d_{j+1}
        self.verify_s += time.perf_counter() - t1
        self.verify_steps += 1

        # -- greedy longest-prefix acceptance + symmetric rollback
        for slot, (req, k_i) in plan.items():
            m = 0
            while m < k_i and d[slot, m] == y[slot, m]:
                m += 1
            committed = [int(tok) for tok in d[slot, :m]]
            if m < k_i:
                committed.append(int(y[slot, m]))  # the free correction
            new_len = base_len[slot] + min(m + 1, k_i)
            eng.kv.truncate(req.id, new_len)
            eng.kv.truncate(draft_seq_id(req.id), new_len)
            self.ctrl.update(req, drafted=k_i, accepted=m)
            self.drafted_total += k_i
            self.accepted_total += m
            self.slot_rounds += 1
            for tok in committed:
                req.tokens.append(tok)
                eng.tokens_out += 1
                self.committed_total += 1
                eng._maybe_finish(req, tok)
                if req.state == "finished":
                    break  # eos mid-window: later tokens are discarded
                    #        (exactly what the baseline never emits)

    # -- reporting ---------------------------------------------------------
    def decode_program_count(self) -> int:
        """Spec mode's share of the zero-recompile pin: draft + verify
        must each stay at ONE compiled program."""
        return (self._draft_decode_fn._cache_size()
                + self._verify_fn._cache_size())

    def prefill_program_count(self) -> int:
        return self._draft_prefill_fn._cache_size()

    def stats_fields(self, running: dict[int, Request]) -> dict[str, Any]:
        """``serve_spec_*`` gauges — ride the flat serve record onto
        ``/status`` and ``/metrics`` untouched."""
        k_live = [r.draft_k if r.draft_k >= 1 else self.ctrl.k_max
                  for r in running.values()]
        return {
            "serve_spec_k_max": self.ctrl.k_max,
            "serve_spec_draft_depth": self.depth,
            "serve_spec_k_mean": (sum(k_live) / len(k_live)
                                  if k_live else 0.0),
            "serve_spec_accept_rate": (
                self.accepted_total / self.drafted_total
                if self.drafted_total else 0.0),
            "serve_spec_accept_rate_rolling": self.ctrl.accept_rate,
            "serve_spec_accepted_per_target_step": (
                self.committed_total / self.slot_rounds
                if self.slot_rounds else 0.0),
            "serve_spec_drafted_total": self.drafted_total,
            "serve_spec_accepted_total": self.accepted_total,
            "serve_spec_committed_total": self.committed_total,
            "serve_spec_draft_steps": self.draft_steps,
            "serve_spec_verify_steps": self.verify_steps,
            "serve_spec_draft_s_total": self.draft_s,
            "serve_spec_verify_s_total": self.verify_s,
        }
