"""Serving forward passes: the GPT decoder as pure functions over the
scanned param tree.

Training applies the model through flax modules; serving wants two
*different* programs over the SAME parameters — a bucketed full-context
prefill and a one-token-per-sequence decode reading the paged KV cache
— and neither fits the module's ``__call__`` (which recomputes every
position's KV every token). This module re-expresses the
``models/gpt.GptDecoder`` math as pure functions over the scanned
``{"wte", "wpe", "decoder": {"layers": stacked}, "final_ln"}`` tree:

- the primitive sequence matches flax's exactly (``lax.dot_general``
  with DenseGeneral's dimension numbers, the fast-variance LayerNorm,
  ``jax.nn.gelu``), so :func:`prefill_forward` is **bit-identical** to
  ``GptDecoder(fused_head=True).apply`` on the prompt — the
  checkpoint→serving seam is testable as equality, not tolerance;
- both passes drive ONE ``lax.scan`` over the stacked layer weights
  (the r7 compile-time contract), and the decode scan threads the KV
  pool's layer axis as scan xs/ys — layer ``l``'s blocks are read and
  written inside iteration ``l``, never gathered whole.

Supported templates: the plain GSPMD path (model sharding comes from
the params'/pool's NamedShardings, GSPMD partitions these functions
like any other jitted program), and — since r21 — the ``--tp_overlap``
ring path: :func:`tp_decode_forward` re-expresses the decode step as
explicit all-gather-matmul / matmul-reduce-scatter rings under ONE
``shard_map`` region (slots play the ring's sequence axis, attention
heads and the paged pool shard over ``model``, and the LM head is the
rotating-argmax ring). MoE/pipe templates are still refused by the
engine with intent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from .decode_ops import paged_attention
from .kv_cache import quantize_kv


def layer_norm(x: jax.Array, p: dict) -> jax.Array:
    """flax ``nn.LayerNorm(dtype=f32)`` exactly: fast-variance stats
    (``E[x^2] - E[x]^2`` clipped at 0), ``rsqrt``, scale-into-mul —
    the same primitive sequence, for bitwise parity."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    mean2 = jnp.mean(lax.square(xf), axis=-1, keepdims=True)
    var = jnp.maximum(0.0, mean2 - lax.square(mean))
    y = xf - mean
    mul = lax.rsqrt(var + 1e-6) * p["scale"].astype(jnp.float32)
    return y * mul + p["bias"].astype(jnp.float32)


def dense(x: jax.Array, p: dict, n_axes: int, dtype) -> jax.Array:
    """``nn.DenseGeneral`` contraction over the trailing ``n_axes``
    dims of ``x`` (kernel's leading dims), bias broadcast-added."""
    x = x.astype(dtype)
    kernel = p["kernel"].astype(dtype)
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    kaxes = tuple(range(n_axes))
    y = lax.dot_general(x, kernel, ((axes, kaxes), ((), ())))
    return y + p["bias"].astype(dtype)


def embed_tokens(params: dict, input_ids: jax.Array, positions: jax.Array,
                 dtype) -> jax.Array:
    """``wte[ids] + wpe[pos]`` — the flax ``nn.Embed`` lookups."""
    wte = params["wte"]["embedding"].astype(dtype)
    wpe = params["wpe"]["embedding"].astype(dtype)
    return jnp.take(wte, input_ids, axis=0) + jnp.take(wpe, positions, axis=0)


def stacked_layers(params: dict) -> dict:
    """The scanned ``(L, ...)`` block-param stack of the decoder."""
    layers = params["decoder"].get("layers")
    if layers is None:
        raise ValueError(
            "serving template needs the scanned layer layout "
            "(decoder/layers stacked params); run the checkpoint through "
            "parallel.stacking.convert_tree_layout(..., 'scanned') — "
            "ServeEngine.from_checkpoint does this automatically")
    return layers


def _attn_qkv(p: dict, x: jax.Array, dtype):
    q = dense(x, p["attention"]["query"], 1, dtype)
    k = dense(x, p["attention"]["key"], 1, dtype)
    v = dense(x, p["attention"]["value"], 1, dtype)
    return q, k, v


def _block_prefill(p: dict, x: jax.Array, dtype, attn_impl: str):
    """One pre-LN decoder block over the full prompt ``x (B, T, E)``;
    returns ``(x, (k, v))`` with the block's KV for cache insertion."""
    h = layer_norm(x, p["ln_attn"]).astype(dtype)
    q, k, v = _attn_qkv(p, h, dtype)
    a = attention(q, k, v, causal=True, impl=attn_impl)
    a = dense(a, p["attention"]["out"], 2, dtype)
    x = x + a
    h = layer_norm(x, p["ln_mlp"]).astype(dtype)
    h = dense(h, p["mlp"]["fc1"], 1, dtype)
    h = jax.nn.gelu(h)
    h = dense(h, p["mlp"]["fc2"], 1, dtype)
    return x + h, (k, v)


def prefill_forward(params: dict, input_ids: jax.Array, *, dtype,
                    attn_impl: str = "auto"):
    """Full-context forward of the prompt batch ``(B, T)``.

    Returns ``(hidden, k, v)``: ``hidden (B, T, E)`` after the final
    LayerNorm (exactly ``GptDecoder(fused_head=True).apply``), and the
    per-layer KV ``(L, B, T, H, D)`` for paged-cache insertion.
    """
    t = input_ids.shape[1]
    x = embed_tokens(params, input_ids, jnp.arange(t), dtype)

    def body(carry, p):
        y, kv = _block_prefill(p, carry, dtype, attn_impl)
        return y, kv

    x, (k, v) = lax.scan(body, x, stacked_layers(params))
    hidden = layer_norm(x, params["final_ln"]).astype(dtype)
    return hidden, k, v


def _write_pool(pool_l: dict, key: str, val: jax.Array,
                write_blocks: jax.Array, write_offsets: jax.Array,
                kv_quant: str) -> dict:
    """Scatter one decode step's ``val (S, H, D)`` into the layer's
    physical blocks at ``(write_blocks, write_offsets)`` per slot.
    Inactive slots target the null block (the engine points them
    there) — a harmless dump the mask never reads."""
    out = dict(pool_l)
    if kv_quant == "int8":
        q, s = quantize_kv(val)
        out[key] = pool_l[key].at[write_blocks, write_offsets].set(q)
        out[key + "_scale"] = pool_l[key + "_scale"].at[
            write_blocks, write_offsets].set(s)
    else:
        out[key] = pool_l[key].at[write_blocks, write_offsets].set(
            val.astype(pool_l[key].dtype))
    return out


def decode_forward(params: dict, pool: dict, token_ids: jax.Array,
                   positions: jax.Array, tables: jax.Array,
                   context_lens: jax.Array, write_blocks: jax.Array,
                   write_offsets: jax.Array, *, dtype,
                   kv_quant: str = "off"):
    """One decode step for ``S`` slots: embed the last token, run the
    scanned stack with per-layer (write-KV → paged attention), final
    LayerNorm. Returns ``(hidden (S, E), pool)`` with the pool's layer
    axis updated in the same scan that consumed it.

    ``context_lens`` INCLUDE the token being decoded (its KV is written
    before the gather, so a token attends to itself — the causal
    diagonal); inactive slots carry ``context_len 0`` and a null-block
    write target, and their hidden rows are garbage the engine ignores.
    """
    x = embed_tokens(params, token_ids, positions, dtype)  # (S, E)

    def body(carry, layer):
        p, pool_l = layer
        h = layer_norm(carry, p["ln_attn"]).astype(dtype)
        q, k, v = _attn_qkv(p, h, dtype)                   # (S, H, D)
        pool_l = _write_pool(pool_l, "k", k, write_blocks, write_offsets,
                             kv_quant)
        pool_l = _write_pool(pool_l, "v", v, write_blocks, write_offsets,
                             kv_quant)
        a = paged_attention(
            q, pool_l["k"], pool_l["v"], tables, context_lens,
            k_scale=pool_l.get("k_scale"), v_scale=pool_l.get("v_scale"))
        a = dense(a, p["attention"]["out"], 2, dtype)
        y = carry + a
        h = layer_norm(y, p["ln_mlp"]).astype(dtype)
        h = dense(h, p["mlp"]["fc1"], 1, dtype)
        h = jax.nn.gelu(h)
        h = dense(h, p["mlp"]["fc2"], 1, dtype)
        return y + h, pool_l

    x, pool = lax.scan(body, x, (stacked_layers(params), pool))
    hidden = layer_norm(x, params["final_ln"]).astype(dtype)
    return hidden, pool


def verify_forward(params: dict, pool: dict, token_ids: jax.Array,
                   positions: jax.Array, tables: jax.Array,
                   context_lens: jax.Array, write_blocks: jax.Array,
                   write_offsets: jax.Array, *, dtype,
                   kv_quant: str = "off"):
    """Score a k-token draft window for every slot in ONE step — the
    speculative-decode batch-verify path.

    Each slot's window of ``k`` consecutive draft positions flattens
    into ``k`` independent decode lanes sharing that slot's block
    table, with STAGGERED context lengths (lane ``j`` sees positions
    ``< positions[s, j] + 1``): inside :func:`decode_forward`'s scan
    every layer writes the whole window's KV before its paged-attention
    gather, so lane ``j`` attends to lanes ``< j`` of the same window —
    intra-window causality without a new kernel, and the target scores
    all ``k`` draft positions in one compiled program.

    Window tails past a slot's live draft length (``k`` rarely fills
    the fixed verify bucket) follow the bucketed-prefill scrap
    convention: ``context_len 0``, null-block write target — the lane
    computes garbage the mask never reads and the scatter dumps into
    block 0's scrap space (unit-pinned).

    Args:
      token_ids, positions, context_lens, write_blocks, write_offsets:
        ``(S, K)`` per-slot windows.
      tables: ``(S, K, max_blocks)`` — the slot's table replicated per
        lane (extra trailing blocks are masked by the lane's context).

    Returns ``(hidden (S, K, E), pool)``.
    """
    s, k = token_ids.shape

    def flat(a):
        return a.reshape((s * k,) + a.shape[2:])

    hidden, pool = decode_forward(
        params, pool, flat(token_ids), flat(positions), flat(tables),
        flat(context_lens), flat(write_blocks), flat(write_offsets),
        dtype=dtype, kv_quant=kv_quant)
    return hidden.reshape(s, k, -1), pool


# -- TP ring decode (r21): the decode step as explicit collective rings ----
#
# Decode activations are one token per slot — ``(S, E)`` — so the slot
# axis plays the role the sequence axis plays in training's decomposed
# stack (``parallel/collective_matmul.py``): each shard holds its
# ``S/n`` home slots, the fused-qkv/fc1 column matmuls all-gather the
# slot chunks around the ring while producing head-/mlp-sharded
# activations for ALL slots, paged attention runs on the local H/n head
# shard of the pool, and the out/fc2 row matmuls reduce-scatter back to
# the home chunk. Everything — embed, the layer scan, the rotating-
# argmax LM head — lives in ONE ``shard_map`` region, so the engine's
# compile contract is unchanged: the TP decode step is still exactly
# one jitted program. Forward-only: the training kernels' custom_vjp
# never runs (no grad is taken through serving).


def serving_param_spec(path, *, tp_head: bool = False):
    """``PartitionSpec`` for one serving-template leaf — the ONE spec
    rule shared by ``engine.place_for_serving`` (placement) and
    :func:`tp_decode_forward` (the region's in_specs): attention heads
    (qkv kernel dim 2 / out kernel dim 1, behind the stacked-layer
    axis) and the MLP hidden split over ``model``; embeddings, norms
    and embed-spanning biases replicate. ``tp_head=True`` additionally
    shards the tied ``wte`` table over vocab (rows pre-padded to
    ``ops/lm_head.tp_head_geometry``) — the resident shards the
    rotating-argmax head and the vocab-parallel embed lookup consume.
    """
    from jax.sharding import PartitionSpec as P

    from ..runtime.context import MODEL_AXIS

    keys = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
    if "layers" in keys:
        name, field = keys[-2], keys[-1]
        if name in ("query", "key", "value"):
            return (P(None, None, MODEL_AXIS, None)
                    if field == "kernel" else P(None, MODEL_AXIS, None))
        if name == "out" and field == "kernel":
            return P(None, MODEL_AXIS, None, None)
        if name == "fc1":
            return (P(None, None, MODEL_AXIS)
                    if field == "kernel" else P(None, MODEL_AXIS))
        if name == "fc2" and field == "kernel":
            return P(None, MODEL_AXIS, None)
    if tp_head and keys[-2:] == ["wte", "embedding"]:
        return P(MODEL_AXIS, None)
    return P()


def tp_decode_forward(params: dict, pool: dict, token_ids: jax.Array,
                      positions: jax.Array, tables: jax.Array,
                      context_lens: jax.Array, write_blocks: jax.Array,
                      write_offsets: jax.Array, *, mesh, dtype, vocab: int,
                      kv_quant: str = "off", quant: str = "off",
                      policy: str = "greedy", vocab_block: int = 8192):
    """One model-sharded decode step for ``S`` slots: the ring twin of
    :func:`decode_forward` fused with the rotating-argmax LM head.

    Per shard, per layer: home slot chunk ``(S/n, E)`` → fused-qkv
    all-gather-matmul ring → q/k/v ``(S, H/n, D)`` for ALL slots →
    KV write + paged attention on the local head shard of the pool →
    out-projection matmul-reduce-scatter ring → home chunk; same
    column/gelu/row pattern for fc1/fc2. The embed lookup is
    vocab-parallel (each shard contributes the rows its ``wte`` shard
    owns; one tiny ``psum``), and the final hidden chunk feeds
    ``ops/lm_head.tp_sample_tokens_local`` directly — the logits row
    never exists and no shard ever holds more than ``V/n`` table rows.

    Requirements (validated by the engine with named refusals):
    ``S % n == 0`` (slots are the ring axis; scrap slots pad),
    ``num_heads % n == 0``, ``mlp_dim % n == 0``, and the tied table
    padded to ``tp_head_geometry`` rows. ``quant`` rides the r17 narrow
    wire through the stack rings and the head bundle. Block tables,
    context lens and write targets stay host-shaped and replicated —
    the allocator knows nothing about the mesh.

    Returns ``(next_tokens (S,), pool)`` — tokens, not hidden: sampling
    happens inside the region (the decode and verify paths both end in
    the head ring, so hidden never leaves the shards).
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.lm_head import tp_head_geometry, tp_sample_tokens_local
    from ..parallel.collective_matmul import (tp_column_dense_local,
                                              tp_row_dense_local,
                                              validate_tp_mesh)
    from ..parallel.shard_map_compat import shard_map
    from ..runtime.context import MODEL_AXIS

    validate_tp_mesh(mesh)
    n = mesh.shape[MODEL_AXIS]
    s = token_ids.shape[0]
    if s % n:
        raise ValueError(
            f"TP decode shards the {s} slot lanes over the model axis "
            f"({n}); max_slots must be a multiple of it")
    block, vs, pad_v = tp_head_geometry(vocab, n, vocab_block)
    rows = params["wte"]["embedding"].shape[0]
    if rows != vocab + pad_v:
        raise ValueError(
            f"TP decode needs the tied table padded to ring granularity "
            f"({vocab + pad_v} rows for vocab {vocab} on a {n}-way ring), "
            f"got {rows} — place params through the engine (it pads once "
            "at placement)")

    def local(p, pool_l, ids, pos_c, tabs, ctx, wb, wo):
        wte = p["wte"]["embedding"]              # (vs, E) vocab shard
        me = lax.axis_index(MODEL_AXIS)
        off = me * vs
        # vocab-parallel embed: ids stay REPLICATED (sharding them would
        # let the psum mix different slots' rows) — each shard
        # contributes the rows its vocab shard owns for ALL slots, one
        # (S, E) psum assembles the lookup, and the home chunk is
        # sliced out for the rings.
        hit = (ids >= off) & (ids < off + vs)
        rows = jnp.take(wte.astype(dtype),
                        jnp.clip(ids - off, 0, vs - 1), axis=0)
        x = lax.psum(rows * hit[:, None].astype(dtype), MODEL_AXIS)
        t = ids.shape[0] // n
        x = lax.dynamic_slice_in_dim(x, me * t, t, axis=0)
        x = x + jnp.take(p["wpe"]["embedding"].astype(dtype), pos_c,
                         axis=0)                 # (S/n, E) home chunk

        def body(carry, layer):
            lp, pool_l = layer
            h = layer_norm(carry, lp["ln_attn"]).astype(dtype)
            q, k, v = tp_column_dense_local(
                h[None],
                [lp["attention"]["query"]["kernel"].astype(dtype),
                 lp["attention"]["key"]["kernel"].astype(dtype),
                 lp["attention"]["value"]["kernel"].astype(dtype)],
                [lp["attention"]["query"]["bias"].astype(dtype),
                 lp["attention"]["key"]["bias"].astype(dtype),
                 lp["attention"]["value"]["bias"].astype(dtype)],
                quant=quant)                     # each (1, S, H/n, D)
            q, k, v = q[0], k[0], v[0]           # ALL slots, local heads
            pool_l = _write_pool(pool_l, "k", k, wb, wo, kv_quant)
            pool_l = _write_pool(pool_l, "v", v, wb, wo, kv_quant)
            a = paged_attention(
                q, pool_l["k"], pool_l["v"], tabs, ctx,
                k_scale=pool_l.get("k_scale"),
                v_scale=pool_l.get("v_scale"))   # (S, H/n, D)
            a = tp_row_dense_local(
                a[None], lp["attention"]["out"]["kernel"].astype(dtype),
                lp["attention"]["out"]["bias"].astype(dtype),
                quant=quant)[0]                  # (S/n, E) home chunk
            y = carry + a.astype(dtype)
            h = layer_norm(y, lp["ln_mlp"]).astype(dtype)
            h = tp_column_dense_local(
                h[None], [lp["mlp"]["fc1"]["kernel"].astype(dtype)],
                [lp["mlp"]["fc1"]["bias"].astype(dtype)],
                quant=quant)[0]                  # (1, S, mlp/n)
            h = jax.nn.gelu(h.astype(dtype))
            h = tp_row_dense_local(
                h, lp["mlp"]["fc2"]["kernel"].astype(dtype),
                lp["mlp"]["fc2"]["bias"].astype(dtype),
                quant=quant)[0]                  # (S/n, E) home chunk
            return y + h.astype(dtype), pool_l

        x, pool_out = lax.scan(body, x, (stacked_layers(p), pool_l))
        hidden = layer_norm(x, p["final_ln"]).astype(dtype)
        nxt = tp_sample_tokens_local(
            hidden, wte, jnp.zeros((vs,), jnp.float32), policy=policy,
            block=block, vocab=vocab, quant=quant)
        # tokens leave REPLICATED (S ints — one tiny all-gather): the
        # spec draft chains each step's output into the next step's
        # input, and a sharded output would hash as a new jit signature
        # against the host-built first step (breaking the one-program-
        # per-role pin)
        return lax.all_gather(nxt, MODEL_AXIS, tiled=True), pool_out

    p_specs = jax.tree_util.tree_map_with_path(
        lambda path, _: serving_param_spec(path, tp_head=True), params)
    pool_spec = {k: P(None, None, None, MODEL_AXIS, None) for k in pool}
    return shard_map(
        local, mesh=mesh,
        in_specs=(p_specs, pool_spec, P(), P(MODEL_AXIS),
                  P(), P(), P(), P()),
        out_specs=(P(), pool_spec), check_vma=False,
    )(params, pool, token_ids, positions, tables, context_lens,
      write_blocks, write_offsets)


def tp_verify_forward(params: dict, pool: dict, token_ids: jax.Array,
                      positions: jax.Array, tables: jax.Array,
                      context_lens: jax.Array, write_blocks: jax.Array,
                      write_offsets: jax.Array, *, mesh, dtype, vocab: int,
                      kv_quant: str = "off", quant: str = "off",
                      policy: str = "greedy", vocab_block: int = 8192):
    """:func:`verify_forward` on the TP ring path: the ``(S, K)``
    draft windows flatten into ``S*K`` staggered lanes exactly as the
    single-replica path does (``S % n == 0`` keeps the lane count ring-
    divisible), ride :func:`tp_decode_forward`, and the per-lane argmax
    comes back ``(S, K)`` — the spec verify dispatch IS the sharded
    decode program, so spec × tp parity holds by construction.

    Returns ``(next_tokens (S, K), pool)``."""
    s, k = token_ids.shape

    def flat(a):
        return a.reshape((s * k,) + a.shape[2:])

    nxt, pool = tp_decode_forward(
        params, pool, flat(token_ids), flat(positions), flat(tables),
        flat(context_lens), flat(write_blocks), flat(write_offsets),
        mesh=mesh, dtype=dtype, vocab=vocab, kv_quant=kv_quant,
        quant=quant, policy=policy, vocab_block=vocab_block)
    return nxt.reshape(s, k), pool
