"""Serving forward passes: the GPT decoder as pure functions over the
scanned param tree.

Training applies the model through flax modules; serving wants two
*different* programs over the SAME parameters — a bucketed full-context
prefill and a one-token-per-sequence decode reading the paged KV cache
— and neither fits the module's ``__call__`` (which recomputes every
position's KV every token). This module re-expresses the
``models/gpt.GptDecoder`` math as pure functions over the scanned
``{"wte", "wpe", "decoder": {"layers": stacked}, "final_ln"}`` tree:

- the primitive sequence matches flax's exactly (``lax.dot_general``
  with DenseGeneral's dimension numbers, the fast-variance LayerNorm,
  ``jax.nn.gelu``), so :func:`prefill_forward` is **bit-identical** to
  ``GptDecoder(fused_head=True).apply`` on the prompt — the
  checkpoint→serving seam is testable as equality, not tolerance;
- both passes drive ONE ``lax.scan`` over the stacked layer weights
  (the r7 compile-time contract), and the decode scan threads the KV
  pool's layer axis as scan xs/ys — layer ``l``'s blocks are read and
  written inside iteration ``l``, never gathered whole.

Supported template: the plain GSPMD path (no tp_overlap/MoE/pipe —
the engine refuses those with intent; model sharding comes from the
params'/pool's NamedShardings, GSPMD partitions these functions like
any other jitted program).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from .decode_ops import paged_attention
from .kv_cache import quantize_kv


def layer_norm(x: jax.Array, p: dict) -> jax.Array:
    """flax ``nn.LayerNorm(dtype=f32)`` exactly: fast-variance stats
    (``E[x^2] - E[x]^2`` clipped at 0), ``rsqrt``, scale-into-mul —
    the same primitive sequence, for bitwise parity."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    mean2 = jnp.mean(lax.square(xf), axis=-1, keepdims=True)
    var = jnp.maximum(0.0, mean2 - lax.square(mean))
    y = xf - mean
    mul = lax.rsqrt(var + 1e-6) * p["scale"].astype(jnp.float32)
    return y * mul + p["bias"].astype(jnp.float32)


def dense(x: jax.Array, p: dict, n_axes: int, dtype) -> jax.Array:
    """``nn.DenseGeneral`` contraction over the trailing ``n_axes``
    dims of ``x`` (kernel's leading dims), bias broadcast-added."""
    x = x.astype(dtype)
    kernel = p["kernel"].astype(dtype)
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    kaxes = tuple(range(n_axes))
    y = lax.dot_general(x, kernel, ((axes, kaxes), ((), ())))
    return y + p["bias"].astype(dtype)


def embed_tokens(params: dict, input_ids: jax.Array, positions: jax.Array,
                 dtype) -> jax.Array:
    """``wte[ids] + wpe[pos]`` — the flax ``nn.Embed`` lookups."""
    wte = params["wte"]["embedding"].astype(dtype)
    wpe = params["wpe"]["embedding"].astype(dtype)
    return jnp.take(wte, input_ids, axis=0) + jnp.take(wpe, positions, axis=0)


def stacked_layers(params: dict) -> dict:
    """The scanned ``(L, ...)`` block-param stack of the decoder."""
    layers = params["decoder"].get("layers")
    if layers is None:
        raise ValueError(
            "serving template needs the scanned layer layout "
            "(decoder/layers stacked params); run the checkpoint through "
            "parallel.stacking.convert_tree_layout(..., 'scanned') — "
            "ServeEngine.from_checkpoint does this automatically")
    return layers


def _attn_qkv(p: dict, x: jax.Array, dtype):
    q = dense(x, p["attention"]["query"], 1, dtype)
    k = dense(x, p["attention"]["key"], 1, dtype)
    v = dense(x, p["attention"]["value"], 1, dtype)
    return q, k, v


def _block_prefill(p: dict, x: jax.Array, dtype, attn_impl: str):
    """One pre-LN decoder block over the full prompt ``x (B, T, E)``;
    returns ``(x, (k, v))`` with the block's KV for cache insertion."""
    h = layer_norm(x, p["ln_attn"]).astype(dtype)
    q, k, v = _attn_qkv(p, h, dtype)
    a = attention(q, k, v, causal=True, impl=attn_impl)
    a = dense(a, p["attention"]["out"], 2, dtype)
    x = x + a
    h = layer_norm(x, p["ln_mlp"]).astype(dtype)
    h = dense(h, p["mlp"]["fc1"], 1, dtype)
    h = jax.nn.gelu(h)
    h = dense(h, p["mlp"]["fc2"], 1, dtype)
    return x + h, (k, v)


def prefill_forward(params: dict, input_ids: jax.Array, *, dtype,
                    attn_impl: str = "auto"):
    """Full-context forward of the prompt batch ``(B, T)``.

    Returns ``(hidden, k, v)``: ``hidden (B, T, E)`` after the final
    LayerNorm (exactly ``GptDecoder(fused_head=True).apply``), and the
    per-layer KV ``(L, B, T, H, D)`` for paged-cache insertion.
    """
    t = input_ids.shape[1]
    x = embed_tokens(params, input_ids, jnp.arange(t), dtype)

    def body(carry, p):
        y, kv = _block_prefill(p, carry, dtype, attn_impl)
        return y, kv

    x, (k, v) = lax.scan(body, x, stacked_layers(params))
    hidden = layer_norm(x, params["final_ln"]).astype(dtype)
    return hidden, k, v


def _write_pool(pool_l: dict, key: str, val: jax.Array,
                write_blocks: jax.Array, write_offsets: jax.Array,
                kv_quant: str) -> dict:
    """Scatter one decode step's ``val (S, H, D)`` into the layer's
    physical blocks at ``(write_blocks, write_offsets)`` per slot.
    Inactive slots target the null block (the engine points them
    there) — a harmless dump the mask never reads."""
    out = dict(pool_l)
    if kv_quant == "int8":
        q, s = quantize_kv(val)
        out[key] = pool_l[key].at[write_blocks, write_offsets].set(q)
        out[key + "_scale"] = pool_l[key + "_scale"].at[
            write_blocks, write_offsets].set(s)
    else:
        out[key] = pool_l[key].at[write_blocks, write_offsets].set(
            val.astype(pool_l[key].dtype))
    return out


def decode_forward(params: dict, pool: dict, token_ids: jax.Array,
                   positions: jax.Array, tables: jax.Array,
                   context_lens: jax.Array, write_blocks: jax.Array,
                   write_offsets: jax.Array, *, dtype,
                   kv_quant: str = "off"):
    """One decode step for ``S`` slots: embed the last token, run the
    scanned stack with per-layer (write-KV → paged attention), final
    LayerNorm. Returns ``(hidden (S, E), pool)`` with the pool's layer
    axis updated in the same scan that consumed it.

    ``context_lens`` INCLUDE the token being decoded (its KV is written
    before the gather, so a token attends to itself — the causal
    diagonal); inactive slots carry ``context_len 0`` and a null-block
    write target, and their hidden rows are garbage the engine ignores.
    """
    x = embed_tokens(params, token_ids, positions, dtype)  # (S, E)

    def body(carry, layer):
        p, pool_l = layer
        h = layer_norm(carry, p["ln_attn"]).astype(dtype)
        q, k, v = _attn_qkv(p, h, dtype)                   # (S, H, D)
        pool_l = _write_pool(pool_l, "k", k, write_blocks, write_offsets,
                             kv_quant)
        pool_l = _write_pool(pool_l, "v", v, write_blocks, write_offsets,
                             kv_quant)
        a = paged_attention(
            q, pool_l["k"], pool_l["v"], tables, context_lens,
            k_scale=pool_l.get("k_scale"), v_scale=pool_l.get("v_scale"))
        a = dense(a, p["attention"]["out"], 2, dtype)
        y = carry + a
        h = layer_norm(y, p["ln_mlp"]).astype(dtype)
        h = dense(h, p["mlp"]["fc1"], 1, dtype)
        h = jax.nn.gelu(h)
        h = dense(h, p["mlp"]["fc2"], 1, dtype)
        return y + h, pool_l

    x, pool = lax.scan(body, x, (stacked_layers(params), pool))
    hidden = layer_norm(x, params["final_ln"]).astype(dtype)
    return hidden, pool


def verify_forward(params: dict, pool: dict, token_ids: jax.Array,
                   positions: jax.Array, tables: jax.Array,
                   context_lens: jax.Array, write_blocks: jax.Array,
                   write_offsets: jax.Array, *, dtype,
                   kv_quant: str = "off"):
    """Score a k-token draft window for every slot in ONE step — the
    speculative-decode batch-verify path.

    Each slot's window of ``k`` consecutive draft positions flattens
    into ``k`` independent decode lanes sharing that slot's block
    table, with STAGGERED context lengths (lane ``j`` sees positions
    ``< positions[s, j] + 1``): inside :func:`decode_forward`'s scan
    every layer writes the whole window's KV before its paged-attention
    gather, so lane ``j`` attends to lanes ``< j`` of the same window —
    intra-window causality without a new kernel, and the target scores
    all ``k`` draft positions in one compiled program.

    Window tails past a slot's live draft length (``k`` rarely fills
    the fixed verify bucket) follow the bucketed-prefill scrap
    convention: ``context_len 0``, null-block write target — the lane
    computes garbage the mask never reads and the scatter dumps into
    block 0's scrap space (unit-pinned).

    Args:
      token_ids, positions, context_lens, write_blocks, write_offsets:
        ``(S, K)`` per-slot windows.
      tables: ``(S, K, max_blocks)`` — the slot's table replicated per
        lane (extra trailing blocks are masked by the lane's context).

    Returns ``(hidden (S, K, E), pool)``.
    """
    s, k = token_ids.shape

    def flat(a):
        return a.reshape((s * k,) + a.shape[2:])

    hidden, pool = decode_forward(
        params, pool, flat(token_ids), flat(positions), flat(tables),
        flat(context_lens), flat(write_blocks), flat(write_offsets),
        dtype=dtype, kv_quant=kv_quant)
    return hidden.reshape(s, k, -1), pool
