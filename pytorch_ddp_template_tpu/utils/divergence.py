"""Cross-host divergence detection: the SPMD race/desync detector.

The reference has no sanitizers (SURVEY.md §5.2); its correctness rests on
DDP's synchronous semantics. The SPMD equivalent failure mode is *replica
divergence* — hosts computing on drifted parameters after a silent data
hazard, a non-deterministic op, or hardware corruption. The cheap
invariant check: every process fingerprints its (supposedly replicated)
state and all fingerprints must be bit-identical.

``fingerprint`` is a jitted reduction (one scalar pair per leaf — sum and
L2 — folded into a single f64 vector); ``check`` gathers fingerprints from
every process (``process_allgather`` — a DCN collective, so it is itself a
liveness probe of the cluster) and raises/logs on mismatch. Single-process
meshes short-circuit to trivially-true, so the call is safe (and nearly
free) to leave on at a low cadence in production.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger

log = get_logger(__name__)


@jax.jit
def fingerprint(tree: Any) -> jax.Array:
    """Order-stable f32 digest of a pytree: per-leaf (sum, l2) pairs."""
    leaves = [x for x in jax.tree.leaves(tree)
              if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.number)]
    if not leaves:
        return jnp.zeros((2,), jnp.float32)
    sums = jnp.stack([jnp.sum(x, dtype=jnp.float32) for x in leaves])
    norms = jnp.stack([jnp.sum(jnp.square(x), dtype=jnp.float32) for x in leaves])
    return jnp.concatenate([sums, norms])


def check(tree: Any, *, step: int | None = None, raise_on_divergence: bool = False) -> bool:
    """True iff every process holds a bit-identical fingerprint of ``tree``."""
    if jax.process_count() == 1:
        return True  # before fingerprinting: don't stall async dispatch
    fp = np.asarray(fingerprint(tree))
    from jax.experimental import multihost_utils

    all_fps = np.asarray(multihost_utils.process_allgather(fp))
    # bit-pattern comparison: NaN != NaN would misreport ordinary numeric
    # blowup (same NaNs everywhere) as cross-host divergence
    bits = all_fps.view(np.uint32)
    ok = bool((bits == bits[0]).all())
    if not ok:
        detail = {
            "step": step,
            "process": jax.process_index(),
            "local_fp_head": fp[:4].tolist(),
            "divergent_processes": [
                int(i) for i in range(len(bits))
                if not (bits[i] == bits[0]).all()
            ],
        }
        if raise_on_divergence:
            raise RuntimeError(f"cross-host parameter divergence: {detail}")
        log.error("cross-host parameter divergence detected", detail)
    return ok
