"""Cross-host divergence detection: the SPMD race/desync detector.

The reference has no sanitizers (SURVEY.md §5.2); its correctness rests on
DDP's synchronous semantics. The SPMD equivalent failure mode is *replica
divergence* — hosts computing on drifted parameters after a silent data
hazard, a non-deterministic op, or hardware corruption. The cheap
invariant check: every process fingerprints its (supposedly replicated)
state and all fingerprints must be bit-identical.

``fingerprint`` is a jitted reduction (one scalar pair per leaf — sum and
L2 — folded into a single f64 vector); ``check`` gathers fingerprints from
every process (``process_allgather`` — a DCN collective, so it is itself a
liveness probe of the cluster) and raises/logs on mismatch. Single-process
meshes short-circuit to trivially-true, so the call is safe (and nearly
free) to leave on at a low cadence in production.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger

log = get_logger(__name__)


@jax.jit
def fingerprint(tree: Any) -> jax.Array:
    """Order-stable f32 digest of a pytree: per-leaf (sum, l2) pairs."""
    leaves = [x for x in jax.tree.leaves(tree)
              if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.number)]
    if not leaves:
        return jnp.zeros((2,), jnp.float32)
    sums = jnp.stack([jnp.sum(x, dtype=jnp.float32) for x in leaves])
    norms = jnp.stack([jnp.sum(jnp.square(x), dtype=jnp.float32) for x in leaves])
    return jnp.concatenate([sums, norms])


def _compare(fp: np.ndarray, *, step: int | None,
             raise_on_divergence: bool) -> bool:
    """Allgather ``fp`` across processes and compare bit patterns."""
    from jax.experimental import multihost_utils

    all_fps = np.asarray(multihost_utils.process_allgather(fp))
    # bit-pattern comparison: NaN != NaN would misreport ordinary numeric
    # blowup (same NaNs everywhere) as cross-host divergence
    bits = all_fps.view(np.uint32)
    ok = bool((bits == bits[0]).all())
    if not ok:
        detail = {
            "step": step,
            "process": jax.process_index(),
            "local_fp_head": fp[:4].tolist(),
            "divergent_processes": [
                int(i) for i in range(len(bits))
                if not (bits[i] == bits[0]).all()
            ],
        }
        if raise_on_divergence:
            raise RuntimeError(f"cross-host parameter divergence: {detail}")
        log.error("cross-host parameter divergence detected", detail)
    return ok


def check(tree: Any, *, step: int | None = None, raise_on_divergence: bool = False) -> bool:
    """True iff every process holds a bit-identical fingerprint of ``tree``."""
    if jax.process_count() == 1:
        return True  # before fingerprinting: don't stall async dispatch
    return _compare(np.asarray(fingerprint(tree)), step=step,
                    raise_on_divergence=raise_on_divergence)


class DivergenceMonitor:
    """:func:`check` with the device fetch taken off the critical path.

    ``submit`` only *dispatches* the jitted fingerprint reduction (async,
    returns immediately); ``poll`` completes a pending check once its
    fingerprint is at least ``lag`` steps old — by which point the
    reduction has retired behind later train steps, so the host fetch
    costs ~nothing. Only the DCN allgather remains on the main thread
    (collectives must issue in identical order on every process, so it
    cannot move to a background thread), and every process polls the same
    deterministic schedule, keeping the allgathers matched.

    Single-process meshes are a no-op end to end, like :func:`check`.
    """

    def __init__(self, *, lag: int = 2, raise_on_divergence: bool = False):
        self.lag = max(int(lag), 1)
        self.raise_on_divergence = raise_on_divergence
        self.ok = True
        self._pending: list[tuple[int, jax.Array]] = []

    def submit(self, tree: Any, step: int) -> None:
        if jax.process_count() == 1:
            return
        self._pending.append((step, fingerprint(tree)))

    def _complete_first(self) -> bool:
        step, fp = self._pending.pop(0)
        ok = _compare(np.asarray(fp), step=step,
                      raise_on_divergence=self.raise_on_divergence)
        self.ok = self.ok and ok
        return ok

    def poll(self, current_step: int) -> bool | None:
        """Complete the oldest pending check if it is ripe; None if no
        check ran this call (nothing pending, or still within ``lag``)."""
        if not self._pending or current_step - self._pending[0][0] < self.lag:
            return None
        return self._complete_first()

    def drain(self) -> bool:
        """Complete every pending check (call before leaving the loop)."""
        while self._pending:
            self._complete_first()
        return self.ok
