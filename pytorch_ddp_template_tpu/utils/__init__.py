"""Observability and process helpers (reference: ``utils.py``)."""

from .dist import (
    global_device_count,
    is_main_process,
    local_device_count,
    process_count,
    process_index,
)
from .logging import get_logger, redirect_warnings_to_logger

__all__ = [
    "get_logger",
    "redirect_warnings_to_logger",
    "process_index",
    "process_count",
    "is_main_process",
    "local_device_count",
    "global_device_count",
]
