"""JSON-safety for telemetry records that may carry non-finite floats.

``json.dumps`` serialises NaN/Infinity as the bare tokens ``NaN`` /
``Infinity`` — legal nowhere in the JSON spec, so any compliant consumer
(``jq``, pandas ``read_json``, a Go/JS dashboard) chokes on the one record
that mattered most: the step where the loss went NaN. The anomaly sentry
*intentionally* surfaces non-finite scalars, so every sink that writes
them (``train/metrics.MetricsWriter``, ``obs/sentry.FlightRecorder``)
routes records through :func:`json_sanitize` first: the non-finite value
becomes ``null`` and the original spelling is preserved in a sibling
``"<key>_repr"`` string — machine-parseable AND lossless for the human
reading the triage bundle.
"""

from __future__ import annotations

import math
from typing import Any


def _finite(v: float) -> bool:
    return math.isfinite(v)


def json_sanitize(record: dict[str, Any]) -> dict[str, Any]:
    """Return a copy of ``record`` that ``json.dumps(..., allow_nan=False)``
    accepts: non-finite floats become ``None`` plus a ``"<key>_repr"``
    sibling holding the original spelling (``"nan"``, ``"inf"``, ``"-inf"``).
    Lists are sanitised element-wise (one ``_repr`` for the whole list).
    Nested dicts recurse. Non-numeric values pass through untouched.
    """
    out: dict[str, Any] = {}
    for k, v in record.items():
        if isinstance(v, bool) or v is None:
            out[k] = v
        elif isinstance(v, dict):
            out[k] = json_sanitize(v)
        elif isinstance(v, (list, tuple)):
            vals = list(v)
            bad = [x for x in vals
                   if isinstance(x, float) and not _finite(x)]
            if bad:
                out[k] = [None if isinstance(x, float) and not _finite(x)
                          else x for x in vals]
                out[f"{k}_repr"] = ("["
                                    + ", ".join(repr(x) for x in vals)
                                    + "]")
            else:
                out[k] = vals
        elif isinstance(v, float) and not _finite(v):
            out[k] = None
            out[f"{k}_repr"] = repr(v)  # 'nan' | 'inf' | '-inf'
        else:
            out[k] = v
    return out
