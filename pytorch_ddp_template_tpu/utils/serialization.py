"""JSON-safety for telemetry records that may carry non-finite floats.

``json.dumps`` serialises NaN/Infinity as the bare tokens ``NaN`` /
``Infinity`` — legal nowhere in the JSON spec, so any compliant consumer
(``jq``, pandas ``read_json``, a Go/JS dashboard) chokes on the one record
that mattered most: the step where the loss went NaN. The anomaly sentry
*intentionally* surfaces non-finite scalars, so every sink that writes
them (``train/metrics.MetricsWriter``, ``obs/sentry.FlightRecorder``,
``obs/goodput.GoodputLedger``) routes records through
:func:`json_sanitize` first: the non-finite value becomes ``null`` and
the original spelling is preserved in a sibling ``"<key>_repr"`` string —
machine-parseable AND lossless for the human reading the triage bundle.

r13 hardening (first direct unit tests forced the contract to be
written down): values may also be numpy/jax **device arrays** (0-d
scalars become numbers, n-d arrays become nested lists — fetching a jax
array blocks, which is fine for the triage/ledger paths this serves),
containers **nest** (dicts and lists sanitise recursively), and any
other object that JSON cannot represent falls back to its ``repr``
string instead of blowing up the dump — a partially-readable bundle
beats an exception in the failure path.
"""

from __future__ import annotations

import math
from typing import Any


def _finite(v: float) -> bool:
    return math.isfinite(v)


def _coerce(v: Any) -> Any:
    """Array-likes (numpy scalars/arrays, jax device arrays) to plain
    Python via ``tolist`` — duck-typed so this module stays importable
    without numpy or jax."""
    if isinstance(v, float):
        # normalise float subclasses (np.float64): repr(np.float64(nan))
        # spells "np.float64(nan)", and the _repr contract is "nan"
        return float(v)
    if v is None or isinstance(v, (bool, int, str, dict, list, tuple)):
        return v
    if hasattr(v, "dtype") and hasattr(v, "tolist"):
        try:
            return v.tolist()  # 0-d -> number, n-d -> nested lists
        except Exception:  # noqa: BLE001 - fall through to the repr path
            pass
    return v


def _element(x: Any) -> Any:
    """Sanitise one container element: nested dicts/lists recurse,
    non-finite floats become None (the enclosing list's ``_repr``
    sibling keeps flat spellings; deeper nesting trades the repr for
    staying parseable), anything unserialisable becomes its repr."""
    x = _coerce(x)
    if x is None or isinstance(x, (bool, str, int)):
        return x
    if isinstance(x, float):
        return x if _finite(x) else None
    if isinstance(x, dict):
        return json_sanitize(x)
    if isinstance(x, (list, tuple)):
        return [_element(e) for e in x]
    return repr(x)


def json_sanitize(record: dict[str, Any]) -> dict[str, Any]:
    """Return a copy of ``record`` that ``json.dumps(..., allow_nan=False)``
    accepts: non-finite floats become ``None`` plus a ``"<key>_repr"``
    sibling holding the original spelling (``"nan"``, ``"inf"``, ``"-inf"``).
    Lists are sanitised element-wise (one ``_repr`` for the whole list).
    Nested dicts recurse. Device/numpy arrays convert via ``tolist``
    first; objects JSON cannot represent serialise as their ``repr``.
    """
    out: dict[str, Any] = {}
    for k, v in record.items():
        v = _coerce(v)
        if isinstance(v, bool) or v is None:
            out[k] = v
        elif isinstance(v, dict):
            out[k] = json_sanitize(v)
        elif isinstance(v, (list, tuple)):
            vals = [_coerce(x) for x in v]
            bad = [x for x in vals
                   if isinstance(x, float) and not _finite(x)]
            if bad:
                out[k] = [_element(x) for x in vals]
                out[f"{k}_repr"] = ("["
                                    + ", ".join(repr(x) for x in vals)
                                    + "]")
            else:
                out[k] = [_element(x) for x in vals]
        elif isinstance(v, float) and not _finite(v):
            out[k] = None
            out[f"{k}_repr"] = repr(v)  # 'nan' | 'inf' | '-inf'
        elif isinstance(v, (str, int, float)):
            out[k] = v
        else:
            out[k] = repr(v)  # unserialisable object: lossless-ish fallback
    return out
