"""Profiling: trace capture + step timing statistics.

The reference has no tracing/profiling at all (SURVEY.md §5.1: only tqdm
rates and TB scalars); for a TPU framework the profiler is table stakes —
the ≥90% scaling target (BASELINE.md) is won by reading overlap out of
traces, not by guessing.

Three tools:

- :class:`TraceWindow` — captures a ``jax.profiler`` trace for steps
  ``[start, start+steps)`` into ``<output_dir>/profile``; view with
  TensorBoard's profile plugin or Perfetto. Wired to ``--profile_steps``.
- :class:`StepTimer` — cheap wall-clock accounting of every step with
  p50/p90/p99 summaries; catches input-bound stalls (step time >> device
  time) without a trace.
- :func:`annotate` — named host-side phase annotations
  (``jax.profiler.TraceAnnotation``) around the loop phases (input
  wait, dispatch, device wait, checkpoint, eval), so every captured
  trace — ``--profile_steps`` windows AND the flight recorder's
  post-trigger captures — reads in loop phases instead of raw op soup.
  A TraceAnnotation outside an active capture is a near-free TraceMe
  check; :func:`set_phase_annotations` exists so the bench neutrality
  leg can measure an honest annotations-off baseline, not because the
  annotations need turning off.
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from pathlib import Path

import jax
import numpy as np

from .logging import get_logger

log = get_logger(__name__)

_annotations_enabled = True

_NULL = contextlib.nullcontext()

#: the loop thread's live phase-name stack (r15): :func:`annotate` spans
#: push/pop their name so the memory watermark poller (telemetry drain
#: thread) can attribute a sample to the phase active when it fired.
#: Written by the loop thread only; the cross-thread read is a racy
#: last-element peek by design — a one-sample-stale phase label is
#: honest enough for peak attribution, and a lock here would tax every
#: loop phase to serve a per-cadence poll.
_phase_stack: list[str] = []


def current_phase() -> str:
    """The innermost active :func:`annotate` phase name on the loop
    thread (``"between_steps"`` outside any span or with annotations
    disabled) — the r13 named phases, readable without a trace."""
    try:
        return _phase_stack[-1]
    except IndexError:
        return "between_steps"


def set_phase_annotations(enabled: bool) -> None:
    """Globally enable/disable :func:`annotate` (process-wide). Default
    on; the BENCH_MODE=perf off-leg and tests flip it."""
    global _annotations_enabled
    _annotations_enabled = bool(enabled)


def phase_annotations_enabled() -> bool:
    return _annotations_enabled


class _PhaseAnnotation(jax.profiler.TraceAnnotation):
    """A TraceAnnotation that also tracks the phase name for
    :func:`current_phase` (subclass so callers pinning the
    TraceAnnotation contract keep holding one)."""

    def __init__(self, name: str):
        super().__init__(name)
        self._phase_name = name

    def __enter__(self):
        _phase_stack.append(self._phase_name)
        return super().__enter__()

    def __exit__(self, *exc):
        try:
            return super().__exit__(*exc)
        finally:
            if _phase_stack and _phase_stack[-1] == self._phase_name:
                _phase_stack.pop()


def annotate(name: str):
    """Context manager naming the enclosed host span ``name`` in any
    active profiler trace (no-op context when disabled) and exposing it
    via :func:`current_phase` while active."""
    if not _annotations_enabled:
        return _NULL
    return _PhaseAnnotation(name)


class TraceWindow:
    """Capture a profiler trace over a step window.

    Host 0 only by default (the ``--profile_steps`` convention: one
    trace per run, written where the operator looks). ``all_hosts=True``
    lifts the pin — the r12 flight recorder's post-trigger capture uses
    it, because the host whose sentry fired is the host whose trace
    matters, and before r14 a trigger on a non-zero host silently
    produced no trace at all.

    Usage: call :meth:`step` once per training step; the window
    [start_step, start_step + num_steps) is traced.
    """

    def __init__(self, output_dir: str | Path, start_step: int = 10,
                 num_steps: int = 0, enabled: bool = True,
                 all_hosts: bool = False):
        self.dir = str(Path(output_dir) / "profile")
        self.start = start_step
        self.stop_at = start_step + num_steps
        host_ok = all_hosts or jax.process_index() == 0
        self.enabled = enabled and num_steps > 0 and host_ok
        self._active = False

    def step(self, step: int) -> None:
        if not self.enabled:
            return
        if not self._active and step >= self.start and step < self.stop_at:
            jax.profiler.start_trace(self.dir)
            self._active = True
            log.info("profiler trace started", {"step": step, "dir": self.dir})
        elif self._active and step >= self.stop_at:
            jax.profiler.stop_trace()
            self._active = False
            log.info("profiler trace written", {"step": step, "dir": self.dir})

    @property
    def active(self) -> bool:
        """True while a trace capture is running — the jax profiler
        supports ONE live trace per process, so anything arming a second
        window (the flight recorder's post-trigger capture) must check
        here first."""
        return self._active

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False


class StepTimer:
    """Rolling wall-clock step timer with percentile summaries.

    The sample store is a bounded ``deque``: append past capacity evicts
    the oldest sample in O(1) (a list's ``pop(0)`` is O(capacity) — paid
    every step of a long run once the buffer fills), and the summaries
    always describe the newest ``capacity`` recorded intervals."""

    def __init__(self, capacity: int = 2048):
        self._times: deque[float] = deque(maxlen=capacity)
        self._last: float | None = None

    def tick(self, *, discard: bool = False) -> float | None:
        """Mark a step boundary; returns the last step's duration.

        ``discard=True`` still advances the boundary but drops the interval
        from the statistics — callers pass it when the interval included
        non-step work (eval, checkpoint save, divergence allgather), which
        would otherwise corrupt the p90/p99 step-time percentiles."""
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            dt = now - self._last
            if not discard:
                self._times.append(dt)  # maxlen evicts the oldest
        self._last = now
        return dt

    @property
    def sample_count(self) -> int:
        """Recorded (non-discarded) intervals currently held — the
        steady-state-readiness gate for the r14 baseline comparison."""
        return len(self._times)

    def p50_ms(self) -> float | None:
        """Median recorded step time in ms (None before any sample) —
        cheap yardstick for "did this side-work call actually stall?"."""
        if not self._times:
            return None
        return float(np.percentile(np.asarray(self._times), 50) * 1e3)

    @staticmethod
    def _summarize(times) -> dict[str, float]:
        if not times:
            return {}
        arr = np.asarray(times)
        return {
            "step_time_p50_ms": float(np.percentile(arr, 50) * 1e3),
            "step_time_p90_ms": float(np.percentile(arr, 90) * 1e3),
            "step_time_p99_ms": float(np.percentile(arr, 99) * 1e3),
            "step_time_mean_ms": float(arr.mean() * 1e3),
        }

    def summary(self) -> dict[str, float]:
        return self._summarize(self._times)

    def deferred_summary(self):
        """Zero-arg callable computing :meth:`summary` over a snapshot of
        the samples *as of now*. The copy is a cheap C-level list copy (no
        numpy on the caller); the percentile math runs wherever the
        callable is invoked (the telemetry drain thread) — and reports the
        state at snapshot time, not whatever the timer holds when a lagging
        drain finally gets to the record."""
        times = tuple(self._times)
        return lambda: self._summarize(times)
