"""Process/rank helpers with uninitialised-safe fallbacks.

Capability parity with the reference's ``get_rank``/``get_world_size``/
``is_main_process`` (``/root/reference/utils.py:84-101``), which fall back to
rank 0 / world size 1 when ``torch.distributed`` is unavailable or
uninitialised. Here the runtime is JAX: a single process drives all local
chips, so "rank" means the JAX *process* (host), not a device.

These helpers never import-fail and never raise when JAX's distributed
runtime is not initialised — single-process development and unit tests use
the same code path as a multi-host pod (SURVEY.md §4).
"""

from __future__ import annotations


def process_index() -> int:
    """Global index of this host process (0 when not distributed)."""
    try:
        import jax

        return jax.process_index()
    except Exception:  # noqa: BLE001 - pre-init / no-backend fallback
        return 0


def process_count() -> int:
    """Number of host processes participating (1 when not distributed)."""
    try:
        import jax

        return jax.process_count()
    except Exception:  # noqa: BLE001
        return 1


def is_main_process() -> bool:
    """True on the coordinating host — the checkpoint/metrics writer.

    Mirrors ``is_main_process()`` (``utils.py:99-101``): rank 0, with a safe
    ``True`` when running undistributed.
    """
    return process_index() == 0


def local_device_count() -> int:
    """Number of accelerator devices attached to this host (1 fallback)."""
    try:
        import jax

        return jax.local_device_count()
    except Exception:  # noqa: BLE001
        return 1


def global_device_count() -> int:
    """Total devices across all hosts (1 fallback)."""
    try:
        import jax

        return jax.device_count()
    except Exception:  # noqa: BLE001
        return 1
