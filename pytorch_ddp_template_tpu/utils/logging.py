"""Structured, process-aware logging for multi-host TPU training.

Capability parity with the reference's ``utils.py`` observability stack
(``/root/reference/utils.py:9-101``): millisecond timestamps, ``[k=v]``
structured pairs, progress-bar-safe emission, per-process rank tagging,
INFO on the main process / WARNING elsewhere, and capture of Python
warnings into the log stream.

TPU-first design notes (not a translation):

- The reference injects ``node_rank``/``local_rank`` captured at logger
  construction (``utils.py:49-58``). Under JAX the distributed runtime may
  be initialised *after* module import, so ranks are resolved lazily at
  emit time from :mod:`..utils.dist` (uninitialised-safe: 0/1 fallbacks).
- The reference gates verbosity by setting the logger level once at
  construction (``utils.py:67-68``). We gate per-record with a filter so a
  logger created before ``jax.distributed.initialize`` still quiets itself
  on non-main hosts afterwards.
"""

from __future__ import annotations

import datetime
import logging
import sys
import threading
import warnings
from collections.abc import Mapping
from typing import Any

from . import dist

#: Base format. Mirrors the reference's field set (``utils.py:9``) with the
#: rank misnomer fixed: the reference prints the *global* rank under the name
#: ``node_rank`` (``ddp.py:104``); we label fields honestly.
LOG_FORMAT = (
    "%(asctime)s - %(levelname)s - %(name)s - "
    "[host=%(process_index)s/%(process_count)s] - %(message)s"
)


class StructuredFormatter(logging.Formatter):
    """Append ``[k=v]`` pairs when a log call passes a single mapping arg.

    ``log.info("msg", {"lr": 1e-3})`` renders ``msg [lr=0.001]``.
    Reference behaviour: ``utils.py:16-21``; local-timezone millisecond
    timestamps: ``utils.py:23-31``.
    """

    default_msec_format = "%s.%03d"

    def format(self, record: logging.LogRecord) -> str:
        kv: Mapping[str, Any] | None = None
        if isinstance(record.args, Mapping):
            kv = record.args
            record.args = None  # prevent %-interpolation against the mapping
        base = super().format(record)
        if kv:
            pairs = " ".join(f"[{k}={v!r}]" for k, v in kv.items())
            base = f"{base} {pairs}"
        return base

    def formatTime(self, record: logging.LogRecord, datefmt: str | None = None) -> str:
        dt = datetime.datetime.fromtimestamp(record.created).astimezone()
        if datefmt:
            return dt.strftime(datefmt)
        return dt.strftime("%Y-%m-%d %H:%M:%S.%f")[:-3]


class ProcessInfoFilter(logging.Filter):
    """Inject ``process_index``/``process_count`` into every record, lazily.

    Counterpart of the reference's ``RankFilter`` (``utils.py:49-58``), but
    resolved at emit time so initialisation order does not matter.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        record.process_index = dist.process_index()
        record.process_count = dist.process_count()
        return True


class MainProcessLevelFilter(logging.Filter):
    """Drop sub-WARNING records on non-main processes.

    Capability of the reference's level rule (``utils.py:67-68``): INFO on
    ranks {-1, 0}, WARNING otherwise — evaluated per-record here.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno >= logging.WARNING:
            return True
        return dist.is_main_process()


class ProgressSafeHandler(logging.StreamHandler):
    """Route records through ``tqdm.write`` when tqdm is active.

    Keeps progress bars intact like the reference's ``TqdmLoggingHandler``
    (``utils.py:34-46``), but degrades to a plain stream handler when tqdm
    is unavailable (e.g. headless pod workers).
    """

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = self.format(record)
            try:
                from tqdm import tqdm

                tqdm.write(msg, file=sys.stdout)
            except ImportError:
                self.stream.write(msg + self.terminator)
                self.flush()
        except Exception:  # noqa: BLE001 - never let logging kill training
            self.handleError(record)


_configured_loggers: set[str] = set()
_lock = threading.Lock()


def get_logger(name: str) -> logging.Logger:
    """Return a structured process-aware logger.

    Equivalent capability to ``getLoggerWithRank`` (``utils.py:65-75``): the
    returned logger emits INFO+ on the main process and WARNING+ elsewhere,
    with structured ``[k=v]`` formatting.
    """
    log = logging.getLogger(name)
    with _lock:
        if name in _configured_loggers:
            return log
        handler = ProgressSafeHandler(stream=sys.stdout)
        handler.setFormatter(StructuredFormatter(LOG_FORMAT))
        handler.addFilter(ProcessInfoFilter())
        handler.addFilter(MainProcessLevelFilter())
        log.addHandler(handler)
        log.setLevel(logging.INFO)
        log.propagate = False
        _configured_loggers.add(name)
    return log


def redirect_warnings_to_logger(log: logging.Logger) -> None:
    """Route ``warnings.warn`` output through *log* (``utils.py:78-82``)."""

    def _showwarning(message, category, filename, lineno, file=None, line=None):  # noqa: ANN001
        log.warning(
            "%s", warnings.formatwarning(message, category, filename, lineno, line).strip()
        )

    warnings.showwarning = _showwarning
