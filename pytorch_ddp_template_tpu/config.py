"""Training configuration: the reference's 15-flag CLI surface, TPU-native.

Flag-for-flag coverage of the reference argparse block
(``/root/reference/ddp.py:291-314``), re-spelled for TPU semantics:

- ``--per_gpu_train_batch_size`` → ``--per_device_train_batch_size``
  (per TPU chip); the GPU spelling is kept as a hidden alias.
- ``--no_cuda`` → ``--cpu`` (force the CPU backend; alias kept).
- ``--fp16``/``--fp16_opt_level``/``--loss_scale`` → ``--bf16``. TPU MXUs
  compute natively in bfloat16 and need no loss scaling, so the three
  AMP knobs collapse into one; the fp16 spellings are accepted and mapped.
- ``--local_rank`` is accepted-and-ignored (JAX owns all local chips in a
  single process; there is no per-device process launcher).
- ``--global-step`` is parsed *and consumed*: the reference parses it but
  never reads it, so checkpoints can never be resumed (``ddp.py:293`` vs
  ``ddp.py:206``, SURVEY.md §2d) — here it selects the checkpoint to
  restore and training continues from that step.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from pathlib import Path
from typing import Any


@dataclasses.dataclass
class TrainingConfig:
    """Everything the trainer needs, serialisable for checkpointing.

    The reference pickles its whole args namespace into
    ``training_args.bin`` (``ddp.py:260-262``); we serialise to JSON so the
    artifact is portable and diffable.
    """

    # -- reference flag surface (ddp.py:292-309) --------------------------
    global_step: int = 0  # resume-from step; 0 = fresh (or auto-resume latest)
    cpu: bool = False  # reference: --no_cuda
    output_dir: str = "outputs"
    seed: int = 42
    gradient_accumulation_steps: int = 1
    per_device_train_batch_size: int = 128  # reference: --per_gpu_train_batch_size
    max_steps: int = -1
    logging_steps: int = 50
    save_steps: int = 50
    num_train_epochs: float = 3.0
    warmup_steps: int = 0
    max_grad_norm: float = 1000.0
    bf16: bool = False  # reference: --fp16 (+ loss_scale/fp16_opt_level, moot on TPU)

    # -- TPU-native additions ---------------------------------------------
    learning_rate: float = 1e-3  # reference hardcodes SGD(lr=1e-3) at ddp.py:183
    lr_schedule: str = "linear"  # linear (reference parity) | cosine | constant
    optimizer: str = "sgd"  # sgd | momentum | adam | adamw | lamb | lars;
    #                         the reference's
    #                         --fp16 FusedAdam path is a NameError (SURVEY.md
    #                         §2d) — here the adaptive family actually works
    momentum: float = 0.9  # for optimizer=momentum
    weight_decay: float = 0.0  # adamw decoupled weight decay
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    mesh: str = "data:-1"  # mesh spec, e.g. "data:-1" or "data:4,model:2"
    cp_impl: str = "ring"  # context-parallel engine: ring | ulysses
    pipe_microbatches: int = 4  # microbatch count for the pipelined
    #                             entries (models/gpt_pipe.py); clamped to
    #                             divide the per-replica batch (a clamp
    #                             to 1 is refused — the pipeline would
    #                             serialise)
    pipe_schedule: str = "1f1b"  # pipeline schedule for the pipelined
    #                              entries (parallel/pipeline.py):
    #                              gpipe (masked fill/drain, AD backward
    #                              — the r4 parity/bench baseline) |
    #                              1f1b (fused one-forward-one-backward
    #                              slot loop, O(P) activation residency)
    #                              | zb (zero-bubble: backward split
    #                              into the critical-path dx pass and
    #                              dw products deferred wholesale to a
    #                              batched post-loop wave — the drain
    #                              region doing the work the bubble
    #                              used to waste)
    zero1: bool = False  # shard optimizer state over the data axis (ZeRO-1)
    fsdp: bool = False  # shard params+grads+opt state over data (FSDP/ZeRO-3;
    #                     subsumes zero1)
    fsdp_overlap: bool = False  # decomposed-FSDP execution
    #                             (parallel/overlap.py): the scanned block
    #                             stack prefetches layer k+1's weight gather
    #                             under layer k's compute and drains layer
    #                             k's grad reduction under layer k-1's
    #                             backward. Implies --fsdp; needs
    #                             --scan_layers; data-only meshes. On the
    #                             pipelined entries: slot-boundary
    #                             gather/scatter waves instead (pipe×fsdp,
    #                             r22, parallel/pipeline.py)
    xla_overlap_flags: bool = False  # set the XLA latency-hiding-scheduler
    #                                  flag pack (async collectives overlap
    #                                  with compute) before backend init;
    #                                  runtime/context.py logs what was set
    ddp_overlap: bool = False  # per-layer overlapped grad reduce for pure
    #                            DDP (parallel/compress.py): the scanned
    #                            stack's backward issues each layer's
    #                            cross-replica grad reduce inside its own
    #                            reverse-scan iteration (the TPU-native
    #                            form of DDP bucketing). Needs
    #                            --scan_layers; replicated params on
    #                            data-only meshes; FSDP/MoE refused. On
    #                            the pipelined entries: per-slot masked
    #                            reduces at the slot boundary (pipe×ddp,
    #                            r22, parallel/pipeline.py)
    grad_comm: str = "fp32"  # wire precision of the per-layer grad reduce
    #                          under --ddp_overlap: fp32 | bf16 | int8
    #                          (chunked symmetric quantization with
    #                          stochastic rounding; halves/quarters grad
    #                          bytes on the wire)
    grad_error_feedback: bool = False  # carry a per-replica compression-
    #                                    error residual in TrainState and
    #                                    re-inject it next step (1-bit-SGD
    #                                    lineage): the quantization error
    #                                    telescopes instead of random-
    #                                    walking. Needs a lossy --grad_comm
    tp_overlap: bool = False  # decomposed tensor-parallel collective
    #                           matmuls (parallel/collective_matmul.py):
    #                           the scanned stack's Megatron matmuls run
    #                           as ring all-gather-matmul (fc1/fused-qkv)
    #                           and matmul-reduce-scatter (fc2/out)
    #                           shard_map regions over the `model` axis —
    #                           single-hop ppermutes hide under partial
    #                           dots instead of GSPMD's blocking psum/
    #                           all-gather walls; the model-sharded LM
    #                           head rides the same ring (ops/lm_head.py).
    #                           Needs --scan_layers and a `model` mesh
    #                           axis; composes with --fsdp_overlap /
    #                           --ddp_overlap (r11); MoE refused. On the
    #                           pipelined entries: psum-form Megatron TP
    #                           inside each stage, collectives hoisted to
    #                           the slot boundary (pipe×tp, r22)
    quant_compute: str = "off"  # low-precision compute path
    #                             (ops/quant.py): off | int8 | fp8. The
    #                             transformer block matmuls
    #                             (fc1/fc2/qkv/out) run as per-channel
    #                             scaled narrow dots re-derived from the
    #                             fp32 master weights every step (the
    #                             optimizer never sees a quantized
    #                             value); composed with --tp_overlap the
    #                             ring collective matmuls quantize each
    #                             chunk once and rotate the narrow
    #                             tensor + its scales — wire and FLOPs
    #                             shrink together. Transformer families
    #                             only; MoE/pipe refused with intent
    remat: bool = False  # rematerialise blocks (peak-memory for FLOPs trade;
    #                      long-context entries default it on regardless)
    scan_layers: bool = False  # drive the transformer block stack as ONE
    #                            nn.scan-compiled block over weights stacked
    #                            on a leading (num_layers, ...) dim: compile
    #                            time stops growing with depth; with --remat
    #                            the checkpoint sits inside the scan body
    #                            (remat-scan). Checkpoints restack via
    #                            tools/convert_checkpoint.py; pipe entries
    #                            excluded (own stage stacking)
    remat_policy: str = "block"  # block = save only block boundaries;
    #                              save-convs = ResNet selective remat (save
    #                              conv outputs, recompute only norm/ReLU)
    fused_head: bool = False  # blockwise LM head (ops/lm_head.py): no
    #                           (B,T,V) logits; long-context LMs default on
    num_layers: int = 0  # override the zoo entry's transformer depth
    #                      (0 = entry default). The serving draft
    #                      workflow: train a shallow twin of the target
    #                      config (--num_layers d) and point
    #                      ServeEngine.from_checkpoint(draft_dir=...) at
    #                      it — same vocab/width, restored through the
    #                      same layout converter (serve/spec.py)
    coordinator_address: str | None = None  # jax.distributed rendezvous
    num_processes: int | None = None
    process_id: int | None = None
    model: str = "mlp"  # model-zoo key (models/registry.py)
    dataset_size: int = 100_000  # reference: FooDataset(100000) at ddp.py:135
    data_dir: str | None = None  # file-backed store (data/filestore.py); None = synthetic
    eval_data_dir: str | None = None  # held-out store (e.g. the CIFAR-10 test
    #                                   split); None = tail-holdout of data_dir
    augment: str = "none"  # on-device augmentation: none | flip | crop-flip
    eval_steps: int = 0  # 0 disables; reference evaluate() is a stub (ddp.py:123-124)
    keep_checkpoints: int = 5  # retain the newest N step dirs (0 = unbounded);
    #                            the reference GCs nothing (ddp.py:254-277)
    eval_only: bool = False  # evaluate a checkpoint (no training); needs one
    resume: bool = True  # auto-resume from latest checkpoint in output_dir
    hot_save_steps: int = 0  # hot-checkpoint cadence (checkpoint/hot.py):
    #                          fast local-disk snapshots of the whole
    #                          state every N steps, layered UNDER the
    #                          durable orbax saves (atomic staging dir +
    #                          generation counter + per-leaf CRCs; the
    #                          newest VALID generation is preferred over
    #                          an older durable step on restore, so a
    #                          crash loses O(hot_save_steps) work instead
    #                          of O(save_steps)). Cost booked to the
    #                          goodput `hot_checkpoint_save` bucket.
    #                          0 = off
    supervise: str = "off"  # off | warn | act — supervisor policy
    #                         (train/supervisor.py): confirmed
    #                         straggler/mem-pressure verdicts from the
    #                         r12/r14 sentry trigger checkpoint →
    #                         evict-the-named-host → coordinated stop
    #                         (the r6 device-side agreement) → resume on
    #                         the healthy subset via reshard-on-restore.
    #                         warn logs the would-be action only; every
    #                         decision lands in supervisor.json and the
    #                         goodput `evict_resume` bucket
    supervise_cooldown_s: float = 600.0  # hysteresis: a stopping verdict
    #                         within this window of the previous ACTED
    #                         stop is downgraded to observe-only (a
    #                         flapping host cannot evict-loop the
    #                         fleet); enforced across attempts from the
    #                         supervisor.json ledger. 0 = off
    supervise_evict_budget: int = 4  # max acted evictions per trailing
    #                         24h (the "K evictions per day" budget,
    #                         same ledger); past it, evict verdicts are
    #                         recorded suppressed. 0 = unlimited
    inject_fault: str = ""  # deterministic fault injection
    #                         "kind:step[:param]" with kind one of
    #                         crash | hang-host | corrupt-hot-snapshot |
    #                         slow-host (train/supervisor.FaultInjector)
    #                         — drives the elastic stack in tests and
    #                         BENCH_MODE=elastic; empty = off
    profile_steps: int = 0  # trace steps [10, 10+N) to output_dir/profile (SURVEY.md §5.1)
    divergence_check_steps: int = 0  # cross-host param fingerprint every N steps (§5.2)
    preempt_sync_steps: int = 8  # legacy (accepted, unused): SIGTERM agreement
    #                              now rides inside the jitted step every step
    telemetry: str = "async"  # async (device arrays drained off-thread) | sync
    #                           (inline host conversion — the pre-async loop,
    #                           kept as the host_overhead_pct "before" leg)
    max_inflight_steps: int = 2  # bounded dispatch depth: the loop reads one
    #                              scalar from the step N-K dispatch each
    #                              iteration, capping host-side buffer growth
    #                              and carrying the device-side stop agreement
    health_pack: bool = True  # in-step device-side health scalars
    #                           (obs/health.py): param norm, update ratio,
    #                           non-finite counts, per-layer grad norms
    #                           under --scan_layers, EF-residual norm —
    #                           computed inside the jitted step, drained
    #                           through the async telemetry channel
    #                           (zero extra host syncs; overhead measured
    #                           by BENCH_MODE=obs). --no_health_pack for
    #                           the before-leg / minimal-metrics runs
    anomaly: str = "off"  # off | warn | halt — anomaly sentry
    #                       (obs/sentry.py): rolling median/MAD spike
    #                       detection on loss/grad_norm + a non-finite
    #                       trigger over the per-step health feed; on
    #                       trigger, dump a flight-record triage bundle
    #                       to <output_dir>/flight_records/. `halt` also
    #                       stops the run through the same device-side
    #                       stop agreement SIGTERM uses (checkpoint +
    #                       clean exit on every host coherently)
    anomaly_window: int = 128  # ring-buffer steps the sentry keeps (and
    #                            the rolling median/MAD history length)
    anomaly_threshold: float = 10.0  # spike trigger at
    #                                  |x - median| > threshold * scale,
    #                                  scale = max(1.4826*MAD, 5%|median|)
    perf_report: bool = False  # performance-attribution subsystem
    #                            (obs/attribution.py): AOT-compile the
    #                            step at startup (shared with
    #                            --hlo_report when both are on), derive
    #                            the static cost model (model FLOPs/step
    #                            + HBM bytes/step from cost_analysis,
    #                            collective wire bytes/step per mesh
    #                            axis from the op census) and emit
    #                            rolling MFU, achieved HBM/wire GB/s and
    #                            the compute/comm/host/input fractional
    #                            breakdown into the progress records.
    #                            Costs one extra AOT compilation at
    #                            startup — opt-in like --hlo_report.
    #                            The goodput ledger (obs/goodput.py)
    #                            runs regardless: it is host-side float
    #                            adds + one JSON write per interval
    perf_every: int = 0  # cadence (steps) of the perf-attribution
    #                      records and goodput.json flushes; 0 = ride
    #                      the --logging_steps cadence (perf fields
    #                      merge into the progress record)
    peak_tflops: float = 0.0  # per-chip peak bf16 TFLOP/s override for
    #                           MFU; 0 = use the obs/attribution.py
    #                           PEAK_FLOPS spec table (required for
    #                           hardware the table does not know — MFU
    #                           is omitted rather than invented)
    fleet: bool = False  # fleet watchtower (obs/fleet.py): periodic
    #                      cross-host exchange of host-side signals
    #                      (step wall, input/host/device-wait fractions,
    #                      producer idle, goodput deltas, anomaly state)
    #                      at the perf/logging cadence ON the telemetry
    #                      drain thread — never the hot loop. Rank-0
    #                      logs a min/median/max fleet table; a host
    #                      slower than the fleet median by more than
    #                      --straggler_threshold for
    #                      --straggler_windows consecutive windows
    #                      feeds the sentry as a `straggler` trigger
    #                      (triage bundle names the host). Degenerate
    #                      (this host only) on single-process runs
    straggler_threshold: float = 0.25  # relative step-wall excess over
    #                                    the fleet median that marks a
    #                                    window suspect (0.25 = 25%)
    straggler_windows: int = 3  # consecutive suspect windows before the
    #                             straggler verdict fires
    status_port: int = 0  # opt-in live status endpoint (obs/server.py):
    #                       serve /status (JSON snapshot: latest
    #                       progress/perf records, goodput, sentry,
    #                       fleet table), /metrics (Prometheus text
    #                       format, tpuddp_ gauges) and /healthz on
    #                       this port from a background daemon thread;
    #                       0 = off; -1 = bind an ephemeral port (the
    #                       actual port is logged and exposed as
    #                       Trainer.status.port — tests/bench, where a
    #                       probed "free" port could be taken back in
    #                       the build/compile window before bind).
    #                       Closed in the engine's crash-safe shutdown
    #                       path
    status_host: str = "0.0.0.0"  # interface --status_port binds;
    #                               default all interfaces (a fleet's
    #                               Prometheus scrapes cross-host, the
    #                               node-exporter convention) — pass
    #                               127.0.0.1 to keep the endpoint
    #                               loopback-only (it serves the full
    #                               config snapshot, unauthenticated)
    regression_pct: float = 20.0  # perf-regression tripwire band
    #                               (obs/regression.py): a restarted
    #                               run whose steady step wall is
    #                               slower (or MFU lower) than the
    #                               prior attempt's perf_baseline.json
    #                               by more than this percentage WARNs
    #                               with the delta
    mem_report: bool = False  # memory X-ray (obs/memory.py): ride the
    #                           startup AOT compile (shared with
    #                           --perf_report/--hlo_report) for a
    #                           compile-time memory split
    #                           (memory_analysis: argument/output/temp/
    #                           code/aliased bytes) + a donation audit
    #                           that WARNs on undonated train-state
    #                           leaves (a silently doubled state
    #                           footprint); poll device.memory_stats()
    #                           on the telemetry drain thread at the
    #                           perf/logging cadence into kind="mem"
    #                           records (per-device bytes-in-use/peak/
    #                           limit, rolling watermark, per-phase peak
    #                           attribution — backends without
    #                           memory_stats degrade to the static
    #                           model, never an invented watermark);
    #                           feed the sentry a mem_pressure trigger
    #                           when the watermark crosses the budget;
    #                           attach memory forensics (live-buffer
    #                           census + the split + last K records) to
    #                           flight bundles. Opt-in: costs one AOT
    #                           compile at startup, like its siblings
    mem_budget_frac: float = 0.9  # capacity tripwire bar: projected/
    #                               measured peak HBM above this
    #                               fraction of the device limit WARNs
    #                               at startup and triggers the sentry
    #                               (kind="mem_pressure") at runtime
    hlo_report: bool = False  # compile the train step ahead of the loop
    #                           and write an HLO schedule report
    #                           (obs/hlo_report.py) to
    #                           <output_dir>/hlo_report.json: collective
    #                           census + wire bytes, overlap-evidence
    #                           walkers, and WARNs when an overlap flag's
    #                           collectives are not compute-independent
    #                           (the schedule-regression tripwire). Costs
    #                           one extra ahead-of-time compilation

    def __post_init__(self) -> None:
        # --fsdp_overlap is an execution strategy FOR the FSDP layout: the
        # sharded stacked weights it gathers only exist under --fsdp, so
        # the flag implies it (the same way --fsdp subsumes --zero1)
        if self.fsdp_overlap:
            self.fsdp = True
        if self.grad_comm not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"unknown --grad_comm {self.grad_comm!r}; expected "
                "fp32 | bf16 | int8"
            )
        if self.num_layers < 0:
            raise ValueError(
                f"--num_layers must be >= 0 (0 = the zoo entry's "
                f"default depth), got {self.num_layers}"
            )
        if self.ddp_overlap and self.fsdp:
            # mutually exclusive by construction: --ddp_overlap's reduce
            # regions assume replicated params, --fsdp shards them (its
            # own overlapped execution is --fsdp_overlap)
            raise ValueError(
                "--ddp_overlap assumes replicated params and cannot "
                "compose with --fsdp/--fsdp_overlap (whose grads are "
                "reduce-scattered by layout); pick one execution mode"
            )
        if self.grad_comm != "fp32" and not self.ddp_overlap:
            raise ValueError(
                f"--grad_comm {self.grad_comm} compresses the per-layer "
                "grad reduce that only exists under --ddp_overlap (the "
                "GSPMD-implicit reduce is fp32-or-nothing); pass "
                "--ddp_overlap too"
            )
        if self.grad_error_feedback and self.grad_comm == "fp32":
            raise ValueError(
                "--grad_error_feedback compensates lossy gradient "
                "compression; with --grad_comm fp32 there is no error to "
                "feed back — pass --grad_comm bf16|int8 or drop the flag"
            )
        if self.tp_overlap and not self.scan_layers:
            raise ValueError(
                "--tp_overlap needs --scan_layers: the ring-decomposed "
                "block is compiled once and driven over the stacked "
                "(num_layers, ...) weights; pass both flags"
            )
        if self.tp_overlap and self.fsdp and not self.fsdp_overlap:
            # the composed schedule needs the EXPLICIT gather pipeline:
            # plain GSPMD FSDP leaves data-split weights that the ring
            # region specs would silently unshard every layer
            raise ValueError(
                "--tp_overlap composes with FSDP only through "
                "--fsdp_overlap (the explicit gather pipeline carries the "
                "model placement through its region specs); plain --fsdp "
                "leaves GSPMD-managed data-split weights the ring regions "
                "cannot serve — pass --fsdp_overlap instead of --fsdp"
            )
        # EF×tp composes since r17: the residual leaves are sized for the
        # model-sharded layout (compress.residual_shape_tp), so the
        # ddp×tp drain's per-shard quantization error telescopes per
        # (data, model) coordinate — the r11 named refusal, lifted
        if self.quant_compute not in ("off", "int8", "fp8"):
            raise ValueError(
                f"unknown --quant_compute {self.quant_compute!r}; "
                "expected off | int8 | fp8"
            )
        if self.pipe_schedule not in ("gpipe", "1f1b", "zb"):
            raise ValueError(
                f"unknown --pipe_schedule {self.pipe_schedule!r}; "
                "expected gpipe | 1f1b | zb"
            )
        if self.pipe_microbatches < 1:
            raise ValueError(
                f"--pipe_microbatches must be >= 1, got "
                f"{self.pipe_microbatches}"
            )
        if self.perf_every < 0:
            raise ValueError(
                f"--perf_every must be >= 0, got {self.perf_every} "
                "(0 = ride the --logging_steps cadence)"
            )
        if self.peak_tflops < 0:
            raise ValueError(
                f"--peak_tflops must be >= 0, got {self.peak_tflops} "
                "(0 = use the obs/attribution.py spec table)"
            )
        if self.status_port < -1 or self.status_port > 65535:
            raise ValueError(
                f"--status_port must be in [-1, 65535], got "
                f"{self.status_port} (0 = off, -1 = ephemeral)"
            )
        if self.straggler_threshold <= 0:
            raise ValueError(
                f"--straggler_threshold must be > 0, got "
                f"{self.straggler_threshold} (a relative excess over the "
                "fleet median, e.g. 0.25 = 25%)"
            )
        if self.straggler_windows < 1:
            raise ValueError(
                f"--straggler_windows must be >= 1, got "
                f"{self.straggler_windows}"
            )
        if self.regression_pct <= 0:
            raise ValueError(
                f"--regression_pct must be > 0, got {self.regression_pct}"
            )
        if not (0.0 < self.mem_budget_frac <= 1.0):
            raise ValueError(
                f"--mem_budget_frac must be in (0, 1], got "
                f"{self.mem_budget_frac} (a fraction of the device HBM "
                "limit, e.g. 0.9 = warn at 90%)"
            )
        if self.mem_report and not (self.logging_steps or self.perf_every):
            raise ValueError(
                "--mem_report polls the HBM watermark at the perf/logging "
                "cadence, but both --logging_steps and --perf_every are 0 "
                "— set one of them or drop --mem_report (a cadence-less "
                "watermark never samples)"
            )
        if self.fleet and not (self.logging_steps or self.perf_every):
            raise ValueError(
                "--fleet exchanges at the perf/logging cadence, but both "
                "--logging_steps and --perf_every are 0 — set one of them "
                "or drop --fleet (a cadence-less watchtower never fires)"
            )
        if self.hot_save_steps < 0:
            raise ValueError(
                f"--hot_save_steps must be >= 0, got "
                f"{self.hot_save_steps} (0 = off)")
        if self.supervise not in ("off", "warn", "act"):
            raise ValueError(
                f"unknown --supervise {self.supervise!r}; expected "
                "off | warn | act")
        if self.supervise_cooldown_s < 0:
            raise ValueError(
                f"--supervise_cooldown_s must be >= 0, got "
                f"{self.supervise_cooldown_s} (0 = off)")
        if self.supervise_evict_budget < 0:
            raise ValueError(
                f"--supervise_evict_budget must be >= 0, got "
                f"{self.supervise_evict_budget} (0 = unlimited)")
        if self.inject_fault:
            # fail a typo'd fault spec at parse time, not at the
            # injection step hours into the run it was meant to test
            # (lazy import: the supervisor module is jax-free, but the
            # common no-fault construction should not pay any import)
            from .train.supervisor import FaultInjector

            FaultInjector.parse(self.inject_fault)
        if self.anomaly not in ("off", "warn", "halt"):
            raise ValueError(
                f"unknown --anomaly {self.anomaly!r}; expected "
                "off | warn | halt"
            )
        if self.anomaly != "off" and not self.health_pack:
            raise ValueError(
                "--anomaly needs the in-step health pack (its non-finite "
                "counters are the sentry's hard trigger); drop "
                "--no_health_pack or set --anomaly off"
            )
        if self.grad_error_feedback and self.gradient_accumulation_steps > 1:
            raise ValueError(
                "--grad_error_feedback does not compose with "
                "--gradient_accumulation_steps > 1 yet: each microbatch "
                "would need the previous one's residual sequentially, but "
                "the accumulation scan reduces per microbatch in "
                "parallel semantics; drop one of the two"
            )

    def validate_mesh_consistency(self) -> None:
        """Reject overlap-flag × ``--mesh`` combinations that can never
        build, at parse time and with the reason named — instead of
        failing deep inside shard_map spec construction after model init.

        Syntactic check on the mesh *spec string* (no devices needed):
        an axis is treated as live when its size is > 1 or the ``-1``
        wildcard (which could resolve to > 1; the runtime validators
        still catch a wildcard that lands on 1). Called by
        :func:`parse_args`; programmatic ``TrainingConfig`` construction
        with an externally-built mesh is validated at build time instead
        (``models/registry.py``).
        """
        if not (self.fsdp_overlap or self.ddp_overlap or self.tp_overlap):
            return
        flags = "/".join(
            f for f, on in (("--fsdp_overlap", self.fsdp_overlap),
                            ("--ddp_overlap", self.ddp_overlap),
                            ("--tp_overlap", self.tp_overlap)) if on)
        axes: dict[str, int] = {}
        for part in self.mesh.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, size_s = part.partition(":")
            try:
                axes[name] = int(size_s) if size_s else -1
            except ValueError:
                return  # malformed spec: leave it to parse_mesh_spec
        live = {n: s for n, s in axes.items() if s == -1 or s > 1}
        # the pipelined entries compose pipe with one of tp/ddp/fsdp
        # since r22 (parallel/pipeline.py boundary-hoisted waves), so a
        # live pipe axis is admitted there; the per-run refusal matrix
        # (parallel/schedule.py::validate_schedule_mesh) still applies
        # at build time
        allowed = {"data", "model"}
        if self.model.startswith("gpt-pipe"):
            allowed.add("pipe")
        extra = {n: s for n, s in live.items() if n not in allowed}
        if extra:
            raise ValueError(
                f"{flags} composes over data×model only (plus pipe on "
                f"the pipelined entries), but --mesh {self.mesh!r} has "
                f"live axes {extra} — drop those axes or the overlap "
                "flags"
            )
        if self.tp_overlap and "model" not in live:
            raise ValueError(
                f"--tp_overlap decomposes model-axis collectives, but "
                f"--mesh {self.mesh!r} has no live model axis — add "
                "model:N (N>=2) to --mesh or drop --tp_overlap"
            )
        if "model" in live and not self.tp_overlap:
            which = ("--fsdp_overlap" if self.fsdp_overlap
                     else "--ddp_overlap")
            why = ("model-sharded weights the gather region specs would "
                   "silently unshard" if self.fsdp_overlap else
                   "model-sharded (not replicated) params the reduce "
                   "region specs would silently unshard")
            raise ValueError(
                f"{which} on --mesh {self.mesh!r}: a live model axis "
                f"means {why} — pass --tp_overlap too (the composed "
                "schedule) or drop the model axis"
            )

    @property
    def data_axis_size(self) -> int:
        """Number of data-parallel replicas under ``self.mesh``.

        Delegates to the runtime's canonical mesh-spec parser (lazy import:
        ``runtime.context`` imports this module at its top level), so a spec
        that cannot build a mesh fails here too instead of silently flooring.
        """
        import jax

        from .runtime.context import parse_mesh_spec

        return parse_mesh_spec(self.mesh, jax.device_count()).get("data", 1)

    @property
    def train_batch_size(self) -> int:
        """Global batch per optimizer micro-step across all *replicas*.

        Reference computes ``per_gpu * max(1, n_gpu)`` (``ddp.py:110-111``)
        — batch scales with the number of replicas. On a pure-DP mesh every
        chip is a replica; under tensor/sequence parallelism a replica is a
        model×seq device group, so the multiplier is the ``data`` axis size,
        not the global device count.
        """
        return self.per_device_train_batch_size * self.data_axis_size

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "TrainingConfig":
        raw: dict[str, Any] = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save(self, directory: str | Path) -> Path:
        path = Path(directory) / "training_config.json"
        path.write_text(self.to_json())
        return path


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native distributed trainer")
    # reference surface -----------------------------------------------------
    p.add_argument("--global-step", "--global_step", dest="global_step", type=int, default=0,
                   help="Checkpoint step to resume from (0 = fresh or auto-latest).")
    p.add_argument("--cpu", "--no_cuda", dest="cpu", action="store_true",
                   help="Force the CPU backend (reference: --no_cuda).")
    p.add_argument("--output_dir", type=str, default="outputs")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--gradient_accumulation_steps", type=int, default=1)
    p.add_argument("--per_device_train_batch_size", "--per_gpu_train_batch_size",
                   dest="per_device_train_batch_size", type=int, default=128)
    p.add_argument("--max_steps", type=int, default=-1)
    p.add_argument("--logging_steps", type=int, default=50)
    p.add_argument("--save_steps", type=int, default=50)
    p.add_argument("--num_train_epochs", type=float, default=3.0)
    p.add_argument("--warmup_steps", type=int, default=0)
    p.add_argument("--max_grad_norm", type=float, default=1000.0)
    p.add_argument("--local_rank", type=int, default=-1,
                   help="Accepted for launcher compatibility; ignored under JAX.")
    p.add_argument("--bf16", "--fp16", dest="bf16", action="store_true",
                   help="bfloat16 compute (reference: --fp16; no loss scaling on TPU).")
    p.add_argument("--loss_scale", type=float, default=0,
                   help="Accepted for compatibility; bf16 needs no loss scaling.")
    p.add_argument("--fp16_opt_level", type=str, default="O1",
                   help="Accepted for compatibility; bf16 has a single policy.")
    # TPU-native additions --------------------------------------------------
    p.add_argument("--learning_rate", type=float, default=1e-3)
    p.add_argument("--lr_schedule", type=str, default="linear",
                   choices=["linear", "cosine", "constant"],
                   help="Warmup + decay shape: linear matches the "
                        "reference's get_linear_schedule_with_warmup; "
                        "cosine is the standard transformer recipe; "
                        "constant holds base LR after warmup.")
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "momentum", "adam", "adamw", "lamb",
                            "lars"])
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight_decay", type=float, default=0.0)
    p.add_argument("--adam_beta1", type=float, default=0.9)
    p.add_argument("--adam_beta2", type=float, default=0.999)
    p.add_argument("--adam_eps", type=float, default=1e-8)
    p.add_argument("--mesh", type=str, default="data:-1")
    p.add_argument("--cp_impl", type=str, default="ring",
                   choices=["ring", "ulysses"],
                   help="Context-parallel attention engine over the seq "
                        "axis: ring (ppermute) or ulysses (all-to-all).")
    p.add_argument("--pipe_microbatches", type=int, default=4,
                   help="Microbatch count for the pipelined entries "
                        "(more microbatches shrink the pipeline bubble; "
                        "clamped to divide the per-replica batch — a "
                        "clamp to 1 is refused, the pipeline would "
                        "serialise).")
    p.add_argument("--pipe_schedule", type=str, default="1f1b",
                   choices=["gpipe", "1f1b", "zb"],
                   help="Pipeline schedule for the pipelined entries "
                        "(parallel/pipeline.py): 'gpipe' = masked "
                        "fill/drain with AD backward (the round-4 "
                        "baseline; O(M) activation residency); '1f1b' = "
                        "fused one-forward-one-backward slot loop "
                        "(Megatron 1F1B; O(P) residency, per-microbatch "
                        "loss on the last stage inside the schedule); "
                        "'zb' = zero-bubble: backward split into the "
                        "critical-path dx pass and dw products deferred "
                        "to a batched post-loop wave filling the drain "
                        "region (ZB-H1 lineage).")
    p.add_argument("--zero1", action="store_true",
                   help="Shard optimizer state over the data axis (ZeRO-1): "
                        "momentum/Adam memory divided by the DP degree.")
    p.add_argument("--fsdp", action="store_true",
                   help="Shard params, grads and optimizer state over the "
                        "data axis (FSDP/ZeRO-3): per-chip model memory "
                        "divided by the DP degree; GSPMD inserts the "
                        "gather/scatter protocol. Subsumes --zero1.")
    p.add_argument("--fsdp_overlap", action="store_true",
                   help="Decomposed-FSDP execution (parallel/overlap.py): "
                        "the scanned transformer stack gathers layer k+1's "
                        "weights under layer k's compute and drains layer "
                        "k's grad reduction under layer k-1's backward, so "
                        "the collectives hide behind the matmuls instead "
                        "of serialising before them. Implies --fsdp; "
                        "requires --scan_layers; transformer families on "
                        "data-only meshes. Gathered weights never exceed "
                        "two layers live.")
    p.add_argument("--xla_overlap_flags", action="store_true",
                   help="Append the XLA latency-hiding-scheduler flag "
                        "pack (async collectives overlapped with compute) "
                        "to XLA_FLAGS before backend init — the compiler "
                        "half of --fsdp_overlap. Applied only when a TPU "
                        "plugin is importable and the CPU backend is not "
                        "forced (unknown flags are FATAL to other "
                        "backends); the runtime logs exactly what was set "
                        "or why it was skipped.")
    p.add_argument("--ddp_overlap", action="store_true",
                   help="Per-layer overlapped gradient reduce for pure "
                        "DDP (parallel/compress.py): the scanned stack's "
                        "hand-written backward issues each layer's cross-"
                        "replica grad reduce inside its own reverse-scan "
                        "iteration, so the reduce drains under the next "
                        "layer's backward compute — PyTorch DDP's bucketed-"
                        "allreduce overlap, TPU-native (one bucket per "
                        "layer, pinned by construction). Requires "
                        "--scan_layers; replicated-param data-only meshes; "
                        "FSDP/MoE/pipe entries refused.")
    p.add_argument("--grad_comm", type=str, default="fp32",
                   choices=["fp32", "bf16", "int8"],
                   help="Wire precision of the --ddp_overlap per-layer "
                        "grad reduce: quantized reduce-scatter -> fp32 "
                        "dequant-sum -> re-quantized all-gather. bf16 "
                        "halves and int8 quarters gradient wire bytes "
                        "(chunked symmetric per-bucket quantization with "
                        "stochastic rounding). Embedding/head grads "
                        "outside the scanned stack keep the GSPMD fp32 "
                        "reduce; startup logs record both byte totals.")
    p.add_argument("--grad_error_feedback", action="store_true",
                   help="Keep each replica's gradient-compression error in "
                        "a TrainState residual and re-inject it next step "
                        "(1-bit-SGD lineage error feedback): the applied-"
                        "update sum tracks the true-gradient sum to within "
                        "one step's residual. Needs a lossy --grad_comm. "
                        "Residuals checkpoint best-effort: restoring onto "
                        "a different topology or from a pre-residual "
                        "checkpoint zero-initialises them (fresh runs "
                        "recommended when changing comm settings).")
    p.add_argument("--tp_overlap", action="store_true",
                   help="Decomposed tensor-parallel collective matmuls "
                        "(parallel/collective_matmul.py): the scanned "
                        "stack's Megatron matmuls run as ring collectives "
                        "over the `model` mesh axis — all-gather-matmul "
                        "for column-split fc1/fused-qkv (each activation "
                        "chunk's partial dot hides the next chunk's "
                        "single-hop ppermute), matmul-reduce-scatter for "
                        "row-split fc2/out (partials reduce around the "
                        "ring; no blocking psum), with hand-written "
                        "backwards pipelining the transposed collectives. "
                        "The model-sharded LM head accumulates per-shard "
                        "partial logits around the same ring (fused_head "
                        "is turned on for LM families). Requires "
                        "--scan_layers and a model:N mesh axis. Composes "
                        "with --fsdp_overlap (gathers carry the model "
                        "placement) and --ddp_overlap (one data x model "
                        "region, merged grad drain); plain --fsdp and "
                        "MoE/pipe refused.")
    p.add_argument("--fused_head", action="store_true",
                   help="Compute the LM head blockwise over the vocab "
                        "(ops/lm_head.py): the (B,T,V) logits tensor never "
                        "materialises. gpt-long/bert-long default it on; "
                        "this turns it on for the other LM families.")
    p.add_argument("--num_layers", type=int, default=0,
                   help="Override the zoo entry's transformer depth "
                        "(0 = entry default; transformer families only). "
                        "The speculative-serving draft workflow: train a "
                        "shallow twin of the target config with "
                        "--num_layers d, then serve with "
                        "ServeEngine.from_checkpoint(draft_dir=...) — "
                        "same vocab and width, depth is the only knob "
                        "(serve/spec.py shares the target's embedding "
                        "table at serving time).")
    p.add_argument("--quant_compute", type=str, default="off",
                   choices=["off", "int8", "fp8"],
                   help="Low-precision compute path (ops/quant.py): the "
                        "transformer block matmuls (fc1/fc2/qkv/out) run "
                        "as per-channel-scaled int8/fp8 dots re-derived "
                        "from the fp32 master weights every step — the "
                        "optimizer updates the masters, rounding error "
                        "never accumulates. Composed with --tp_overlap "
                        "the ring collective matmuls quantize each chunk "
                        "once and the ppermute carries the narrow tensor "
                        "+ its scales (~0.26x the fp32 ring wire), so "
                        "wire and FLOPs shrink together. fp8 uses e4m3 "
                        "values / e5m2 cotangents. Transformer families "
                        "only; MoE and the pipelined entries refused.")
    p.add_argument("--remat", action="store_true",
                   help="Rematerialise model blocks in backward: peak "
                        "activation memory for recompute FLOPs (measured a "
                        "net loss on HBM-bound resnet50 — see BENCH.md — "
                        "but unlocks otherwise-OOM batch/seq configs).")
    p.add_argument("--remat_policy", type=str, default="block",
                   choices=["block", "save-convs"],
                   help="With --remat: 'block' saves only block boundaries "
                        "(re-runs the convs in backward); 'save-convs' "
                        "(ResNet) saves conv outputs by name and recomputes "
                        "only the norm/ReLU chains — cheap elementwise "
                        "recompute for the post-norm activation stores.")
    p.add_argument("--scan_layers", action="store_true",
                   help="Scan-over-layers: compile ONE transformer block "
                        "and drive it over weights stacked on a leading "
                        "layer dim (nn.scan) — trace/compile time stops "
                        "growing with depth, and FSDP gets a uniform "
                        "always-dividable split axis. Composes with "
                        "--remat (remat-scan: activations saved only at "
                        "layer boundaries). Transformer families only; "
                        "checkpoints convert between layouts with "
                        "tools/convert_checkpoint.py.")
    p.add_argument("--coordinator_address", type=str, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--model", type=str, default="mlp")
    p.add_argument("--dataset_size", type=int, default=100_000)
    p.add_argument("--data_dir", type=str, default=None,
                   help="Train from a memory-mapped array store instead of "
                        "synthetic data (see data/filestore.py).")
    p.add_argument("--eval_data_dir", type=str, default=None,
                   help="Evaluate on this store (e.g. the CIFAR-10 test "
                        "split) instead of a tail holdout of --data_dir.")
    p.add_argument("--augment", type=str, default="none",
                   choices=["none", "flip", "crop-flip"],
                   help="On-device image augmentation inside the jitted step.")
    p.add_argument("--eval_steps", type=int, default=0)
    p.add_argument("--keep_checkpoints", type=int, default=5,
                   help="Retain only the newest N checkpoint dirs (0 = keep "
                        "all). A long run with small --save_steps otherwise "
                        "accumulates checkpoints without bound.")
    p.add_argument("--eval_only", action="store_true",
                   help="Run the exactly-once eval on a saved checkpoint "
                        "(latest, or --global-step) and exit — no training.")
    p.add_argument("--no_resume", dest="resume", action="store_false")
    p.add_argument("--hot_save_steps", type=int, default=0,
                   help="Hot-checkpoint cadence (checkpoint/hot.py): "
                        "snapshot the whole training state to local "
                        "disk every N steps, layered under the durable "
                        "orbax saves (atomic generation dirs, per-leaf "
                        "CRCs; the newest VALID snapshot is preferred "
                        "over an older durable step on restore, so a "
                        "crash loses O(N) steps instead of "
                        "O(save_steps)). Cost is booked to the goodput "
                        "hot_checkpoint_save bucket. 0 = off.")
    p.add_argument("--supervise", type=str, default="off",
                   choices=["off", "warn", "act"],
                   help="Supervisor policy (train/supervisor.py) over "
                        "confirmed sentry verdicts: 'act' turns a "
                        "straggler/mem-pressure verdict into checkpoint "
                        "-> evict the named host -> coordinated stop "
                        "(the r6 device-side agreement) -> resume on "
                        "the healthy subset via reshard-on-restore; "
                        "'warn' logs the would-be action only. Every "
                        "decision lands in supervisor.json, /status "
                        "and the goodput evict_resume bucket.")
    p.add_argument("--supervise_cooldown_s", type=float, default=600.0,
                   help="Supervisor hysteresis: a stopping verdict "
                        "landing within this window of the previous "
                        "acted stop is recorded but downgraded to "
                        "observe-only, so a flapping host cannot "
                        "evict-loop the fleet; enforced across "
                        "attempts from the supervisor.json ledger. "
                        "0 = off.")
    p.add_argument("--supervise_evict_budget", type=int, default=4,
                   help="Max acted evictions per trailing 24h (same "
                        "ledger); evict verdicts past the budget are "
                        "recorded suppressed. 0 = unlimited.")
    p.add_argument("--inject_fault", type=str, default="",
                   help="Deterministic fault injection 'kind:step"
                        "[:param]', kind one of crash | hang-host | "
                        "corrupt-hot-snapshot | slow-host — the "
                        "elastic-stack test harness (fires after that "
                        "step's save blocks; crash is a hard os._exit "
                        "with no final save). Empty = off.")
    p.add_argument("--profile_steps", type=int, default=0,
                   help="Capture a profiler trace over N steps (from step 10).")
    p.add_argument("--divergence_check_steps", type=int, default=0,
                   help="Cross-host replicated-state fingerprint check every N steps.")
    p.add_argument("--preempt_sync_steps", type=int, default=None,
                   help="DEPRECATED, accepted-and-unused. Multi-process "
                        "SIGTERM agreement now travels inside the jitted "
                        "train step (a device-side reduction over per-"
                        "process stop votes) and is read through the "
                        "bounded dispatch-depth barrier, so no host "
                        "allgather cadence exists anymore. Passing the "
                        "flag logs a one-time deprecation warning.")
    p.add_argument("--telemetry", type=str, default="async",
                   choices=["async", "sync"],
                   help="Scalar sink for logging_steps: 'async' hands device "
                        "arrays to a background drain thread (the loop "
                        "never blocks on a logging boundary; scalars may "
                        "land up to one interval late, step keys exact); "
                        "'sync' converts inline (pre-async behaviour, the "
                        "host_overhead_pct before-leg in BENCH_MODE=e2e).")
    p.add_argument("--no_health_pack", dest="health_pack",
                   action="store_false",
                   help="Disable the in-step health scalars (param norm, "
                        "update ratio ‖Δw‖/‖w‖, non-finite counts, "
                        "per-layer grad norms under --scan_layers, "
                        "EF-residual norm). On by default: the bundle is "
                        "a few fused device reductions riding the async "
                        "telemetry channel — BENCH_MODE=obs pins the "
                        "overhead inside the 0.9 neutrality band.")
    p.add_argument("--anomaly", type=str, default="off",
                   choices=["off", "warn", "halt"],
                   help="Anomaly sentry over the per-step health feed: "
                        "rolling median/MAD spike detection on "
                        "loss/grad_norm plus a non-finite trigger. On "
                        "trigger, a triage bundle (ring-buffer JSONL, "
                        "describe() snapshot, config, divergence "
                        "fingerprint, and a short profiler trace of the "
                        "following steps) lands in "
                        "<output_dir>/flight_records/. 'halt' then stops "
                        "the run through the same device-side stop "
                        "agreement SIGTERM uses — every host checkpoints "
                        "and exits at the same step.")
    p.add_argument("--anomaly_window", type=int, default=128,
                   help="Sentry ring-buffer length in steps (also the "
                        "rolling median/MAD history).")
    p.add_argument("--anomaly_threshold", type=float, default=10.0,
                   help="Spike sensitivity in robust deviations: trigger "
                        "at |x - median| > threshold * max(1.4826*MAD, "
                        "5%% of |median|).")
    p.add_argument("--perf_report", action="store_true",
                   help="Performance attribution (obs/attribution.py): "
                        "AOT-compile the step at startup (one compile, "
                        "shared with --hlo_report), derive a static cost "
                        "model (model FLOPs/step, HBM bytes/step, "
                        "collective wire bytes/step per mesh axis) and "
                        "emit rolling MFU, achieved HBM/wire GB/s and a "
                        "compute/comm/host/input fractional breakdown "
                        "(fractions sum to 1.0) into the progress "
                        "records. The goodput ledger runs regardless of "
                        "this flag.")
    p.add_argument("--perf_every", type=int, default=0,
                   help="Cadence in steps of the perf-attribution records "
                        "and goodput.json flushes (0 = ride "
                        "--logging_steps; perf fields then merge into "
                        "the progress record).")
    p.add_argument("--peak_tflops", type=float, default=0.0,
                   help="Per-chip peak bf16 TFLOP/s override for MFU "
                        "(0 = the obs/attribution.py spec table; on "
                        "hardware the table does not know, MFU is "
                        "omitted unless this is set).")
    p.add_argument("--fleet", action="store_true",
                   help="Fleet watchtower (obs/fleet.py): exchange each "
                        "host's host-side signals (step wall, "
                        "input/host/device-wait fractions, producer "
                        "idle, goodput deltas, anomaly state) across "
                        "processes at the perf/logging cadence, on the "
                        "telemetry drain thread. Rank 0 logs a "
                        "min/median/max fleet table; a sustained "
                        "straggler feeds the sentry as a `straggler` "
                        "trigger whose triage bundle names the host. "
                        "Single-process runs degrade to a one-host "
                        "table.")
    p.add_argument("--straggler_threshold", type=float, default=0.25,
                   help="Relative step-wall excess over the fleet median "
                        "that marks a window suspect (0.25 = 25%%).")
    p.add_argument("--straggler_windows", type=int, default=3,
                   help="Consecutive suspect windows before the "
                        "straggler verdict fires.")
    p.add_argument("--status_port", type=int, default=0,
                   help="Serve /status (JSON), /metrics (Prometheus "
                        "text format) and /healthz on this port from a "
                        "background thread (obs/server.py): the latest "
                        "drained progress/perf records, goodput "
                        "summary, sentry state and fleet table, live. "
                        "0 = off; -1 = ephemeral port (logged at "
                        "startup). Closed in the engine's crash-safe "
                        "shutdown path.")
    p.add_argument("--status_host", type=str, default="0.0.0.0",
                   help="Interface the --status_port endpoint binds. "
                        "Default all interfaces (fleet Prometheus "
                        "scrapes cross-host); pass 127.0.0.1 for a "
                        "loopback-only endpoint — it serves the full "
                        "config snapshot, unauthenticated.")
    p.add_argument("--regression_pct", type=float, default=20.0,
                   help="Perf-regression tripwire band: a restarted run "
                        "whose steady step wall is slower (or MFU "
                        "lower) than the prior attempt's "
                        "perf_baseline.json by more than this "
                        "percentage logs a WARNING with the delta.")
    p.add_argument("--mem_report", action="store_true",
                   help="Memory X-ray (obs/memory.py): compile-time "
                        "memory split (argument/output/temp/code/aliased "
                        "bytes from memory_analysis) + donation audit "
                        "(WARNs on undonated train-state leaves — a "
                        "silently doubled state footprint) off the "
                        "startup AOT compile (shared with "
                        "--perf_report/--hlo_report); a runtime HBM "
                        "watermark poller on the telemetry drain thread "
                        "(kind=\"mem\" records: per-device bytes-in-use/"
                        "peak/limit, rolling watermark, per-phase peak "
                        "attribution; backends without memory_stats "
                        "degrade to the static model); a capacity "
                        "tripwire at --mem_budget_frac of the device "
                        "limit (startup WARN + sentry mem_pressure "
                        "trigger); and memory forensics (live-buffer "
                        "census + the split + last K mem records) in "
                        "flight bundles. Costs one extra AOT compile at "
                        "startup.")
    p.add_argument("--mem_budget_frac", type=float, default=0.9,
                   help="Capacity tripwire bar: projected/measured peak "
                        "HBM above this fraction of the device limit "
                        "warns at startup and feeds the sentry a "
                        "mem_pressure trigger at runtime (default 0.9).")
    p.add_argument("--hlo_report", action="store_true",
                   help="Compile the train step ahead of the loop and "
                        "write obs/hlo_report.py's schedule report to "
                        "<output_dir>/hlo_report.json (collective census "
                        "+ estimated wire bytes + the r8-r11 overlap-"
                        "evidence walkers), WARNing when an active "
                        "overlap flag's collectives are not compute-"
                        "independent in the compiled program — the "
                        "schedule-regression tripwire, in production "
                        "rather than only in bench. Costs one extra "
                        "ahead-of-time compilation at startup.")
    p.add_argument("--max_inflight_steps", type=int, default=2,
                   help="Bounded dispatch depth K: each iteration the loop "
                        "reads one scalar produced K steps ago (complete in "
                        "steady state, so the read is ~free). Caps host-side "
                        "buffer growth and, on multi-process runs, carries "
                        "the device-side preemption-stop agreement (stop "
                        "lands within K steps of every host voting).")
    return p


def parse_args(argv: list[str] | None = None) -> TrainingConfig:
    ns = build_arg_parser().parse_args(argv)
    if ns.preempt_sync_steps is not None:
        # accepted-and-unused since the host-sync-free hot loop landed;
        # silently ignoring an explicit flag hides dead config from the
        # user, so say so ONCE (warnings dedupe repeat emissions)
        import warnings

        warnings.warn(
            "--preempt_sync_steps is deprecated and has no effect: the "
            "SIGTERM stop agreement rides inside the jitted train step "
            "(device-side vote reduction read through the dispatch-depth "
            "barrier); drop the flag",
            DeprecationWarning,
            stacklevel=2,
        )
    else:
        ns.preempt_sync_steps = 8  # dataclass default, for config dumps
    known = {f.name for f in dataclasses.fields(TrainingConfig)}
    config = TrainingConfig(
        **{k: v for k, v in vars(ns).items() if k in known})
    # overlap-flag × mesh inconsistencies fail HERE with named reasons,
    # not deep inside shard_map spec construction after model init
    config.validate_mesh_consistency()
    return config
