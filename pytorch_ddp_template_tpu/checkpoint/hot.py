"""Hot checkpoints: fast local-disk snapshots layered UNDER orbax.

CheckFreq's (Mohan et al., FAST'21) observation is that checkpoint
cadence is set by checkpoint *cost*: durable orbax saves are priced for
durability (every host participates, OCDBT commit protocol), so runs
space them out — and a preemption then loses up to ``--save_steps`` of
work. The hot layer closes that gap with a second, much cheaper tier:

- ``--hot_save_steps N`` snapshots the whole training state to LOCAL
  disk every N steps: one ``device_get`` of the flat leaves, one
  ``.npz`` write, a manifest. No cross-host protocol, no orbax session.
- **Atomic + generational** — each snapshot is staged in a temp dir and
  ``os.replace``d into ``<output_dir>/hot/gen_<g>_step_<s>`` with the
  manifest (step, generation counter, per-leaf CRCs, the full config)
  written last *inside* the staging dir: a kill mid-write leaves a temp
  dir the next scan ignores, never a half-snapshot that validates. The
  newest ``keep`` generations are retained so one corrupt/partial
  snapshot still leaves a previous hot generation before falling all
  the way back to durable.
- **Restore preference** — ``Trainer.restore_or_init`` prefers the
  newest *valid* hot snapshot over an older durable step (validation =
  manifest parse + leaf count + per-leaf CRC; anything invalid is
  logged and skipped). MTTR drops from ``O(save_steps)`` lost steps to
  ``O(hot_save_steps)``; ``BENCH_MODE=elastic`` measures both the
  overhead and the MTTR delta.
- **Cost accounting** — the engine books every hot save into the
  goodput ledger's ``hot_checkpoint_save`` bucket (split out of
  ``checkpoint_save``), so the MTTR-vs-overhead trade is readable in
  ``goodput.json`` and ``/metrics`` without post-processing.

The wire format is the pure-tree form from ``checkpoint/reshard.py``
(containers + flat leaves), so a hot snapshot restores through the SAME
reshard-on-restore placement path as a durable checkpoint — including
onto a different chip count or layer layout.

Multi-controller caveat (v1): a hot snapshot is one process's
``device_get`` of the full state, so it requires every leaf to be
fully addressable (single-process runs, or replicated state). The
first save on a run that does not qualify logs once and disables the
layer — the durable orbax tier keeps the fleet covered.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import time
import zlib
from pathlib import Path
from typing import Any

import jax
import numpy as np

from ..utils import get_logger
from ..utils.serialization import json_sanitize
from .manager import _split_residual
from .reshard import from_pure_arrays, to_pure

log = get_logger(__name__)

DIRNAME = "hot"
MANIFEST = "manifest.json"
ARRAYS = "arrays.npz"

_GEN_RE = re.compile(r"^gen_(\d+)_step_(\d+)$")


def _crc(a: np.ndarray) -> int:
    """CRC32 straight off the array's buffer — ``tobytes()`` would
    materialise a second copy of every leaf on each save AND each
    validated load (ascontiguousarray is copy-free on the already-
    contiguous arrays ``device_get``/``np.load`` produce)."""
    return int(zlib.crc32(np.ascontiguousarray(a)))


def _offset_markers(pure: Any, offset: int) -> Any:
    """Shift every ``{__leaf__: i}`` marker in a :func:`to_pure` tree by
    ``offset`` — the residual tree's markers index into the snapshot's
    ONE combined arrays list, after the body's leaves."""
    from .reshard import LEAF_KEY

    if isinstance(pure, dict):
        if set(pure) == {LEAF_KEY}:
            return {LEAF_KEY: int(pure[LEAF_KEY]) + offset}
        return {k: _offset_markers(v, offset) for k, v in pure.items()}
    if isinstance(pure, list):
        return [_offset_markers(v, offset) for v in pure]
    return pure


@dataclasses.dataclass
class HotSnapshot:
    """One validated hot snapshot, leaves already substituted: ``body``
    is the state field-dict (no ``comm_residual``), ``residual`` the
    separately-stored EF tree (or None) — mirroring the durable
    checkpoint's item split so both restore identically."""

    step: int
    generation: int
    body: Any
    residual: Any | None
    config: dict
    path: Path


@dataclasses.dataclass
class HotSnapshotMeta:
    """Manifest-only view of the newest committed generation — the
    cheap peek ``restore_or_init`` uses to DECIDE hot-vs-durable
    without reading or CRC-validating the array payload (a full
    redundant state read on every restart's critical path when the
    durable tier wins)."""

    step: int
    generation: int
    config: dict
    path: Path


class HotCheckpointManager:
    """Generational local-disk snapshots under ``<output_dir>/hot/``."""

    def __init__(self, output_dir: str | Path, *, keep: int = 2):
        self.base = Path(output_dir) / DIRNAME
        self.keep = max(int(keep), 1)
        #: set True once a save proves the state is not fully
        #: addressable from this process — the layer disables itself
        #: rather than snapshot a silently partial state
        self.disabled = False
        self.saves = 0

    # -- discovery ---------------------------------------------------------
    def generations(self) -> list[tuple[int, int, Path]]:
        """``(generation, step, path)`` for every committed snapshot dir,
        oldest first (staging dirs and strangers are ignored)."""
        if not self.base.is_dir():
            return []
        out = []
        for d in self.base.iterdir():
            m = _GEN_RE.match(d.name)
            if m and d.is_dir():
                out.append((int(m.group(1)), int(m.group(2)), d))
        return sorted(out)

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Any, config: Any) -> Path | None:
        """Snapshot ``state`` at ``step``; returns the committed dir, or
        None when the layer is disabled. Atomic: stage, manifest last,
        one ``os.replace``."""
        if self.disabled:
            return None
        body, residual = _split_residual(state)
        pure_body, leaves = to_pure(body)
        pure_res = None
        if residual is not None:
            pure_res, res_leaves = to_pure(residual)
            # one flat arrays list serves both trees: shift the residual
            # markers past the body leaves (to_pure numbers from 0)
            pure_res = _offset_markers(pure_res, len(leaves))
            leaves = leaves + res_leaves
        for leaf in leaves:
            if hasattr(leaf, "is_fully_addressable") \
                    and not leaf.is_fully_addressable:
                log.warning(
                    "hot checkpoints disabled: the training state is not "
                    "fully addressable from this process (multi-controller "
                    "sharded run) — v1 hot snapshots are single-controller; "
                    "the durable orbax tier still covers this run")
                self.disabled = True
                return None
        host_leaves = [np.asarray(x) for x in jax.device_get(leaves)]
        gens = self.generations()
        gen = (gens[-1][0] + 1) if gens else 1
        final = self.base / f"gen_{gen:08d}_step_{step:08d}"
        tmp = self.base / f".staging_gen_{gen:08d}_{os.getpid()}"
        try:
            shutil.rmtree(tmp, ignore_errors=True)
            tmp.mkdir(parents=True)
            np.savez(tmp / ARRAYS,
                     **{f"a{i}": arr for i, arr in enumerate(host_leaves)})
            cfg_payload = (dataclasses.asdict(config)
                           if dataclasses.is_dataclass(config)
                           else dict(config or {}))
            manifest = {
                "schema": "hot/v1",
                "generation": gen,
                "step": int(step),
                "time": time.time(),
                "n_leaves": len(host_leaves),
                "leaves": [{"shape": list(a.shape), "dtype": str(a.dtype),
                            "crc32": _crc(a)}
                           for a in host_leaves],
                "tree": pure_body,
                "residual_tree": pure_res,
                "config": cfg_payload,
            }
            # manifest LAST inside the staging dir: its presence marks a
            # complete payload, and the rename below publishes both at once
            (tmp / MANIFEST).write_text(
                json.dumps(json_sanitize(manifest), allow_nan=False))
            if final.exists():  # a re-save at the same generation (tests)
                shutil.rmtree(final)
            os.replace(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.saves += 1
        self._prune()
        return final

    def _prune(self) -> None:
        gens = self.generations()
        for _, _, path in gens[:-self.keep]:
            shutil.rmtree(path, ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def latest_meta(self) -> HotSnapshotMeta | None:
        """The newest committed generation's manifest metadata (step,
        config) WITHOUT touching the array payload. Unreadable
        manifests fall back to the previous generation. Full
        validation (leaf count + per-leaf CRC) stays in
        :meth:`latest_valid`, paid only once the hot tier is chosen."""
        for gen, step, path in reversed(self.generations()):
            try:
                manifest = json.loads((path / MANIFEST).read_text())
                return HotSnapshotMeta(
                    step=int(manifest["step"]), generation=gen,
                    config=dict(manifest.get("config") or {}), path=path)
            except Exception as exc:  # noqa: BLE001 - fall back older
                log.warning(
                    "hot snapshot %s manifest unreadable (%s) — "
                    "checking the previous generation", path.name,
                    type(exc).__name__)
        return None

    def latest_valid(self) -> HotSnapshot | None:
        """The newest snapshot that passes validation (manifest parse,
        leaf count, per-leaf CRC). Invalid generations — a corrupt or
        truncated snapshot from a crash or the fault injector — log a
        warning and fall back to the previous generation; None when no
        generation survives."""
        for gen, step, path in reversed(self.generations()):
            try:
                return self._load(gen, step, path)
            except Exception as exc:  # noqa: BLE001 - fall back older
                log.warning(
                    "hot snapshot %s failed validation (%s: %s) — falling "
                    "back to the previous generation / the durable tier",
                    path.name, type(exc).__name__, exc)
        return None

    def _load(self, gen: int, step: int, path: Path) -> HotSnapshot:
        manifest = json.loads((path / MANIFEST).read_text())
        n = int(manifest["n_leaves"])
        with np.load(path / ARRAYS) as z:
            arrays = [z[f"a{i}"] for i in range(n)]
        metas = manifest["leaves"]
        if len(metas) != n:
            raise ValueError(f"manifest leaf count mismatch ({len(metas)} "
                             f"!= {n})")
        for i, (a, m) in enumerate(zip(arrays, metas)):
            if list(a.shape) != list(m["shape"]):
                raise ValueError(f"leaf a{i} shape {list(a.shape)} != "
                                 f"manifest {m['shape']}")
            if _crc(a) != int(m["crc32"]):
                raise ValueError(f"leaf a{i} CRC mismatch (corrupt "
                                 "snapshot)")
        body = from_pure_arrays(manifest["tree"], arrays)
        residual = (from_pure_arrays(manifest["residual_tree"], arrays)
                    if manifest.get("residual_tree") is not None else None)
        return HotSnapshot(step=int(manifest["step"]), generation=gen,
                           body=body, residual=residual,
                           config=dict(manifest.get("config") or {}),
                           path=path)

    # -- fault injection (the deterministic harness) -----------------------
    def corrupt_latest(self, nbytes: int = 64) -> Path | None:
        """Flip ``nbytes`` of the newest generation's array payload in
        place (manifest left intact, so only the CRC check can catch
        it) — the ``--inject_fault corrupt-hot-snapshot:<step>`` kind,
        proving the restore-side fallback."""
        gens = self.generations()
        if not gens:
            return None
        path = gens[-1][2] / ARRAYS
        size = path.stat().st_size
        pos = max(size // 2, 0)
        with open(path, "r+b") as f:
            f.seek(pos)
            chunk = f.read(nbytes)
            f.seek(pos)
            f.write(bytes(b ^ 0xFF for b in chunk) or b"\xff")
        log.warning("fault injection: corrupted hot snapshot %s",
                    gens[-1][2].name)
        return gens[-1][2]
