"""Step-numbered checkpoints with symmetric save **and** restore.

Capability parity-plus with the reference's checkpoint writer
(``/root/reference/ddp.py:64-77, 254-277``): the reference saves four
artifacts (model / args / optimizer / scheduler) into
``outputs/checkpoint-{step}`` on rank 0, but has **no load path at all** —
``--global-step`` is parsed and never used (SURVEY.md §2d). Here save and
restore are symmetric, and both are multi-host-correct via orbax (every
process participates; OCDBT handles concurrent writers — the reference's
"no barrier after rank-0 save" hazard, SURVEY.md §3.4, cannot occur).

One orbax step directory holds the whole training state: params, optimizer
state, step, RNG key, and the JSON config (the reference's
``training_args.bin`` equivalent, portable instead of pickled).
The LR schedule needs no artifact — it is a pure function of the step
(``train/schedule.py``), so restoring the step restores the schedule;
the reference needed ``scheduler.pt`` only because ``LambdaLR`` is stateful.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from ..config import TrainingConfig
from ..utils import get_logger

log = get_logger(__name__)


def _split_residual(state: Any) -> tuple[Any, Any]:
    """``(body, residual)`` for a state that may carry ``comm_residual``.

    The error-feedback residual (``--grad_error_feedback``,
    ``parallel/compress.py``) is stored as its OWN checkpoint item, and
    the state body is serialised as a field dict *without* the key: the
    stored layout is byte-identical whether the field exists, is None, or
    holds a tree — so pre-residual checkpoints restore into the new
    ``TrainState`` and residual-carrying checkpoints restore into runs
    that turned error feedback off (the item is simply never requested).
    Non-dataclass states (raw pytrees from tools) pass through untouched.
    """
    if not hasattr(state, "comm_residual"):
        return state, None
    body = {f.name: getattr(state, f.name)
            for f in dataclasses.fields(state) if f.name != "comm_residual"}
    return body, state.comm_residual


class CheckpointManager:
    """Save/restore ``(state_pytree, config)`` at step-numbered dirs."""

    def __init__(self, directory: str | Path, *, max_to_keep: int | None = None):
        self.directory = Path(directory).absolute()
        base = dict(
            max_to_keep=max_to_keep,
            step_prefix="checkpoint",  # dirs named checkpoint_<step>, like the
            #                            reference's checkpoint-<step> (ddp.py:256)
            create=True,
        )
        try:
            # pin the async path explicitly (it is orbax's default, but the
            # engine's side-work accounting relies on save() being a
            # schedule-and-return, so state the contract rather than
            # inherit it)
            options = ocp.CheckpointManagerOptions(
                enable_async_checkpointing=True, **base
            )
        except TypeError:  # older orbax without the kwarg: default is async
            options = ocp.CheckpointManagerOptions(**base)
        #: save() schedules the write and returns; wait() is the durability
        #: barrier. The engine uses this to decide whether a save tripped
        #: the step-timer discard.
        self.is_async = True
        self._mngr = ocp.CheckpointManager(self.directory, options=options)

    # -- save -------------------------------------------------------------
    def save(self, step: int, state: Any, config: TrainingConfig,
             *, force: bool = False) -> None:
        from .. import native

        payload = dataclasses.asdict(config)
        # provenance: which RNG stream produced the data order (native C++
        # vs numpy fallback) — resume must replay the same stream for the
        # mid-epoch data-order restore to be exact
        payload["_native_rng"] = native.available()
        # the GLOBAL batch (per-device x data-axis size) is not a config
        # field but determines the eval tail-holdout split point; record
        # it so --eval_only can verify the split is reproducible
        payload["_train_batch_size"] = config.train_batch_size
        body, residual = _split_residual(state)
        items: dict[str, Any] = {
            "state": ocp.args.StandardSave(body),
            "config": ocp.args.JsonSave(payload),
        }
        if residual is not None:
            # separate item so runs without error feedback never see it
            # (and pre-residual checkpoints simply lack it)
            items["residual"] = ocp.args.StandardSave(residual)
        self._mngr.save(step, args=ocp.args.Composite(**items), force=force)
        log.info("checkpoint saved", {"step": step, "dir": str(self.directory)})

    def wait(self) -> None:
        """Block until any async save completes (call before process exit)."""
        self._mngr.wait_until_finished()

    # -- restore ----------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def read_config(self, step: int | None = None) -> dict | None:
        """Read just the JSON config of a checkpoint (no state restore) —
        used to validate template compatibility before StandardRestore.
        ``step=None`` walks steps newest-first past partially-written
        dirs (the restore fallback below does the same for the state)."""
        def attempt(s: int):
            restored = self._mngr.restore(
                s, args=ocp.args.Composite(config=ocp.args.JsonRestore()))
            return restored["config"]

        if step is not None:
            return attempt(step)
        try:
            return self._try_steps(None, attempt)
        except Exception:  # noqa: BLE001 - no step has a readable
            #               config: the caller proceeds template-first
            return None

    def _fallback_steps(self, step: int | None) -> list[int]:
        """The steps a restore may try: the explicit one alone, or —
        ``step=None`` (auto-latest) — every step newest-first, so a
        partially-written dir (crash mid-save) degrades to the latest
        COMPLETE step instead of killing the resume (r18 satellite)."""
        if step is not None:
            return [step]
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        return steps

    def _try_steps(self, step: int | None, attempt):
        """Run ``attempt(step)`` over :meth:`_fallback_steps`, logging and
        skipping steps that fail; re-raises the NEWEST step's error when
        none restores (a model mismatch fails every step identically —
        the caller's named refusal must surface, not the oldest copy)."""
        first_exc: Exception | None = None
        steps = self._fallback_steps(step)
        for i, s in enumerate(steps):
            try:
                return attempt(s)
            except Exception as exc:  # noqa: BLE001 - fall back, rethrow
                if first_exc is None:
                    first_exc = exc
                if i + 1 < len(steps):
                    log.warning(
                        "checkpoint step %s failed to restore "
                        "(%s: %s) — likely a partially-written save from "
                        "a crash mid-write; falling back to step %s",
                        s, type(exc).__name__, exc, steps[i + 1])
        assert first_exc is not None
        raise first_exc

    def restore_raw(self, step: int | None = None) -> tuple[int, Any, dict]:
        """Template-free restore: ``(step, state_pytree, config_dict)`` with
        arrays exactly as saved (host-local, no mesh placement).

        The checkpoint-conversion path (``tools/convert_checkpoint.py``
        restacking between the unrolled ``layer_{i}`` and the scanned
        stacked-layer layouts) needs the tree as stored — a template would
        impose the *destination* structure and defeat the conversion.
        ``step=None`` falls back past partially-written step dirs."""

        def attempt(s: int):
            restored = self._mngr.restore(
                s,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(),
                    config=ocp.args.JsonRestore(),
                ),
            )
            return s, restored["state"], restored["config"]

        return self._try_steps(step, attempt)

    def restore_resharded(self, step: int | None,
                          template_state: Any) -> tuple[Any, dict]:
        """Reshard-on-restore (r18): restore ``(state, config_dict)``
        through the template-free path, converting the layer layout
        in-process (scanned ↔ unrolled ↔ pipelined restacking — the
        ``tools/convert_checkpoint.py`` core) and placing every leaf
        onto the template's shardings, so a run restarted on a
        different chip count / mesh shape / layer layout restores
        directly instead of refusing. The whole state materialises on
        host once (the converter's contract); genuinely lossy
        mismatches still refuse with the leaf named."""
        from .reshard import place_state_onto_template

        step, raw, cfg = self.restore_raw(step)
        raw_res = None
        if _split_residual(template_state)[1] is not None:
            try:
                r = self._mngr.restore(
                    step,
                    args=ocp.args.Composite(
                        residual=ocp.args.StandardRestore()))
                raw_res = r["residual"]
            except Exception as exc:  # noqa: BLE001 - best-effort state
                log.warning(
                    "checkpoint has no comm_residual item "
                    f"({type(exc).__name__}); error-feedback residual "
                    "zero-initialised")
        state = place_state_onto_template(template_state, raw, raw_res)
        self._warn_rng_stream(cfg)
        log.info("checkpoint restored (resharded)", {"step": step})
        return state, cfg

    def _warn_rng_stream(self, cfg: Any) -> None:
        from .. import native

        saved_native = cfg.get("_native_rng") if isinstance(cfg, dict) else None
        if saved_native is not None and saved_native != native.available():
            log.warning(
                "checkpoint was written with a different RNG stream "
                "(native=%s, now=%s); resumed data order will not exactly "
                "replay the interrupted epoch",
                saved_native, native.available(),
            )

    def restore(self, step: int | None, template_state: Any) -> tuple[Any, dict]:
        """Restore ``(state, config_dict)``; ``step=None`` → latest
        COMPLETE step (partially-written dirs from a crash mid-save are
        logged and skipped — the r18 fallback).

        ``template_state`` supplies the pytree structure/shardings so arrays
        are restored directly onto their mesh placement.
        """
        return self._try_steps(
            step, lambda s: self._restore_at(s, template_state))

    def _restore_at(self, step: int, template_state: Any) -> tuple[Any, dict]:
        body_tmpl, res_tmpl = _split_residual(template_state)
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(body_tmpl),
                config=ocp.args.JsonRestore(),
            ),
        )
        state = restored["state"]
        if body_tmpl is not template_state:
            # field-dict body back into the dataclass; then the residual:
            # restore it when the checkpoint carries a compatible one,
            # else keep the template's zero init (pre-residual checkpoint,
            # or one written with different comm settings/topology) —
            # error feedback restarts cleanly rather than crashing the run
            state = template_state.replace(**state)
            if res_tmpl is not None:
                try:
                    r = self._mngr.restore(
                        step,
                        args=ocp.args.Composite(
                            residual=ocp.args.StandardRestore(res_tmpl)),
                    )
                    state = state.replace(comm_residual=r["residual"])
                except Exception as exc:  # noqa: BLE001 - best-effort state
                    log.warning(
                        "checkpoint has no compatible comm_residual — "
                        "error-feedback residual zero-initialised "
                        "(expected for pre-residual checkpoints or after "
                        "changing --grad_comm/topology)",
                        {"step": step, "reason": f"{type(exc).__name__}"},
                    )
        cfg = restored["config"]
        self._warn_rng_stream(cfg)
        log.info("checkpoint restored", {"step": step})
        return state, cfg

    def close(self) -> None:
        self._mngr.close()
