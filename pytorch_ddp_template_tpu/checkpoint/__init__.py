"""Checkpoint save/restore (orbax-backed, multi-host-correct)."""

from .manager import CheckpointManager

__all__ = ["CheckpointManager"]
